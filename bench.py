"""Headline benchmark: `pio train` compute kernel on the flagship template.

Measures ALS matrix-factorization training wall-clock at MovieLens-100K
scale (943 users × 1682 items × 100k ratings, rank 64, 10 sweeps) on the
default JAX device — the TPU under the driver. This is the north-star metric
from BASELINE.md: the reference's `pio train` on the Recommendation template
delegates to Spark MLlib ALS; the reference publishes no numbers, so the
baseline is self-generated (BASELINE.md "to be measured").

Baseline: the same solver on this host's CPU (JAX CPU backend, warm cache)
measured at 3.18 s with the fused single-dispatch training loop — our
stand-in for the single-box Spark driver the reference CI validates against
(tests/before_script.travis.sh:25-28; Spark 1.4 itself cannot run in this
offline image). ``vs_baseline`` > 1 means the TPU path is faster than that
CPU reference.

Prints exactly ONE JSON line on stdout.
"""

import json
import sys
import time

import numpy as np

#: CPU-JAX warm wall-clock for the identical workload on this image's host
#: (measured via `python bench.py --cpu`); the Spark-MLlib single-box number
#: this proxies is historically far slower, so this is a conservative bar.
CPU_BASELINE_S = 3.18

N_USERS, N_ITEMS, NNZ = 943, 1682, 100_000
RANK, ITERATIONS, L2 = 64, 10, 0.1


def make_dataset():
    rng = np.random.default_rng(7)
    users = rng.integers(0, N_USERS, NNZ)
    pop = rng.zipf(1.3, NNZ * 3) - 1
    items = pop[pop < N_ITEMS][:NNZ].astype(np.int64)
    users = users[: len(items)]
    ratings = rng.integers(1, 6, len(items)).astype(np.float32)
    return users, items, ratings


def run(platform_cpu: bool = False) -> None:
    if platform_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    from incubator_predictionio_tpu.ops import als_train, rmse

    users, items, ratings = make_dataset()

    def train():
        state, _ = als_train(
            users, items, ratings, N_USERS, N_ITEMS,
            rank=RANK, iterations=ITERATIONS, l2=L2, seed=0,
        )
        jax.block_until_ready(state.user_factors)
        return state

    t0 = time.perf_counter()
    state = train()
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    state = train()
    warm_s = time.perf_counter() - t0

    fit = rmse(state, users, items, ratings)
    print(
        f"device={jax.devices()[0]} compile+first={compile_s:.2f}s "
        f"warm={warm_s:.3f}s train_rmse={fit:.3f}",
        file=sys.stderr,
    )
    print(json.dumps({
        "metric": "als_ml100k_train_wall_s",
        "value": round(warm_s, 3),
        "unit": "s",
        "vs_baseline": round(CPU_BASELINE_S / warm_s, 2),
    }))


if __name__ == "__main__":
    run(platform_cpu="--cpu" in sys.argv)
