"""Headline benchmark: the north-star metric at MovieLens-20M scale.

BASELINE.json's north star is `pio train` wall-clock + deployed query
latency on the Recommendation template at ML-20M scale (≈138k users ×
27k items, 20M ratings, rank 128) — the reference delegates training to
Spark MLlib ALS and serves queries from a driver-local factor map
(CreateServer.scala:498-650). This bench runs the full TPU-native path:

1. SEED    — 20M synthetic rating events written through the native
             columnar bulk import (eventlog.cc pio_evlog_append_interactions)
2. INGEST  — `scan_interactions` streams them back as columnar COO + id
             tables, fully in C++ (the PEvents/HBase-scan role)
3. PREP    — degree-bucketed padded rows (ops/sparse.py, the native
             csr_builder)
4. TRAIN   — fused single-dispatch ALS (ops/als.py), compile + warm timing;
             MFU from the analytic FLOP count over the warm wall-clock
5. SERVE   — the real PredictionServer (HTTP + micro-batcher): sequential
             p50 and 128-async-client concurrent QPS on the device
             serving path

Prints exactly ONE JSON line on stdout: the headline metric
(`als_ml20m_train_wall_s`, vs the measured single-core CPU baseline) plus
the sub-metrics as extra keys (ingest/seed/prep walls, mfu, serving p50 /
QPS) so the driver's parsed record carries the whole story.

`--cpu` reruns the train stage on the host CPU backend to (re)measure the
baseline constant. `PIO_BENCH_NNZ` shrinks the dataset for smoke runs.
"""

import json
import os
import sys
import time

import numpy as np

# ---------------------------------------------------------------------------
# Workload: synthetic ML-20M shape (ratings.csv of MovieLens-20M has
# 138,493 users, 26,744 movies, 20,000,263 ratings in 0.5..5.0 steps)
# ---------------------------------------------------------------------------
N_USERS = int(os.environ.get("PIO_BENCH_USERS", 138_493))
N_ITEMS = int(os.environ.get("PIO_BENCH_ITEMS", 26_744))
NNZ = int(os.environ.get("PIO_BENCH_NNZ", 20_000_000))
RANK = int(os.environ.get("PIO_BENCH_RANK", 128))
ITERATIONS = int(os.environ.get("PIO_BENCH_SWEEPS", 10))
L2 = 0.1

#: Measured on this image's host CPU (JAX CPU backend, warm compile cache)
#: via `python bench.py --cpu` — the stand-in for the reference's
#: single-box Spark-MLlib driver (Spark 1.4 cannot run here; historically
#: it is far slower than a native CPU solver, so this bar is conservative).
#: Value = warm fused-train wall-clock at the full ML-20M shape above with
#: the same CG solver (measured 2026-07-29).
CPU_BASELINE_TRAIN_S = float(os.environ.get("PIO_BENCH_CPU_BASELINE", 571.1))

#: TPU v5e peak: 197 TFLOP/s bf16 / ~98.5 TFLOP/s fp32 on the MXU. The
#: solver's Gram assembly runs f32 at HIGHEST precision, so the honest
#: denominator is the fp32 figure.
PEAK_FLOPS_F32 = float(os.environ.get("PIO_BENCH_PEAK_FLOPS", 98.5e12))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def make_dataset(rng):
    """Power-law item popularity matching ML-20M's marginals: the real
    ratings.csv tops out at ≈67k ratings for the most-rated movie; an
    i^-0.55 profile over 27k items puts the top item at ≈90k of 20M —
    same order, and it exercises the heavy-row (split-segment) solver.
    Users get a milder i^-0.3 tail (ML-20M users are min-20, median ≈70,
    max ≈9.3k ratings)."""
    iw = (np.arange(N_ITEMS) + 1.0) ** -0.55
    items = rng.choice(N_ITEMS, NNZ, p=iw / iw.sum()).astype(np.int32)
    uw = (np.arange(N_USERS) + 1.0) ** -0.3
    users = rng.choice(N_USERS, NNZ, p=uw / uw.sum()).astype(np.int32)
    ratings = (rng.integers(1, 11, NNZ) * 0.5).astype(np.float32)
    return users, items, ratings


def als_flops_per_run() -> float:
    """Analytic FLOPs of the fused training run.

    Per half-sweep over `nnz` observations with rank K: the Gram batch is
    2·nnz·K² MACs = 4·nnz·K² FLOPs at HIGHEST precision (the f32 multi-pass
    costs ~3× a bf16 pass; counted at face value — conservative), the rhs
    2·nnz·K, and each of the `rows` CG solves ~iters·2·K² FLOPs (the
    batched-matvec Jacobi-PCG in ops/als.py — about the same count as a
    direct K³/3 Cholesky at K=128, iters=32). Both sides per sweep,
    ITERATIONS sweeps.
    """
    from incubator_predictionio_tpu.ops import als

    k = float(RANK)
    per_side_gram = 2.0 * NNZ * k * k * 2.0   # multiply+add
    per_side_rhs = 2.0 * NNZ * k
    if als._SOLVER == "cg":
        per_solve = als._CG_ITERS * 2.0 * k * k
    else:
        per_solve = k ** 3 / 3.0 + 2.0 * k * k
    solves = (N_USERS + N_ITEMS) * per_solve
    per_sweep = 2.0 * per_side_gram + 2.0 * per_side_rhs + solves
    return per_sweep * ITERATIONS


def seed_store(tmpdir, users, items, ratings):
    """Write NNZ rating events through the native columnar bulk import."""
    from incubator_predictionio_tpu.data.storage import StorageClientConfig
    from incubator_predictionio_tpu.data.storage import cpplog
    from incubator_predictionio_tpu.data.storage.base import (
        IdTable,
        Interactions,
    )

    cfg = StorageClientConfig(properties={"PATH": tmpdir})
    client = cpplog.StorageClient(cfg)
    events = cpplog.CppLogEvents(client, cfg, prefix="bench_")
    user_tab = IdTable.from_list([f"u{k}" for k in range(N_USERS)])
    item_tab = IdTable.from_list([f"i{k}" for k in range(N_ITEMS)])
    inter = Interactions(
        user_idx=users, item_idx=items, values=ratings,
        user_ids=user_tab, item_ids=item_tab,
    )
    t0 = time.perf_counter()
    n = events.import_interactions(
        inter, 1, event_name="rate", value_prop="rating",
        base_time=None)
    seed_s = time.perf_counter() - t0
    assert n == len(users)
    return events, client, seed_s


def run(platform_cpu: bool = False) -> None:
    import tempfile

    if platform_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import als

    rng = np.random.default_rng(7)
    log(f"dataset: {N_USERS}x{N_ITEMS}, nnz={NNZ}, rank={RANK}, "
        f"sweeps={ITERATIONS}")
    users, items, ratings = make_dataset(rng)

    with tempfile.TemporaryDirectory(prefix="pio_bench_") as tmpdir:
        # -- 1. SEED: native columnar bulk import --------------------------
        events, client, seed_s = seed_store(tmpdir, users, items, ratings)
        log(f"seed: {NNZ} events in {seed_s:.1f}s "
            f"({NNZ / seed_s / 1e6:.2f}M ev/s)")

        # -- 2. INGEST: columnar scan back out of the event store ----------
        t0 = time.perf_counter()
        inter = events.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating")
        ingest_s = time.perf_counter() - t0
        assert len(inter) == NNZ, len(inter)
        log(f"ingest scan: {ingest_s:.1f}s ({NNZ / ingest_s / 1e6:.2f}M ev/s)")
        client.close()

    # -- 3. PREP: degree-bucketed padded rows ------------------------------
    from incubator_predictionio_tpu.ops.sparse import (
        build_padded_rows,
        split_heavy,
    )

    # dims come from the scan's interned id tables (dense, first-seen order)
    n_users, n_items = len(inter.user_ids), len(inter.item_ids)
    t0 = time.perf_counter()
    u_light, u_heavy = split_heavy(build_padded_rows(
        inter.user_idx, inter.item_idx, inter.values, n_users))
    i_light, i_heavy = split_heavy(build_padded_rows(
        inter.item_idx, inter.user_idx, inter.values, n_items))
    prep_s = time.perf_counter() - t0
    log(f"prep (bucketed padded rows): {prep_s:.1f}s "
        f"(users={n_users}, items={n_items})")

    # -- 4. TRAIN: fused single-dispatch ALS -------------------------------
    u_tree, i_tree = als._buckets_tree(u_light), als._buckets_tree(i_light)
    u_hv, i_hv = als._heavy_tree(u_heavy), als._heavy_tree(i_heavy)

    def train(state0):
        out = als._als_run_fused(
            state0, u_tree, i_tree, L2, 0.0, ITERATIONS, True,
            jnp.float32, jax.lax.Precision.HIGHEST, implicit=False,
            user_heavy=u_hv, item_heavy=i_hv)
        # sync via a dependent 1-element device fetch: on the tunneled
        # platform jax.block_until_ready returns before execution finishes
        # (verified empirically), which silently turns the timer into a
        # dispatch-latency measurement
        np.asarray(out.user_factors[0:1, 0:1])
        np.asarray(out.item_factors[0:1, 0:1])
        return out

    # persistent compile cache: a FRESH directory so the first compile is
    # honestly cold (and writes the entry); clearing the in-memory
    # executable cache then forces a re-trace that must hit the persistent
    # entry — the compile cost every pio process after the first pays.
    # Both compile numbers subtract the warm execution time (each timed
    # call runs the full training once), so they are pure compile cost.
    from incubator_predictionio_tpu.utils import compile_cache

    import atexit
    import shutil

    xla_cache_dir = tempfile.mkdtemp(prefix="pio_bench_xla_")
    atexit.register(shutil.rmtree, xla_cache_dir, True)
    compile_cache.enable(xla_cache_dir)

    t0 = time.perf_counter()
    state = train(als.als_init(jax.random.key(0), n_users, n_items, RANK))
    first_call_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    state = train(als.als_init(jax.random.key(0), n_users, n_items, RANK))
    train_s = time.perf_counter() - t0
    compile_s = max(first_call_s - train_s, 0.0)
    cache_engaged = bool(os.listdir(xla_cache_dir))
    compile_warm_cache_s = None
    if cache_engaged:
        jax.clear_caches()  # drop in-memory executables; cache dir stays
        t0 = time.perf_counter()
        state = train(als.als_init(jax.random.key(0), n_users, n_items,
                                   RANK))
        compile_warm_cache_s = round(
            max(time.perf_counter() - t0 - train_s, 0.0), 1)
        log(f"compile: cold={compile_s:.1f}s warm-persistent-cache="
            f"{compile_warm_cache_s}s (dir {xla_cache_dir})")
    else:
        # PIO_COMPILE_CACHE=off in the environment, or the cache was
        # rejected: do NOT publish a second cold compile as "warm"
        log("compile: persistent cache did not engage "
            "(PIO_COMPILE_CACHE=off or cache rejected); "
            f"cold={compile_s:.1f}s")
    fit = als.rmse(state, inter.user_idx, inter.item_idx, inter.values)
    flops = als_flops_per_run()
    mfu = flops / train_s / PEAK_FLOPS_F32
    log(f"device={jax.devices()[0]} compile={compile_s:.1f}s "
        f"warm={train_s:.2f}s rmse={fit:.3f} "
        f"flops={flops:.3e} mfu={mfu:.3f}")

    if platform_cpu:
        log(f"CPU baseline measured: warm train = {train_s:.1f}s "
            "(update CPU_BASELINE_TRAIN_S)")
        print(json.dumps({
            "metric": "als_ml20m_train_wall_s_cpu",
            "value": round(train_s, 2),
            "unit": "s",
            "vs_baseline": 1.0,
        }))
        return

    # -- 5. SERVE: the real PredictionServer (HTTP + micro-batcher) --------
    serve = bench_serving(state, inter)

    print(json.dumps({
        "metric": "als_ml20m_train_wall_s",
        "value": round(train_s, 3),
        "unit": "s",
        "vs_baseline": round(CPU_BASELINE_TRAIN_S / train_s, 1),
        "train_rmse": round(float(fit), 3),
        "mfu": round(mfu, 4),
        "compile_s_cold": round(compile_s, 1),
        "compile_s_warm_cache": compile_warm_cache_s,
        "seed_wall_s": round(seed_s, 1),
        "ingest_wall_s": round(ingest_s, 1),
        "prep_wall_s": round(prep_s, 1),
        "serve_p50_ms": serve["p50_ms"],
        "serve_p99_ms": serve["p99_ms"],
        "serve_qps": serve["qps_sequential"],
        "serve_qps_concurrent": serve["qps_concurrent"],
        "serve_max_batch": serve["max_batch"],
        "nnz": NNZ,
        "rank": RANK,
        "sweeps": ITERATIONS,
    }))


def bench_serving(state, inter):
    """Deploy the trained factors behind the real PredictionServer and
    measure the device serving path over HTTP: sequential p50/p99/QPS and
    128-async-client concurrent QPS (the micro-batcher fuses those into
    batch_predict dispatches — CreateServer.scala:523's 'TODO')."""
    import threading
    import urllib.request

    from incubator_predictionio_tpu.data.bimap import BiMap
    from incubator_predictionio_tpu.data.storage import (
        EngineInstance,
        Storage,
    )
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        ALSModel,
        RecommendationServing,
    )
    from incubator_predictionio_tpu.servers.prediction_server import (
        PredictionServer,
        ServerConfig,
    )
    from incubator_predictionio_tpu.utils.times import now_utc

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    model = ALSModel(
        user_factors=state.user_factors,   # device-resident
        item_factors=state.item_factors,
        user_bimap=BiMap({u: i for i, u in enumerate(inter.user_ids)}),
        item_bimap=BiMap({t: i for i, t in enumerate(inter.item_ids)}),
        item_years={}, item_categories={},
    )
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=RANK))
    now = now_utc()
    instance = EngineInstance(
        id="bench", status="COMPLETED", start_time=now, end_time=now,
        engine_id="bench", engine_version="1", engine_variant="bench",
        engine_factory="bench")
    server = PredictionServer.__new__(PredictionServer)
    # direct state injection: the bench measures the serving path, not the
    # checkpoint restore (engine=None is never touched by /queries.json)
    server.engine = None
    server.config = ServerConfig(ip="127.0.0.1", port=0)
    from incubator_predictionio_tpu.servers.plugins import PluginContext
    from incubator_predictionio_tpu.servers.prediction_server import (
        _AsyncPoster,
        _MicroBatcher,
    )
    from incubator_predictionio_tpu.utils.http import HttpServer
    from incubator_predictionio_tpu.workflow.workflow import (
        make_runtime_context,
    )
    server.plugin_context = PluginContext()
    server.ctx = make_runtime_context(None)
    server._lock = threading.Lock()
    server.engine_instance = instance
    server.engine_params = None
    server.algorithms = [algo]
    server.serving = RecommendationServing()
    server.models = [model]
    server.start_time = now
    server.request_count = 0
    server.avg_serving_sec = 0.0
    server.last_serving_sec = 0.0
    server.max_batch_served = 0
    server._conf_server_key = None
    server.http = HttpServer(server._build_router(), "127.0.0.1", 0)
    server._batcher = _MicroBatcher(server._handle_batch,
                                    server.config.micro_batch)
    server._feedback_poster = _AsyncPoster("feedback")
    server._log_poster = _AsyncPoster("log", workers=1)
    port = server.http.start_background()

    def query_once(user: str) -> None:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json",
            data=json.dumps({"user": user, "num": 10}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            resp.read()

    # warm the serving dispatch (compiles the scoring kernels)
    query_once("u1")
    query_once("u2")

    # sequential latency distribution
    n_seq = int(os.environ.get("PIO_BENCH_SERVE_N", 200))
    lat = []
    t_seq0 = time.perf_counter()
    for i in range(n_seq):
        t0 = time.perf_counter()
        query_once(f"u{i % N_USERS}")
        lat.append(time.perf_counter() - t0)
    seq_wall = time.perf_counter() - t_seq0
    lat_ms = np.sort(np.asarray(lat)) * 1e3
    p50 = float(lat_ms[int(0.50 * (n_seq - 1))])
    p99 = float(lat_ms[int(0.99 * (n_seq - 1))])
    qps_seq = n_seq / seq_wall

    # concurrent: async keep-alive clients (thread-per-client load
    # generators are GIL-bound ~400 QPS and under-measure the server; 128
    # async connections measured best — 647 vs 426 at 64 and 281 at 256);
    # the micro-batcher fuses the in-flight queries
    n_clients = int(os.environ.get("PIO_BENCH_SERVE_CLIENTS", 128))
    per_client = int(os.environ.get("PIO_BENCH_SERVE_CONC", 25))
    # warm the batched kernel shapes (powers of two up to the PADDED batch
    # cap — batch_score_top_k pads B to the next power of two, so a
    # non-power-of-two micro_batch still lands on 1 << ceil(log2(cap))) so
    # the concurrent window measures serving, not XLA compiles
    from incubator_predictionio_tpu.models.recommendation.engine import Query
    cap = 1 << max(server.config.micro_batch - 1, 0).bit_length()
    size = 1
    while size <= cap:
        algo.batch_predict(model, [
            (i, Query(user=f"u{i % N_USERS}", num=10)) for i in range(size)])
        size *= 2

    import asyncio

    async def _load() -> float:
        async def one(cid: int) -> None:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            try:
                for j in range(per_client):
                    body = json.dumps({
                        "user": f"u{(cid * per_client + j) % N_USERS}",
                        "num": 10}).encode()
                    writer.write(
                        b"POST /queries.json HTTP/1.1\r\nHost: bench\r\n"
                        b"Content-Type: application/json\r\n"
                        + f"Content-Length: {len(body)}\r\n\r\n".encode()
                        + body)
                    await writer.drain()
                    head = await reader.readuntil(b"\r\n\r\n")
                    status_line = head.split(b"\r\n", 1)[0]
                    if b" 200 " not in status_line:
                        raise RuntimeError(
                            f"concurrent query failed: {status_line!r}")
                    clen = int(next(
                        line.split(b":")[1]
                        for line in head.split(b"\r\n")
                        if line.lower().startswith(b"content-length")))
                    await reader.readexactly(clen)
            finally:
                writer.close()
        t0 = time.perf_counter()
        # per-phase deadline replacing the old per-request urlopen timeout
        await asyncio.wait_for(
            asyncio.gather(*[one(c) for c in range(n_clients)]),
            timeout=max(120.0, 0.5 * n_clients * per_client))
        return time.perf_counter() - t0

    conc_wall = asyncio.run(_load())
    qps_conc = n_clients * per_client / conc_wall
    max_batch = server.max_batch_served
    log(f"serving: p50={p50:.2f}ms p99={p99:.2f}ms seq={qps_seq:.0f}qps "
        f"conc{n_clients}={qps_conc:.0f}qps max_batch={max_batch}")
    server.stop()
    Storage.reset()
    return {
        "p50_ms": round(p50, 2),
        "p99_ms": round(p99, 2),
        "qps_sequential": round(qps_seq, 1),
        "qps_concurrent": round(qps_conc, 1),
        "max_batch": int(max_batch),
    }


if __name__ == "__main__":
    run(platform_cpu="--cpu" in sys.argv)
