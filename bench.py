"""Headline benchmark: the north-star metric at MovieLens-20M scale.

BASELINE.json's north star is `pio train` wall-clock + deployed query
latency on the Recommendation template at ML-20M scale (≈138k users ×
27k items, 20M ratings, rank 128) — the reference delegates training to
Spark MLlib ALS and serves queries from a driver-local factor map
(CreateServer.scala:498-650). This bench runs the full TPU-native path:

1. SEED    — 20M synthetic rating events written through the native
             columnar bulk import (eventlog.cc pio_evlog_append_interactions)
2. INGEST  — `scan_interactions` streams them back as columnar COO + id
             tables, fully in C++ (the PEvents/HBase-scan role)
3. PREP    — degree-bucketed padded rows (ops/sparse.py, the native
             csr_builder)
4. TRAIN   — fused single-dispatch ALS (ops/als.py), compile + warm timing;
             MFU from the analytic FLOP count over the warm wall-clock
5. SERVE   — the real PredictionServer (HTTP + micro-batcher): sequential
             p50 and 128-async-client concurrent QPS on the device
             serving path

Prints exactly ONE JSON line on stdout: the headline metric
(`als_ml20m_train_wall_s`, vs the measured single-core CPU baseline) plus
the sub-metrics as extra keys (ingest/seed/prep walls, mfu, serving p50 /
QPS) so the driver's parsed record carries the whole story.

Process architecture (resilience against the single-tenant chip lease —
a stale lease blocks PJRT client construction *forever*, and a blocked
dial can never be retried in-process because the backend-init lock is
held by the blocked thread):

- the PARENT never dials the accelerator. It pins its own jax to CPU,
  runs every host-side stage (seed, ingest scan, prep, REST-ingest
  bench), and supervises a CHILD process that does all TPU work.
- the CHILD dials the chip as its first act and touches a claim file the
  instant the dial succeeds; the parent recycles children that fail to
  claim within an exponentially growing window (a *fresh* process gets a
  fresh dial — the only true retry) until `PIO_BENCH_ACCEL_WAIT_S` runs
  out. Children are stopped with SIGTERM-and-wait, never SIGKILL
  (SIGKILL mid-claim is what wedges the lease in the first place).
- if no child ever lands, the parent emits a **degraded** record —
  host-stage walls at full shape plus train quality measured on the
  pinned all-f32 CPU schedule at a reduced `PIO_BENCH_DEGRADED_NNZ`
  shape, `"degraded": true`, exit 0 — so the driver always gets a
  parsed record, never a null round.

`--cpu` reruns the train stage on the host CPU backend to (re)measure the
baseline constant. `PIO_BENCH_NNZ` shrinks the dataset for smoke runs.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# ---------------------------------------------------------------------------
# Workload: synthetic ML-20M shape (ratings.csv of MovieLens-20M has
# 138,493 users, 26,744 movies, 20,000,263 ratings in 0.5..5.0 steps)
# ---------------------------------------------------------------------------
N_USERS = int(os.environ.get("PIO_BENCH_USERS", 138_493))
N_ITEMS = int(os.environ.get("PIO_BENCH_ITEMS", 26_744))
NNZ = int(os.environ.get("PIO_BENCH_NNZ", 20_000_000))
RANK = int(os.environ.get("PIO_BENCH_RANK", 128))
ITERATIONS = int(os.environ.get("PIO_BENCH_SWEEPS", 10))
#: precision schedule (ops/als.py _mixed_run): bf16 gathers + bf16 Gram
#: batches + single-pass MXU matmuls for the first BF16_SWEEPS sweeps, f32
#: HIGHEST for the rest. The bench default is ALL-bf16: at this exact
#: workload (planted rank-16 + noise 0.35, ML-20M marginals) the all-bf16
#: run measures RMSE parity with all-f32 to 4 decimals on BOTH fit
#: (0.5415 vs 0.5414) and heldout (0.5960 vs 0.5962) at 3.1x the speed
#: (scripts/als_profile.py, v5e). The engine default stays mixed
#: (iterations-2 bf16 + 2 polish) — arbitrary user data may sit far from
#: its noise floor where f32 polish matters; parity is additionally
#: guarded by tests/test_als.py planted-recovery.
BF16_SWEEPS = int(os.environ.get("PIO_BENCH_BF16_SWEEPS", ITERATIONS))
#: ridge weight (ALS-WR λ·nnz scaling). 0.03 is the measured optimum for
#: the planted workload (round-5 sweep at 2M/5M-nnz bench marginals:
#: heldout 0.675/0.494 at λ=0.1 → 0.611/0.472 at 0.03, overfit below) —
#: λ=0.1 was costing ~0.1 heldout RMSE of pure over-regularization.
#: See BASELINE.md "planted-quality gap" for the full decomposition.
L2 = float(os.environ.get("PIO_BENCH_L2", "0.03"))

#: Measured on this image's host CPU (JAX CPU backend, warm compile cache)
#: via `python bench.py --cpu` — the stand-in for the reference's
#: single-box Spark-MLlib driver (Spark 1.4 cannot run here; historically
#: it is far slower than a native CPU solver, so this bar is conservative).
#: Value = warm fused-train wall-clock at the full ML-20M shape above with
#: the same CG solver (measured 2026-07-29).
CPU_BASELINE_TRAIN_S = float(os.environ.get("PIO_BENCH_CPU_BASELINE", 467.7))

#: TPU v5e peak: 197 TFLOP/s bf16 / ~98.5 TFLOP/s fp32 on the MXU. The
#: JSON reports BOTH conventions: `mfu` against the fp32 peak (the series
#: every prior round reported — comparable across rounds) and
#: `mfu_bf16_peak` against the bf16 peak, which is the honest utilization
#: figure when the schedule runs all-bf16 sweeps.
PEAK_FLOPS_F32 = float(os.environ.get("PIO_BENCH_PEAK_FLOPS", 98.5e12))
PEAK_FLOPS_BF16 = float(os.environ.get("PIO_BENCH_PEAK_FLOPS_BF16", 197e12))

#: total budget for landing the TPU child (dial + respawn backoff). The
#: round-4 wedge outlasted a flat 1200 s retry window; the default here is
#: longer AND the wait overlaps the parent's host-side stages, so the
#: worst-case bench wall is max(host stages, wait) + child run, not their
#: sum.
ACCEL_WAIT_S = float(os.environ.get("PIO_BENCH_ACCEL_WAIT_S", "1800"))
#: GLOBAL wall budget for the whole bench process. The driver kills the
#: bench at its own timeout (observed: 870 s, rc=124) — BENCH_r05 lost an
#: already-computed degraded record because the claim-retry loop's third
#: recycle window ran past it. The bench therefore commits to emitting
#: its one JSON record (degraded if need be) BEFORE this deadline: the
#: claim wait is capped at deadline minus an emit margin, and the
#: orchestrator abandons a still-dialing supervisor rather than die
#: recordless. Raise it on drivers with a longer leash.
BENCH_DEADLINE_S = float(os.environ.get("PIO_BENCH_DEADLINE_S", "840"))
#: seconds reserved before the deadline for wrapping up: reading the
#: fragment, joining the degraded thread, serializing the record
EMIT_MARGIN_S = float(os.environ.get("PIO_BENCH_EMIT_MARGIN_S", "30"))
#: how long the degraded fallback (prep + CPU train + quality + serving
#: at DEGRADED_NNZ) is budgeted to take — the orchestrator starts the
#: fallback early enough that it can finish before the deadline, even if
#: that overlaps the accelerator wait from the first second
DEGRADED_BUDGET_S = float(
    os.environ.get("PIO_BENCH_DEGRADED_BUDGET_S", "600"))
#: if no child has claimed the chip this far into the wait, the parent
#: starts computing the degraded record in parallel (a normal dial lands
#: in seconds; by 300 s it is almost certainly a wedge) so the wait and
#: the fallback work overlap instead of adding
DEGRADED_START_S = float(os.environ.get("PIO_BENCH_DEGRADED_START_S", "300"))
#: once a child HAS claimed the chip, how long its full TPU run may take
TPU_RUN_TIMEOUT_S = float(os.environ.get("PIO_BENCH_TPU_RUN_S", "1800"))
#: degraded-mode train shape (events subsampled from the full dataset)
DEGRADED_NNZ = int(os.environ.get("PIO_BENCH_DEGRADED_NNZ", 2_000_000))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


_BENCH_TRACE_ID = None


def _bench_trace_id() -> str:
    """One trace ID per bench process (``bench-<8 hex>``): every HTTP
    request the load generators send carries it, so the servers' span
    logs attribute bench traffic to this run (the bench→servers hop of
    the cross-process trace contract)."""
    global _BENCH_TRACE_ID
    if _BENCH_TRACE_ID is None:
        import secrets

        _BENCH_TRACE_ID = f"bench-{secrets.token_hex(4)}"
    return _BENCH_TRACE_ID


def bench_env() -> dict:
    """Provenance block for the record: enough to answer "what machine,
    what software, what code" about any row of the trajectory without
    archaeology. Every field is best-effort — a missing git binary or
    an uninitialized jax must never cost the round its record."""
    import platform
    import socket

    env = {
        "backend": os.environ.get("JAX_PLATFORMS") or "default",
        "device_count": None,
        "jax_version": None,
        "git_sha": None,
        "hostname": None,
        "python": platform.python_version(),
        "wall_ts": None,
    }
    try:
        env["hostname"] = socket.gethostname()
    except OSError:
        pass
    env["wall_ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    mod = sys.modules.get("jax")
    if mod is not None:
        env["jax_version"] = getattr(mod, "__version__", None)
        try:
            env["device_count"] = len(mod.devices())
            # the LIVE backend beats the env var: the TPU child never
            # sets JAX_PLATFORMS, it dials the chip
            env["backend"] = mod.default_backend()
        except Exception:  # backend not initialized / unavailable
            pass
    else:
        try:
            from importlib.metadata import version

            env["jax_version"] = version("jax")
        except Exception:
            pass
    try:
        env["git_sha"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except Exception:
        pass
    return env


#: planted ground truth: ratings = 3.5 + U·Vᵀ + N(0, NOISE_SIGMA) with a
#: rank-PLANT_RANK U, V. The solver (rank 128 ⊇ 16) can recover the
#: structure, so heldout RMSE has a KNOWN floor (= NOISE_SIGMA) and
#: ranking quality a known ceiling — the r3 verdict's "model quality is
#: asserted, not proven" fix. Marginals stay the r3 power-law (identical
#: bucket shapes → timing comparability across rounds).
PLANT_RANK = int(os.environ.get("PIO_BENCH_PLANT_RANK", 16))
NOISE_SIGMA = float(os.environ.get("PIO_BENCH_NOISE_SIGMA", 0.35))
N_HOLDOUT = int(os.environ.get("PIO_BENCH_HOLDOUT", 200_000))


def _sample_pairs(rng, n):
    """Power-law item popularity matching ML-20M's marginals: the real
    ratings.csv tops out at ≈67k ratings for the most-rated movie; an
    i^-0.55 profile over 27k items puts the top item at ≈90k of 20M —
    same order, and it exercises the heavy-row (split-segment) solver.
    Users get a milder i^-0.3 tail (ML-20M users are min-20, median ≈70,
    max ≈9.3k ratings)."""
    iw = (np.arange(N_ITEMS) + 1.0) ** -0.55
    items = rng.choice(N_ITEMS, n, p=iw / iw.sum()).astype(np.int32)
    uw = (np.arange(N_USERS) + 1.0) ** -0.3
    users = rng.choice(N_USERS, n, p=uw / uw.sum()).astype(np.int32)
    return users, items


def make_dataset(rng):
    """→ (users, items, ratings, heldout (u, i, r), true (U, V)). The
    heldout pairs are fresh draws from the same ground truth — never
    stored, never trained on. Deterministic for a given rng seed: the
    TPU child regenerates the identical dataset from seed 7 instead of
    shipping 240 MB of arrays across the process boundary."""
    u_true = rng.normal(0, 1.0 / np.sqrt(PLANT_RANK),
                        (N_USERS, PLANT_RANK)).astype(np.float32)
    v_true = rng.normal(0, 1.0, (N_ITEMS, PLANT_RANK)).astype(np.float32)

    def rate(users, items):
        signal = np.einsum("nk,nk->n", u_true[users], v_true[items])
        return (3.5 + signal
                + rng.normal(0, NOISE_SIGMA, len(users))).astype(np.float32)

    users, items = _sample_pairs(rng, NNZ)
    ho_u, ho_i = _sample_pairs(rng, N_HOLDOUT)
    return (users, items, rate(users, items),
            (ho_u, ho_i, rate(ho_u, ho_i)), (u_true, v_true))


def quality_metrics(state, inter, heldout, truth, rng):
    """Heldout RMSE vs the known noise floor + precision@10 against the
    ground-truth ranking (sampled users, device-scored).

    The trained factors live in the event-log scan's FIRST-SEEN id order
    (``inter.user_ids``/``inter.item_ids``), not the seed's original
    integer order — translate every ground-truth index through the
    interned id tables before touching the model, or the metrics score a
    permutation of the model (the exact bug this comment guards against:
    p@10 ≈ 10/N_ITEMS ≈ 0)."""
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import als

    ho_u, ho_i, ho_r = heldout
    u_true, v_true = truth
    # IdTable caches its id→index dict on first .index(); reuse it instead
    # of building a parallel lookup (the scan's tables serve the server too)
    u_tab, i_tab = inter.user_ids, inter.item_ids
    u_scan = np.asarray([
        u_tab.index(s) if s in u_tab else -1
        for s in (f"u{k}" for k in range(N_USERS))])
    i_scan = np.asarray([
        i_tab.index(s) if s in i_tab else -1
        for s in (f"i{k}" for k in range(N_ITEMS))])

    # heldout pairs whose user/item never appeared in training have no
    # factor row (possible at smoke-test NNZ); score only the rest
    mask = (u_scan[ho_u] >= 0) & (i_scan[ho_i] >= 0)
    heldout_rmse = als.rmse(
        state, u_scan[ho_u[mask]], i_scan[ho_i[mask]], ho_r[mask])

    # ranking quality over the trainable universe: items present in
    # training (nothing can recommend an item it never saw)
    present_items = np.flatnonzero(i_scan >= 0)
    probe_pool = np.flatnonzero(u_scan >= 0)
    n_probe = min(1000, len(probe_pool))
    probe = rng.choice(probe_pool, n_probe, replace=False)
    true_scores = u_true[probe] @ v_true[present_items].T   # [P, Ip] host
    true_top = np.argsort(-true_scores, axis=1)[:, :10]
    # gather present-item factors in original-item order BEFORE the matmul:
    # everything stays on device in [P, Ip] and dropped columns never score
    probe_factors = jnp.take(
        state.user_factors, jnp.asarray(u_scan[probe]), axis=0)
    present_factors = jnp.take(
        state.item_factors, jnp.asarray(i_scan[present_items]), axis=0)
    model_top = np.asarray(jax.lax.top_k(
        probe_factors @ present_factors.T, 10)[1])
    hits = np.mean([
        len(set(a.tolist()) & set(b.tolist())) / 10.0
        for a, b in zip(model_top, true_top)
    ])
    return float(heldout_rmse), float(hits)


def als_flops_per_run(bf16_sweeps: int = None) -> float:
    """Analytic FLOPs of the fused training run at the bench shape —
    delegates to ``ops.als.train_flops``, the ONE formula the live
    ``pio_mfu{phase="train"}`` gauge (obs/profile.py) also uses, so the
    offline and live MFU figures agree by construction."""
    from incubator_predictionio_tpu.ops import als

    if bf16_sweeps is None:
        bf16_sweeps = BF16_SWEEPS
    return als.train_flops(NNZ, N_USERS, N_ITEMS, RANK, ITERATIONS,
                           bf16_sweeps)


def seed_store(tmpdir, users, items, ratings):
    """Write NNZ rating events through the native columnar bulk import."""
    from incubator_predictionio_tpu.data.storage import StorageClientConfig
    from incubator_predictionio_tpu.data.storage import cpplog
    from incubator_predictionio_tpu.data.storage.base import (
        IdTable,
        Interactions,
    )

    cfg = StorageClientConfig(properties={"PATH": tmpdir})
    client = cpplog.StorageClient(cfg)
    events = cpplog.CppLogEvents(client, cfg, prefix="bench_")
    user_tab = IdTable.from_list([f"u{k}" for k in range(N_USERS)])
    item_tab = IdTable.from_list([f"i{k}" for k in range(N_ITEMS)])
    inter = Interactions(
        user_idx=users, item_idx=items, values=ratings,
        user_ids=user_tab, item_ids=item_tab,
    )
    t0 = time.perf_counter()
    n = events.import_interactions(
        inter, 1, event_name="rate", value_prop="rating",
        base_time=None)
    seed_s = time.perf_counter() - t0
    assert n == len(users)
    return events, client, seed_s


def scan_store(tmpdir):
    """Re-open the seeded store and stream the training projection back
    out (the warm `pio train` read path). → (inter, ingest_wall_s)."""
    from incubator_predictionio_tpu.data.storage import StorageClientConfig
    from incubator_predictionio_tpu.data.storage import cpplog

    cfg = StorageClientConfig(properties={"PATH": tmpdir})
    client = cpplog.StorageClient(cfg)
    events = cpplog.CppLogEvents(client, cfg, prefix="bench_")
    t0 = time.perf_counter()
    inter = events.scan_interactions(
        app_id=1, entity_type="user", target_entity_type="item",
        event_names=("rate",), value_prop="rating")
    ingest_s = time.perf_counter() - t0
    client.close()
    return inter, ingest_s


def prep_buckets(inter):
    """Degree-bucketed padded rows from the scanned projection."""
    from incubator_predictionio_tpu.ops.sparse import build_both_sides

    n_users, n_items = len(inter.user_ids), len(inter.item_ids)
    t0 = time.perf_counter()
    (u_light, u_heavy), (i_light, i_heavy) = build_both_sides(
        inter.user_idx, inter.item_idx, inter.values, n_users, n_items)
    prep_s = time.perf_counter() - t0
    return (u_light, u_heavy), (i_light, i_heavy), n_users, n_items, prep_s


def build_trees(buckets):
    """Device-resident bucket + heavy trees from prep_buckets output —
    built ONCE per child and shared by the kernel selector and the timed
    train (each build uploads the whole padded interaction set)."""
    from incubator_predictionio_tpu.ops import als

    (u_light, u_heavy), (i_light, i_heavy), n_users, n_items = buckets
    u_tree, i_tree = als._buckets_tree(u_light), als._buckets_tree(i_light)
    u_hv, i_hv = als._heavy_tree(u_heavy), als._heavy_tree(i_heavy)
    return u_tree, i_tree, u_hv, i_hv, n_users, n_items


def select_als_kernel(buckets, trees=None):
    """Measured on-chip choice for the fused Pallas ALS bucket solve.

    ``PIO_ALS_KERNEL=auto``'s Mosaic probe only proves the kernel
    COMPILES on this backend; it says nothing about speed, and a slow
    kernel engaged blind would burn the TPU child's run window. A short
    full-shape run each way — covering BOTH kernel programs (a bf16
    DEFAULT sweep and, when the main schedule has one, an f32 HIGHEST
    polish sweep) — warm-timed; the kernel must beat the XLA path
    outright (ties keep the battle-tested path). Any crash in the probe
    falls back to the XLA path instead of forfeiting the accelerator
    leg. → (use_kernel, rows_per_program, fragment fields recording the
    outcome)."""
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import als

    # the timed legs run under the production warm-start default, so the
    # gate must probe that exact kernel variant (warm adds the x0 operand)
    if not als._kernel_enabled(False, warm=als._CG_WARMSTART):
        # distinguish an operator override from backend inability so the
        # fragment's cross-round comparison stays meaningful
        forced_off = als._ALS_KERNEL == "off" or als._SOLVER != "cg"
        return False, 1, {"als_kernel": "disabled" if forced_off
                          else "unavailable"}
    u_tree, i_tree, u_hv, i_hv, n_users, n_items = (
        trees if trees is not None else build_trees(buckets))
    # mirror the main schedule's leg structure: probe the polish program
    # too when the real run will use it
    polish = BF16_SWEEPS < ITERATIONS
    its = 2 if polish else 1
    # (use_kernel, rows-per-program): both kernel layouts compete with
    # the XLA path, so the bench self-selects the best and records every
    # timing — the on-chip layout comparison ships in the fragment
    legs = [(False, 1), (True, 1), (True, 8)]
    times = {}
    for uk, rows in legs:
        def train():
            out = als._mixed_run(
                als.als_init(jax.random.key(0), n_users, n_items, RANK),
                u_tree, i_tree, L2, its, 1, True,
                jnp.float32, jax.lax.Precision.HIGHEST,
                user_heavy=u_hv, item_heavy=i_hv, use_kernel=uk,
                kernel_rows=rows)
            np.asarray(out.user_factors[0:1, 0:1])
            np.asarray(out.item_factors[0:1, 0:1])
        try:
            train()  # compile + first run
            best = None
            for _ in range(2):
                # best-of-2: a single short sweep on the tunneled
                # platform carries dispatch jitter comparable to the 3%
                # decision threshold
                t0 = time.perf_counter()
                train()
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            times[(uk, rows)] = best
        except Exception as e:  # full-shape-only kernel failure
            if not uk:
                raise  # the XLA path must work; nothing to fall back to
            log(f"ALS kernel probe (rows={rows}) crashed at full shape "
                f"({e!r}); leg skipped")
    xla = times[(False, 1)]
    kernel_times = {rows: t for (uk, rows), t in times.items() if uk}
    frag = {"als_kernel_sweep_xla_s": round(xla, 3)}
    for rows, t in kernel_times.items():
        frag[f"als_kernel_sweep_pallas_r{rows}_s"] = round(t, 3)
    if not kernel_times:
        frag["als_kernel"] = "probe_failed"
        log("ALS kernel probe: every kernel leg crashed; XLA path serves")
        return False, 1, frag
    best_rows = min(kernel_times, key=kernel_times.get)
    best = kernel_times[best_rows]
    choice = bool(best < 0.97 * xla)
    log(f"ALS kernel probe ({its} sweep(s), full shape): xla={xla:.3f}s "
        + " ".join(f"pallas_r{r}={t:.3f}s"
                   for r, t in sorted(kernel_times.items()))
        + f" -> {'pallas' if choice else 'xla'}"
        + (f" rows={best_rows}" if choice else ""))
    frag["als_kernel"] = "on" if choice else "off"
    frag["als_kernel_rows"] = best_rows
    return choice, best_rows, frag


def measure_train(buckets, bf16_sweeps, cache_probe=True, use_kernel=None,
                  trees=None, kernel_rows=None):
    """Compile-cold / warm / warm-persistent-cache timing of the fused
    training run. → (state, dict of timing keys)."""
    import atexit
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import als

    u_tree, i_tree, u_hv, i_hv, n_users, n_items = (
        trees if trees is not None else build_trees(buckets))

    def train(state0):
        out = als._mixed_run(
            state0, u_tree, i_tree, L2, ITERATIONS, bf16_sweeps, True,
            jnp.float32, jax.lax.Precision.HIGHEST,
            user_heavy=u_hv, item_heavy=i_hv, use_kernel=use_kernel,
            kernel_rows=kernel_rows)
        # sync via a dependent 1-element device fetch: on the tunneled
        # platform jax.block_until_ready returns before execution finishes
        # (verified empirically), which silently turns the timer into a
        # dispatch-latency measurement
        np.asarray(out.user_factors[0:1, 0:1])
        np.asarray(out.item_factors[0:1, 0:1])
        return out

    # persistent compile cache: a FRESH directory so the first compile is
    # honestly cold (and writes the entry); clearing the in-memory
    # executable cache then forces a re-trace that must hit the persistent
    # entry — the compile cost every pio process after the first pays.
    # Both compile numbers subtract the warm execution time (each timed
    # call runs the full training once), so they are pure compile cost.
    from incubator_predictionio_tpu.utils import compile_cache

    xla_cache_dir = tempfile.mkdtemp(prefix="pio_bench_xla_")
    atexit.register(shutil.rmtree, xla_cache_dir, True)
    compile_cache.enable(xla_cache_dir)

    # both runs under PIO_PROFILE=1: the compile call also compiles the
    # profiler's nnz mask-sum reductions, so the TIMED warm run's outer
    # wall carries only their cached execution — keeping the live
    # pio_mfu{phase=train} gauge (whose dt excludes the FLOP-count work
    # entirely, obs/profile.py flops_fn) within the 10% agreement band
    # the test_bench_e2e cross-check asserts. The timed run's gauge
    # value overwrites the compile run's.
    from incubator_predictionio_tpu.obs import metrics as obs_metrics

    def device_train_booked():
        """(seconds, dispatches) the profiler attributed to the training
        op so far — als_train (XLA assembly) or als_fused (Pallas
        kernel path), whichever this run routes through."""
        secs = dispatches = 0.0
        m = obs_metrics.REGISTRY.get("pio_device_seconds")
        d = obs_metrics.REGISTRY.get("pio_device_dispatches_total")
        for op in ("als_train", "als_fused"):
            if m is not None:
                secs += m.labels(op=op).value
            if d is not None:
                dispatches += d.labels(op=op).value
        return secs, dispatches

    prev_profile = os.environ.get("PIO_PROFILE")
    os.environ["PIO_PROFILE"] = "1"
    try:
        t0 = time.perf_counter()
        state = train(als.als_init(jax.random.key(0), n_users, n_items,
                                   RANK))
        first_call_s = time.perf_counter() - t0
        # per-op device-seconds delta over the TIMED run only (the
        # compile run books its own attribution)
        secs0, disp0 = device_train_booked()
        t0 = time.perf_counter()
        state = train(als.als_init(jax.random.key(0), n_users, n_items,
                                   RANK))
        train_s = time.perf_counter() - t0
        secs1, disp1 = device_train_booked()
    finally:
        if prev_profile is None:
            os.environ.pop("PIO_PROFILE", None)
        else:
            os.environ["PIO_PROFILE"] = prev_profile
    mfu_gauge = obs_metrics.REGISTRY.get("pio_mfu")
    obs_mfu_train = (mfu_gauge.labels(phase="train").value
                     if mfu_gauge is not None else 0.0)
    compile_s = max(first_call_s - train_s, 0.0)
    compile_warm_cache_s = None
    if cache_probe and os.listdir(xla_cache_dir):
        jax.clear_caches()  # drop in-memory executables; cache dir stays
        t0 = time.perf_counter()
        state = train(als.als_init(jax.random.key(0), n_users, n_items,
                                   RANK))
        compile_warm_cache_s = round(
            max(time.perf_counter() - t0 - train_s, 0.0), 1)
        log(f"compile: cold={compile_s:.1f}s warm-persistent-cache="
            f"{compile_warm_cache_s}s (dir {xla_cache_dir})")
    elif cache_probe:
        # PIO_COMPILE_CACHE=off in the environment, or the cache was
        # rejected: do NOT publish a second cold compile as "warm"
        log("compile: persistent cache did not engage "
            "(PIO_COMPILE_CACHE=off or cache rejected); "
            f"cold={compile_s:.1f}s")
    return state, {
        "train_s": train_s,
        "compile_s_cold": round(compile_s, 1),
        "compile_s_warm_cache": compile_warm_cache_s,
        # live device-time attribution over the timed warm run (None
        # when the profiler never booked — a mis-wired hook must not
        # masquerade as MFU 0). Six significant digits, NOT fixed
        # decimals: CPU-backend MFU is ~1e-7 and must survive rounding
        "obs_mfu_train": (float(f"{obs_mfu_train:.6g}")
                          if obs_mfu_train > 0 else None),
        # per-op pio_device_seconds cross-check: the profiler's
        # block-until-ready wall over the SAME timed run — must bracket
        # train_s (test_bench_e2e asserts the ratio), and the dispatch
        # counter pins the whole run as ONE attributed dispatch
        "obs_device_train_s": (round(secs1 - secs0, 4)
                               if secs1 > secs0 else None),
        "obs_device_train_dispatches": int(disp1 - disp0),
        # warm wall through the fused Gram+solve kernel path, when the
        # selector engaged it (None = XLA assembly served this round)
        "train_fused_wall_s": (round(train_s, 3) if use_kernel else None),
    }


#: continuation-retrain record keys (docs/performance.md "Steady-state
#: retrain"): the O(delta) steady-state contract — after a ≤5% event
#: tail, continuation (warm factors + early-stop + plan reuse) must
#: finish in ≤ 1/3 of the fresh-retrain wall at RMSE parity
RETRAIN_KEYS = (
    "retrain_fresh_wall_s", "retrain_continue_wall_s",
    "retrain_sweeps_used", "retrain_delta_rows", "retrain_scan_s",
    "retrain_prep_fresh_s", "retrain_prep_continue_s",
    "retrain_heldout_rmse_fresh", "retrain_heldout_rmse_continue",
    "retrain_speedup", "retrain_one_dispatch", "retrain_train_dispatches",
)


def bench_retrain(store_dir, state, inter, heldout, truth):
    """Steady-state retrain leg: append a tail, re-ingest (traincache
    fold), then measure fresh-vs-continuation retrain walls.

    Fresh = full prep + fixed-budget warm train from random init.
    Continue = plan-reuse prep splice + warm factors + convergence
    early-stop, timed end to end (the splice is part of the wall — the
    plan is reset to its pre-tail state before the timed run so the
    O(delta) fold is actually measured). Both train walls are WARM
    (compile excluded, same convention as measure_train). Guarded by the
    global bench deadline: PIO_BENCH_EMIT_BY_EPOCH (set by the
    orchestrator from PIO_BENCH_DEADLINE_S) skips the leg rather than
    cost the record."""
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.data.storage import (
        StorageClientConfig,
        cpplog,
    )
    from incubator_predictionio_tpu.data.storage.base import (
        IdTable,
        Interactions,
    )
    from incubator_predictionio_tpu.ops import als, retrain
    from incubator_predictionio_tpu.ops.sparse import build_both_sides

    out = dict.fromkeys(RETRAIN_KEYS)
    emit_by = float(os.environ.get("PIO_BENCH_EMIT_BY_EPOCH", "0"))
    if emit_by and time.time() > emit_by - 120.0:
        log("retrain leg skipped: bench deadline too close")
        return out
    tail_frac = float(os.environ.get("PIO_BENCH_RETRAIN_TAIL", "0.05"))
    tail_n = max(int(NNZ * tail_frac), 1)
    rng = np.random.default_rng(13)
    t_users, t_items = _sample_pairs(rng, tail_n)
    u_true, v_true = truth
    signal = np.einsum("nk,nk->n", u_true[t_users], v_true[t_items])
    t_vals = (3.5 + signal
              + rng.normal(0, NOISE_SIGMA, tail_n)).astype(np.float32)

    # -- append the tail through the native columnar import --------------
    cfg = StorageClientConfig(properties={"PATH": store_dir})
    client = cpplog.StorageClient(cfg)
    events = cpplog.CppLogEvents(client, cfg, prefix="bench_")
    try:
        wrote = events.import_interactions(
            Interactions(
                user_idx=t_users, item_idx=t_items, values=t_vals,
                user_ids=IdTable.from_list(
                    [f"u{k}" for k in range(N_USERS)]),
                item_ids=IdTable.from_list(
                    [f"i{k}" for k in range(N_ITEMS)]),
            ), 1, event_name="rate", value_prop="rating")
        assert wrote == tail_n

        # -- re-ingest: the traincache tail fold (O(delta) scan) ---------
        stats: dict = {}
        t0 = time.perf_counter()
        inter2 = events.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating", stats=stats)
        scan_s = time.perf_counter() - t0
        delta_rows = int(stats.get("scan_tail_rows", tail_n))
        n_users2, n_items2 = len(inter2.user_ids), len(inter2.item_ids)

        # -- fresh leg: full prep + fixed-budget train from random init --
        t0 = time.perf_counter()
        (uf_l, uf_h), (if_l, if_h) = build_both_sides(
            inter2.user_idx, inter2.item_idx, inter2.values,
            n_users2, n_items2)
        uf_t, if_t = als._buckets_tree(uf_l), als._buckets_tree(if_l)
        uf_hv, if_hv = als._heavy_tree(uf_h), als._heavy_tree(if_h)
        prep_fresh_s = time.perf_counter() - t0

        def train_fresh():
            st = als._mixed_run(
                als.als_init(jax.random.key(0), n_users2, n_items2, RANK),
                uf_t, if_t, L2, ITERATIONS, BF16_SWEEPS, True,
                jnp.float32, jax.lax.Precision.HIGHEST,
                user_heavy=uf_hv, item_heavy=if_hv)
            np.asarray(st.user_factors[0:1, 0:1])
            np.asarray(st.item_factors[0:1, 0:1])
            return st

        state_f = train_fresh()          # compile
        t0 = time.perf_counter()
        state_f = train_fresh()          # warm
        train_fresh_s = time.perf_counter() - t0

        # -- continue leg: plan splice + warm factors + early stop -------
        prev = als.ALSState(
            user_factors=np.asarray(state.user_factors),
            item_factors=np.asarray(state.item_factors))

        def seed_plan():
            retrain.drop_plans()
            retrain.prepare_with_reuse(
                inter.user_idx, inter.item_idx, inter.values,
                len(inter.user_ids), len(inter.item_ids),
                plan_key="bench")

        rs: dict = {}

        def train_cont():
            rs.clear()
            st = retrain.als_retrain(
                inter2.user_idx, inter2.item_idx, inter2.values,
                n_users2, n_items2, rank=RANK, iterations=ITERATIONS,
                l2=L2, seed=0, bf16_sweeps=BF16_SWEEPS,
                prev_state=prev, plan_key="bench", stats=rs)
            np.asarray(st.user_factors[0:1, 0:1])
            np.asarray(st.item_factors[0:1, 0:1])
            return st

        from incubator_predictionio_tpu.obs import metrics as obs_metrics

        seed_plan()
        state_c = train_cont()           # compile + first fold
        seed_plan()                      # reset so the timed run re-folds
        sweeps_before = obs_metrics.REGISTRY.counter(
            "pio_train_sweeps_total", "ALS sweeps actually run by "
            "training, by schedule mode", labels=("mode",)
        ).labels(mode="continue").value
        t0 = time.perf_counter()
        state_c = train_cont()           # warm, O(delta) splice included
        cont_wall_s = time.perf_counter() - t0
        # registry cross-check over the TIMED run only (the compile run
        # books its own sweeps — a raw snapshot would double-count)
        sweeps_booked = obs_metrics.REGISTRY.get(
            "pio_train_sweeps_total").labels(mode="continue").value \
            - sweeps_before
        prep_cont_s = rs.get("prep_wall_s")  # the O(delta) splice wall

        ho_f, _p1 = quality_metrics(state_f, inter2, heldout, truth, rng)
        ho_c, _p2 = quality_metrics(state_c, inter2, heldout, truth, rng)
        fresh_wall = prep_fresh_s + train_fresh_s
        out.update({
            "retrain_fresh_wall_s": round(fresh_wall, 3),
            "retrain_continue_wall_s": round(cont_wall_s, 3),
            "retrain_sweeps_used": int(rs.get("sweeps_used", 0)),
            "retrain_delta_rows": delta_rows,
            # the one-dispatch contract, measured on the timed run:
            # splice + sweeps + early-stop in a single device dispatch
            "retrain_one_dispatch": bool(rs.get("one_dispatch", False)),
            "retrain_train_dispatches": int(rs.get("train_dispatches", 0)),
            "retrain_scan_s": round(scan_s, 3),
            "retrain_prep_fresh_s": round(prep_fresh_s, 3),
            "retrain_prep_continue_s": (None if prep_cont_s is None
                                        else round(prep_cont_s, 3)),
            "obs_train_sweeps_continue": int(sweeps_booked),
            "retrain_heldout_rmse_fresh": round(ho_f, 3),
            "retrain_heldout_rmse_continue": round(ho_c, 3),
            "retrain_speedup": round(fresh_wall / max(cont_wall_s, 1e-9),
                                     2),
        })
        log(f"retrain: tail={tail_n} (delta_rows={delta_rows}) "
            f"scan={scan_s:.2f}s fresh={fresh_wall:.2f}s "
            f"(prep {prep_fresh_s:.2f}s) continue={cont_wall_s:.2f}s "
            f"({rs.get('sweeps_used')} sweeps, "
            f"mode={rs.get('mode')}, plan={rs.get('prep_plan')}) "
            f"heldout fresh={ho_f:.3f} continue={ho_c:.3f}")
        retrain.drop_plans()
    finally:
        client.close()
    return out


#: speed-layer record keys (docs/production.md "Freshness between
#: retrains"): fold-in latency under concurrent ingest + serve, the
#: overlay hit rate, and how far the tail poll ran behind the writers
SPEED_KEYS = (
    "speed_foldin_p50_ms", "speed_foldin_p95_ms", "speed_hit_rate",
    "speed_cursor_lag_events", "speed_foldins", "speed_ingested_keys",
    "obs_freshness_p95_s",
)


def bench_speed(store_dir, state, inter):
    """Speed-layer leg: concurrent cold-user ingest + overlay serve.

    A writer thread streams brand-new users' rate events into the cpplog
    store while the overlay polls the tail cursor and folds the dirty
    users in on device; the serve side looks every ingested cold user up
    after each poll. Emits the fold-in cycle wall (p50/p95), the overlay
    hit rate over those lookups, and the worst cursor lag observed.
    Deadline-guarded like the retrain leg."""
    import threading

    from incubator_predictionio_tpu.data.storage import App, Storage
    from incubator_predictionio_tpu.speed.overlay import (
        SpeedOverlay,
        SpeedOverlayConfig,
    )

    out = dict.fromkeys(SPEED_KEYS)
    emit_by = float(os.environ.get("PIO_BENCH_EMIT_BY_EPOCH", "0"))
    if emit_by and time.time() > emit_by - 90.0:
        log("speed leg skipped: bench deadline too close")
        return out
    run_s = float(os.environ.get("PIO_BENCH_SPEED_S", "8"))
    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_SOURCES_CPP_TYPE": "cpplog",
        "PIO_STORAGE_SOURCES_CPP_PATH": store_dir,
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        # repo NAME "bench" → namespace prefix "bench_", the seeded log
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "bench",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "CPP",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    try:
        Storage.get_meta_data_apps().insert(App(1, "bench"))
        item_index = {t: k for k, t in enumerate(inter.item_ids)}
        user_index = {u: k for k, u in enumerate(inter.user_ids)}
        overlay = SpeedOverlay(
            SpeedOverlayConfig(
                app_name="bench", engine="bench", event_names=("rate",),
                value_prop="rating", l2=L2, reg_nnz=True,
                max_keys_per_poll=1024, ttl_s=600.0),
            other_factors=state.item_factors,
            other_index=item_index, key_index=user_index)
        assert overlay.enabled

        from incubator_predictionio_tpu.data.storage.base import (
            IdTable,
            Interactions,
        )

        dao = Storage.get_events()
        stop = threading.Event()
        ingested: list = []  # cold user ids, in ingest order
        rng = np.random.default_rng(23)
        events_per_user = 8
        users_per_batch = 16

        def writer() -> None:
            j = 0
            while not stop.is_set():
                uids = [f"cold{j + k}" for k in range(users_per_batch)]
                n = users_per_batch * events_per_user
                uidx = np.repeat(np.arange(users_per_batch, dtype=np.int32),
                                 events_per_user)
                iidx = rng.integers(0, len(item_index), n).astype(np.int32)
                vals = rng.normal(3.5, 1.0, n).astype(np.float32)
                item_tab = IdTable.from_list(
                    [inter.item_ids[int(i)] for i in iidx])
                dao.import_interactions(
                    Interactions(
                        user_idx=uidx,
                        item_idx=np.arange(n, dtype=np.int32),
                        values=vals,
                        user_ids=IdTable.from_list(uids),
                        item_ids=item_tab),
                    1, event_name="rate", value_prop="rating")
                ingested.extend(uids)
                j += users_per_batch
                stop.wait(0.05)

        t_writer = threading.Thread(target=writer, daemon=True)
        t_writer.start()
        fold_walls: list = []
        max_lag = 0
        t_end = time.perf_counter() + run_s
        while time.perf_counter() < t_end:
            t0 = time.perf_counter()
            s = overlay.poll()
            if s.get("solved"):
                fold_walls.append(time.perf_counter() - t0)
            max_lag = max(max_lag, int(s.get("lag", 0)))
            # serve side: look up every cold user ingested so far — the
            # honest freshness probe (users not yet folded in miss)
            for uid in list(ingested):
                overlay.lookup(uid)
        stop.set()
        t_writer.join(timeout=10)
        # drain the remaining dirty set so the final hit-rate pass
        # reflects steady state, not the shutdown edge
        for _ in range(8):
            if not overlay.poll().get("dirty"):
                break
        st = overlay.stats()
        walls_ms = np.sort(np.asarray(fold_walls)) * 1e3
        looked = st["hits"] + st["misses"]
        # end-to-end freshness (event append -> first folded serve) from
        # the new pio_freshness_seconds histogram — the measured form of
        # the speed layer's promise, not an inference from staleness
        from incubator_predictionio_tpu.obs import metrics as obs_metrics
        fh = obs_metrics.REGISTRY.get("pio_freshness_seconds")
        fresh_p95 = (fh.quantile_over_children(0.95)
                     if fh is not None else None)
        out.update({
            "obs_freshness_p95_s": (round(fresh_p95, 3)
                                    if fresh_p95 else None),
            "speed_foldin_p50_ms": (
                round(float(walls_ms[int(0.50 * (len(walls_ms) - 1))]), 2)
                if len(walls_ms) else None),
            "speed_foldin_p95_ms": (
                round(float(walls_ms[int(0.95 * (len(walls_ms) - 1))]), 2)
                if len(walls_ms) else None),
            "speed_hit_rate": (round(st["hits"] / looked, 3)
                               if looked else None),
            "speed_cursor_lag_events": int(max_lag),
            "speed_foldins": int(st["foldins"]),
            "speed_ingested_keys": int(len(ingested)),
        })
        log(f"speed: {len(ingested)} cold users ingested, "
            f"{st['foldins']} fold-ins, "
            f"foldin p50={out['speed_foldin_p50_ms']}ms "
            f"p95={out['speed_foldin_p95_ms']}ms "
            f"hit_rate={out['speed_hit_rate']} max_lag={max_lag} "
            f"freshness_p95={out['obs_freshness_p95_s']}s")
    finally:
        Storage.reset()
    return out


#: registry cross-check keys (docs/observability.md): the telemetry
#: layer and the bench time THE SAME stages, so their numbers must
#: corroborate — obs_ingest_events_total vs the seeded HTTP load,
#: obs_query_p50_ms vs serve_p50_ms, compile-cache hits vs the
#: warm-cache compile probe. A divergence means one of them lies.
OBS_KEYS = (
    "obs_ingest_events_total", "obs_ingest_batches",
    "obs_http_requests_total", "obs_query_latency_count",
    "obs_query_latency_sum_s", "obs_query_p50_ms", "obs_query_p99_ms",
    "obs_compile_cache_hits", "obs_compile_cache_requests",
    "obs_train_sweeps_continue", "obs_mfu_train", "obs_mfu_vs_offline",
)


def obs_snapshot() -> dict:
    """Snapshot the process-wide metrics registry into obs_* bench
    sub-metrics. Keys for stages THIS process never ran stay None
    (a metric that exists but never booked is indistinguishable from a
    mis-wired one — the count guards keep the cross-check honest)."""
    from incubator_predictionio_tpu.obs import metrics as obs_metrics

    reg = obs_metrics.REGISTRY
    out = dict.fromkeys(OBS_KEYS)
    ingest = reg.get("pio_ingest_events_total")
    if ingest is not None and ingest.total():
        out["obs_ingest_events_total"] = int(ingest.total())
    batches = reg.get("pio_ingest_batch_size")
    if batches is not None and batches.count:
        out["obs_ingest_batches"] = int(batches.count)
    http = reg.get("pio_http_requests_total")
    if http is not None and http.total():
        out["obs_http_requests_total"] = int(http.total())
    qlat = reg.get("pio_query_latency_seconds")
    if qlat is not None and qlat.count:
        out["obs_query_latency_count"] = int(qlat.count)
        out["obs_query_latency_sum_s"] = round(qlat.sum, 3)
        out["obs_query_p50_ms"] = round(qlat.quantile(0.50) * 1e3, 2)
        out["obs_query_p99_ms"] = round(qlat.quantile(0.99) * 1e3, 2)
    hits = reg.get("pio_compile_cache_hits_total")
    if hits is not None:
        out["obs_compile_cache_hits"] = int(hits.value)
    reqs = reg.get("pio_compile_cache_requests_total")
    if reqs is not None:
        out["obs_compile_cache_requests"] = int(reqs.value)
    # obs_train_sweeps_continue is NOT snapshotted here: the retrain leg
    # computes it as the counter delta over its timed run (bench_retrain)
    # so it corroborates retrain_sweeps_used exactly — a raw snapshot
    # would include the compile run's sweeps and read as a 2× lie
    return out


#: mesh-sharded training leg (docs/performance.md "Sharded ALS"): the
#: placed-train wall over the forced-host-device mesh, the analytic
#: collective volume, and the fused-kernel routing story at ML-20M —
#: per-shard slice residency is what re-enables the fused kernel on the
#: big-table side (ROADMAP items 1/5)
SHARD_KEYS = (
    "shard_train_wall_s", "shard_mesh_shape", "shard_devices",
    "shard_nnz", "shard_sweeps",
    "shard_backend", "shard_allgather_bytes", "shard_mfu_train",
    "shard_gather_modes", "shard_fused_user_sweep",
    "shard_fused_item_sweep", "shard_fused_fits_ml20m_user_sweep",
    "shard_fused_fits_ml20m_item_sweep",
)

#: the true MovieLens-20M catalog shape + rank: the fused-VMEM routing
#: keys are computed at THIS shape regardless of any smoke-run
#: PIO_BENCH_* overrides — they are the headline claim, not a sample
ML20M_SHAPE = (138_493, 26_744, 128)


def run_shard_child() -> None:
    """``--shard-child``: the mesh-sharded training leg, in its own
    process so the forced-host-device backend (the parent exports
    ``--xla_force_host_platform_device_count``) never perturbs the main
    bench's single-device timings. Prints ONE JSON line on stdout."""
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.obs import metrics as obs_metrics
    from incubator_predictionio_tpu.ops import als
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_fused_fits,
    )
    from incubator_predictionio_tpu.parallel.mesh import make_mesh
    from incubator_predictionio_tpu.parallel.placement import (
        make_placement,
    )

    nnz = int(os.environ.get("PIO_BENCH_SHARD_NNZ",
                             str(min(NNZ, 1_000_000))))
    sweeps = int(os.environ.get("PIO_BENCH_SHARD_SWEEPS", "2"))
    bf16 = min(BF16_SWEEPS, sweeps)
    rng = np.random.default_rng(17)
    users = rng.integers(0, N_USERS, nnz).astype(np.int32)
    items = rng.integers(0, N_ITEMS, nnz).astype(np.int32)
    vals = rng.uniform(1, 5, nnz).astype(np.float32)
    mesh = make_mesh()
    placement = make_placement(mesh, N_USERS, N_ITEMS)
    # mirror als_train_placed's leg structure explicitly so the timed
    # window covers ONLY the training dispatches (the host-side bucket
    # prep would otherwise dominate the CPU-sim wall and make
    # shard_mfu_train incomparable to the main leg's MFU keys), and so
    # the reported routing comes from the cfg the timed sweeps actually
    # run (all-bf16 schedules route at bfloat16, not f32)
    modes = als._shard_gather_modes(placement, RANK, jnp.float32, False)
    u_data, i_data = als.build_placed_sides(
        users, items, vals, placement, modes)
    cfg_lo = als._placed_cfg(
        placement, RANK, False, True, L2, 0.0, jnp.bfloat16,
        jax.lax.Precision.DEFAULT,
        min(als._CG_ITERS_BF16, als._CG_ITERS), modes=modes)
    cfg_f32 = als._placed_cfg(
        placement, RANK, False, True, L2, 1.0, jnp.float32,
        jax.lax.Precision.HIGHEST, als._CG_ITERS, modes=modes)
    cfg = cfg_lo if bf16 >= sweeps else cfg_f32

    state = placement.place_state(
        als.als_init(jax.random.key(0), N_USERS, N_ITEMS, RANK))

    def run():
        uf, vf = state.user_factors, state.item_factors
        if bf16:
            uf, vf = als._als_run_placed(
                uf, vf, u_data, i_data, placement=placement,
                cfg=cfg_lo, iterations=bf16)
        if sweeps - bf16:
            uf, vf = als._als_run_placed(
                uf, vf, u_data, i_data, placement=placement,
                cfg=cfg_f32, iterations=sweeps - bf16)
        jax.block_until_ready((uf, vf))
        return uf, vf

    run()                                    # compile

    def gather_bytes() -> int:
        gb = obs_metrics.REGISTRY.get("pio_shard_gather_bytes_total")
        if gb is None:
            return 0
        return int(sum(gb.labels(strategy=s).value
                       for s in ("allgather", "ring")))

    t0 = time.perf_counter()
    run()                                    # warm, dispatches only
    wall = time.perf_counter() - t0
    # the analytic per-leg collective volume the trainer books
    before = gather_bytes()
    if bf16:
        als._book_shard_metrics(placement, cfg_lo, RANK, bf16)
    if sweeps - bf16:
        als._book_shard_metrics(placement, cfg_f32, RANK, sweeps - bf16)
    flops = als.train_flops(nnz, N_USERS, N_ITEMS, RANK, sweeps, bf16)
    mfu = flops / wall / PEAK_FLOPS_F32

    # fused-kernel routing at the TRUE ML-20M shape under this mesh:
    # the VMEM math alone (deterministic on every backend — the
    # per-run shard_fused_* keys additionally carry the Mosaic probe)
    mu, mi, mr = ML20M_SHAPE
    p20 = make_placement(mesh, mu, mi)
    modes20 = als._shard_gather_modes(p20, mr, jnp.bfloat16, False)
    out = {
        "shard_train_wall_s": round(wall, 3),
        "shard_mesh_shape": placement.describe(),
        "shard_devices": placement.n_shards,
        # the leg's own workload shape: the capacity model
        # (obs/capacity.py) needs rows+sweeps next to the wall to turn
        # shard timings into a rows/chip rate
        "shard_nnz": nnz,
        "shard_sweeps": sweeps,
        "shard_backend": jax.devices()[0].platform,
        "shard_allgather_bytes": gather_bytes() - before,
        "shard_mfu_train": float(f"{mfu:.6g}"),
        "shard_gather_modes": "+".join((cfg.u_mode, cfg.i_mode)),
        "shard_fused_user_sweep": bool(cfg.fused_u),
        "shard_fused_item_sweep": bool(cfg.fused_i),
        "shard_fused_fits_ml20m_user_sweep": bool(als_fused_fits(
            als.gather_source_rows(p20, "item", modes20[0]),
            mr, jnp.bfloat16)),
        "shard_fused_fits_ml20m_item_sweep": bool(als_fused_fits(
            als.gather_source_rows(p20, "user", modes20[1]),
            mr, jnp.bfloat16)),
    }
    sys.stdout.write(json.dumps(out) + "\n")
    sys.stdout.flush()


def bench_shard(budget_s: float) -> dict:
    """Parent-side mesh-sharded leg: spawn ``--shard-child`` with the
    CPU backend forced to ``PIO_BENCH_SHARD_DEVICES`` (default 8)
    virtual host devices — the sharded path measured without hardware,
    and without perturbing this process's single-device jax. Guarded:
    any failure nulls the shard_* keys, never the record."""
    out = dict.fromkeys(SHARD_KEYS)
    if budget_s < 20.0:
        log("shard leg skipped: bench deadline too close")
        return out
    ndev = int(os.environ.get("PIO_BENCH_SHARD_DEVICES", "8"))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={ndev}").strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--shard-child"],
        env=env, capture_output=True, text=True,
        timeout=min(budget_s, float(
            os.environ.get("PIO_BENCH_SHARD_TIMEOUT_S", "300"))))
    if proc.returncode != 0:
        raise RuntimeError(
            f"shard child rc={proc.returncode}: {proc.stderr[-500:]}")
    out.update(json.loads(proc.stdout.splitlines()[-1]))
    log(f"shard: mesh={out['shard_mesh_shape']} "
        f"({out['shard_backend']}) warm={out['shard_train_wall_s']}s "
        f"gather={out['shard_gather_modes']} "
        f"bytes={out['shard_allgather_bytes']} "
        f"fused_ml20m=({out['shard_fused_fits_ml20m_user_sweep']}, "
        f"{out['shard_fused_fits_ml20m_item_sweep']})")
    return out


#: two-stage MIPS serving leg (docs/performance.md "Two-stage MIPS
#: serving"): exhaustive-vs-two-stage per-query device wall and the
#: candidates-scanned fraction on the planted large catalogue, plus the
#: recall@20-vs-exact gate figure. ``mips_sweep`` carries the whole
#: {27k, 256k, 1M} size ladder; the scalar keys are the GATE size (the
#: largest completed ≥ 128k, where the two-stage win must hold). None =
#: the leg's designed deadline-skip (same contract as shard_*/fleet_*).
MIPS_KEYS = (
    "mips_items", "mips_build_s", "mips_exhaustive_per_query_ms",
    "mips_exhaustive_p99_ms", "mips_two_stage_per_query_ms",
    "mips_two_stage_p99_ms", "mips_speedup", "mips_candidates_frac",
    "mips_recall_at_20", "mips_recompiles_steady", "mips_serve_qps",
    "mips_exhaustive_27k_p99_ms", "mips_sweep",
)


def bench_mips(budget_s: float) -> dict:
    """Planted-catalogue MIPS leg, in-process (single device suffices —
    the sharded merge is pinned by tier-1 tests/test_mips.py at mesh
    {1,2,4,8}). Per size: build the index, measure exhaustive and
    two-stage per-query walls through the REAL ops/topk auto-router
    (PIO_SERVE_MIPS=off vs =on), the recall@20 against the exhaustive
    oracle, and the steady-state recompile count. Budget-guarded like
    bench_shard: any failure or deadline squeeze nulls keys, never the
    record."""
    out = dict.fromkeys(MIPS_KEYS)
    if budget_s < 45.0:
        log("mips leg skipped: bench deadline too close")
        return out
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import mips as mips_mod
    from incubator_predictionio_tpu.ops import topk
    from incubator_predictionio_tpu.utils.planted import (
        exhaustive_top_k,
        planted_item_factors,
        planted_queries,
        recall_against_oracle,
    )

    sizes = [int(s) for s in os.environ.get(
        "PIO_BENCH_MIPS_ITEMS", "27000,262144,1048576").split(",") if s]
    rank = int(os.environ.get("PIO_BENCH_MIPS_RANK", "64"))
    n_q = int(os.environ.get("PIO_BENCH_MIPS_QUERIES", "32"))
    leg_deadline = time.monotonic() + min(
        budget_s - 15.0,
        float(os.environ.get("PIO_BENCH_MIPS_TIMEOUT_S", "300")))
    prev_mode = os.environ.get("PIO_SERVE_MIPS")

    def _restore_mode() -> None:
        if prev_mode is None:
            os.environ.pop("PIO_SERVE_MIPS", None)
        else:
            os.environ["PIO_SERVE_MIPS"] = prev_mode

    def _per_query_ms(queries) -> tuple:
        """(p50, p99) wall over the real router, one fetch per query."""
        np.asarray(topk.score_and_top_k(queries[0], table, k=20))  # warm
        walls = []
        for q in queries:
            t0 = time.perf_counter()
            np.asarray(topk.score_and_top_k(q, table, k=20))
            walls.append((time.perf_counter() - t0) * 1e3)
        walls = np.asarray(walls)
        return (float(np.quantile(walls, 0.5)),
                float(np.quantile(walls, 0.99)))

    sweep: dict = {}
    try:
        for n_items in sizes:
            # rough leg cost model (measured on the CI box): build +
            # queries scale ~linearly with the catalogue
            est_s = 8.0 + 30.0 * n_items / 262144.0
            if time.monotonic() + est_s * 1.3 > leg_deadline:
                log(f"mips leg: skipping {n_items} items "
                    "(deadline too close)")
                break
            vf = planted_item_factors(n_items, rank, seed=11)
            queries = [jnp.asarray(q) for q in
                       planted_queries(vf, n_q, seed=5)]
            oracle = exhaustive_top_k(
                vf, np.stack([np.asarray(q) for q in queries]), 20)
            table = jax.device_put(vf)
            os.environ["PIO_SERVE_MIPS"] = "off"
            ex_p50, ex_p99 = _per_query_ms(queries)
            t0 = time.perf_counter()
            index = mips_mod.build_index(table, n_items, seed=11,
                                         host_factors=vf)
            build_s = time.perf_counter() - t0
            os.environ["PIO_SERVE_MIPS"] = "on"
            two_p50, two_p99 = _per_query_ms(queries)
            # steady state: repeat the warmed shapes — the compile
            # cache must not move (the pow2-ladder contract)
            cache0 = topk.serve_compile_cache_size()
            got = np.stack([
                np.asarray(topk.score_and_top_k(q, table, k=20))[1]
                .astype(np.int64) for q in queries])
            recompiles = topk.serve_compile_cache_size() - cache0
            recall, _worst = recall_against_oracle(got, oracle, 20)
            _nprobe, coarse, rerank = mips_mod.scan_budget(index, 20)
            frac = (coarse + rerank) / n_items
            mips_mod.recall_probe(table, index, host_factors=vf)
            sweep[str(n_items)] = {
                "exhaustive_p50_ms": round(ex_p50, 3),
                "exhaustive_p99_ms": round(ex_p99, 3),
                "two_stage_p50_ms": round(two_p50, 3),
                "two_stage_p99_ms": round(two_p99, 3),
                "build_s": round(build_s, 2),
                "candidates_frac": round(frac, 4),
                "recall_at_20": round(recall, 4),
                "recompiles_steady": int(recompiles),
            }
            log(f"mips {n_items}: exhaustive {ex_p50:.2f}ms vs "
                f"two-stage {two_p50:.2f}ms (recall {recall:.3f}, "
                f"frac {frac:.3f}, build {build_s:.1f}s)")
            if n_items <= 32768:
                out["mips_exhaustive_27k_p99_ms"] = round(ex_p99, 3)
            mips_mod.unregister_index(table)
            del table, vf, queries, index
    finally:
        _restore_mode()
    gate_sizes = [int(s) for s in sweep if int(s) >= 131072]
    if gate_sizes:
        gate = sweep[str(max(gate_sizes))]
        out.update({
            "mips_items": max(gate_sizes),
            "mips_build_s": gate["build_s"],
            "mips_exhaustive_per_query_ms": gate["exhaustive_p50_ms"],
            "mips_exhaustive_p99_ms": gate["exhaustive_p99_ms"],
            "mips_two_stage_per_query_ms": gate["two_stage_p50_ms"],
            "mips_two_stage_p99_ms": gate["two_stage_p99_ms"],
            "mips_speedup": round(
                gate["exhaustive_p50_ms"]
                / max(gate["two_stage_p50_ms"], 1e-9), 3),
            "mips_candidates_frac": gate["candidates_frac"],
            "mips_recall_at_20": gate["recall_at_20"],
            "mips_recompiles_steady": gate["recompiles_steady"],
            # the capacity model's device-bound QPS projection
            # (obs/capacity.py qps_source_key="mips_serve_qps")
            "mips_serve_qps": round(
                1000.0 / max(gate["two_stage_p50_ms"], 1e-9), 1),
        })
    if sweep:
        out["mips_sweep"] = sweep
    return out


#: catalogue-at-tens-of-millions leg (docs/performance.md "Catalogue at
#: tens of millions"): the ≥10M-item lifecycle under PQ residual codes.
#: The recall@20 gate must hold at PQ bytes-per-item, the serving p99
#: measured WHILE a background rebuild-and-swap folds a planted churn
#: tail must stay ≤1.5× the quiet baseline (``mips_rebuild_p99_flat_x``),
#: ``mips_index_age_max_s`` is the worst index age observed across that
#: churn cycle, and ``mips_device_bytes_per_item`` is the capacity
#: model's sizing key (table f32 rerank rows + quantized coarse views +
#: index bookkeeping). None = deadline/budget skip — the default cost
#: model always skips on the 1-core CI box; give the leg a real box via
#: PIO_BENCH_MIPS_BIG_ITEMS / PIO_BENCH_MIPS_BIG_TIMEOUT_S.
MIPS_BIG_KEYS = (
    "mips_big_items", "mips_big_build_s", "mips_big_recall_at_20",
    "mips_big_two_stage_p50_ms", "mips_rebuild_p99_flat_x",
    "mips_index_age_max_s", "mips_device_bytes_per_item",
)


def bench_mips_big(budget_s: float) -> dict:
    """≥10M-item MIPS lifecycle leg: PQ build, recall gate, then serve
    a query loop WHILE ``rebuild_index`` re-clusters and swaps under a
    planted churn tail — the flat-p99-through-rebuild claim. Budget-
    guarded like every host leg: a squeeze nulls keys, never the
    record."""
    out = dict.fromkeys(MIPS_BIG_KEYS)
    n_big = int(os.environ.get("PIO_BENCH_MIPS_BIG_ITEMS", "10000000"))
    rank = int(os.environ.get("PIO_BENCH_MIPS_RANK", "64"))
    n_q = int(os.environ.get("PIO_BENCH_MIPS_QUERIES", "32"))
    if n_big < 1_000_000:
        log("mips big leg disabled (PIO_BENCH_MIPS_BIG_ITEMS < 1M)")
        return out
    # cost model for the CI box: sample-kmeans + chunked assignment +
    # PQ train/encode scale ~linearly with the catalogue, and the
    # rebuild pays it a second time
    est_s = 90.0 + 180.0 * n_big / 1_000_000.0
    leg_deadline = time.monotonic() + min(
        budget_s - 20.0,
        float(os.environ.get("PIO_BENCH_MIPS_BIG_TIMEOUT_S", "300")))
    if time.monotonic() + est_s > leg_deadline:
        log(f"mips big leg skipped: needs ~{est_s:.0f}s, "
            "deadline too close")
        return out
    import threading

    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import mips as mips_mod
    from incubator_predictionio_tpu.ops import topk
    from incubator_predictionio_tpu.utils.planted import (
        exhaustive_top_k,
        planted_item_factors,
        planted_queries,
        recall_against_oracle,
    )

    saved = {k: os.environ.get(k)
             for k in ("PIO_SERVE_MIPS", "PIO_SERVE_MIPS_QUANT")}
    os.environ["PIO_SERVE_MIPS"] = "on"
    os.environ["PIO_SERVE_MIPS_QUANT"] = "pq"

    def _timed(q) -> float:
        t0 = time.perf_counter()
        np.asarray(topk.score_and_top_k(q, table, k=20))
        return (time.perf_counter() - t0) * 1e3

    try:
        vf = planted_item_factors(n_big, rank, seed=11)
        queries = [jnp.asarray(q) for q in
                   planted_queries(vf, n_q, seed=5)]
        oracle = exhaustive_top_k(
            vf, np.stack([np.asarray(q) for q in queries]), 20)
        table = jax.device_put(vf)
        t0 = time.perf_counter()
        index = mips_mod.build_index(table, n_big, seed=11,
                                     host_factors=vf)
        build_s = time.perf_counter() - t0
        log(f"mips big: built {n_big} items (pq m={index.pq_m}) "
            f"in {build_s:.1f}s")

        _timed(queries[0])                          # warm
        base = np.asarray([_timed(q) for q in queries])
        got = np.stack([
            np.asarray(topk.score_and_top_k(q, table, k=20))[1]
            .astype(np.int64) for q in queries])
        recall, _worst = recall_against_oracle(got, oracle, 20)

        # planted churn past the fold-out point, then serve THROUGH the
        # background rebuild-and-swap
        churn = planted_queries(vf, 256, seed=9)
        mips_mod.publish_rows(table, churn)
        walls: list = []
        ages: list = []

        def _sample_age() -> None:
            idx = mips_mod.index_for(table)
            if idx is not None:
                ages.append(mips_mod._now() - idx.built_at)

        reb = threading.Thread(
            target=lambda: mips_mod.rebuild_index(table, trigger="tail"),
            daemon=True)
        reb.start()
        i = 0
        while reb.is_alive() and time.monotonic() < leg_deadline:
            walls.append(_timed(queries[i % n_q]))
            _sample_age()
            i += 1
        reb.join(timeout=max(leg_deadline - time.monotonic(), 1.0))
        for j in range(8):                          # post-swap tail
            walls.append(_timed(queries[j % n_q]))
            _sample_age()

        p99_base = float(np.quantile(base, 0.99))
        p99_reb = (float(np.quantile(np.asarray(walls), 0.99))
                   if walls else p99_base)
        new = mips_mod.index_for(table)
        dev_bytes = int(np.asarray(table).nbytes)
        for arr in (new.codes, new.scales, new.bf16, new.pq_codes,
                    new.pq_books, new.centroids, new.cmax,
                    new.crad_cos, new.crad_sin, new.members, new.ext):
            if arr is not None:
                dev_bytes += int(arr.nbytes)
        out.update({
            "mips_big_items": n_big,
            "mips_big_build_s": round(build_s, 2),
            "mips_big_recall_at_20": round(recall, 4),
            "mips_big_two_stage_p50_ms": round(
                float(np.quantile(base, 0.5)), 3),
            "mips_rebuild_p99_flat_x": round(
                p99_reb / max(p99_base, 1e-9), 3),
            "mips_index_age_max_s": (round(float(max(ages)), 3)
                                     if ages else None),
            "mips_device_bytes_per_item": round(dev_bytes / n_big, 2),
        })
        log(f"mips big {n_big}: recall {recall:.3f}, rebuild p99 "
            f"{out['mips_rebuild_p99_flat_x']}x flat, "
            f"{out['mips_device_bytes_per_item']} device B/item")
        mips_mod.unregister_index(table)
        del table, vf, queries, index
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return out


#: serving-fleet leg (docs/production.md "Serving fleet"): the
#: continuous-batching request plane measured across REAL worker
#: processes — goodput burst (real kernels, no floor) for the capacity
#: fit, then an open-loop load ramp against a simulated fixed dispatch
#: wall where queue-depth-adaptive batching must demonstrably engage
#: (fleet_batch_p50 > the old fixed 64) at flat p99
FLEET_KEYS = (
    "fleet_workers", "fleet_qps", "fleet_qps_per_worker",
    "fleet_p99_s", "fleet_p50_ms", "fleet_batch_p50",
    "fleet_shed_rate", "fleet_shed_total", "fleet_p99_ramp_s",
    "fleet_offered_rps_ramp", "fleet_p99_flat_x",
    "fleet_recompiles_steady", "fleet_dispatch_floor_ms",
    # flight-recorder leg keys (docs/observability.md "Flight recorder
    # & incidents"): serving p99 with the recorder + exemplars ON vs
    # recorder OFF (the ≤1.1× overhead pin), and whether the planted
    # over-saturation breach autonomously froze a validated incident
    # bundle
    "recorder_overhead_p99_x", "fleet_incident_captured",
)


def _fleet_worker_env(floor_ms: float, extra: dict = None) -> dict:
    """Environment for a serve-mode fleet worker subprocess: CPU backend
    forced; floored workers get a proportionally relaxed serve_p99
    objective so the simulated dispatch wall itself is not read as an
    overload. ``extra`` overrides land last (the recorder-off baseline
    and the incident stage's breach tuning use this)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    # never inherit the parent's capture destination: only the incident
    # stage's workers are MEANT to freeze bundles
    env.pop("PIO_INCIDENT_DIR", None)
    env["PYTHONPATH"] = (os.path.dirname(os.path.abspath(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    env["PIO_SPEED_LAYER"] = "0"
    if floor_ms > 0:
        # the floored ramp measures BATCHING, not shedding: the
        # objective scales with the simulated dispatch wall (p50 is
        # ~1.5 floors by construction, the live p99 estimate rides on
        # top) so the in-capacity stages stay shed-free and the
        # over-saturation stage still crosses it
        env["PIO_SLO_SERVE_P99_S"] = str(max(8.0 * floor_ms / 1000.0,
                                             0.25))
    if extra:
        env.update(extra)
    return env


def _await_port(proc, deadline: float) -> tuple:
    """Bounded wait for a worker's ``PORT <n> [WARM_S <s>]`` line →
    (port, warm_s): a worker that dies during jax import or ladder
    warmup must fail the leg (nulling its keys), never hang the bench
    past the driver's deadline."""
    import select

    ready, _w, _x = select.select(
        [proc.stdout], [], [], max(deadline - time.monotonic(), 1.0))
    line = proc.stdout.readline() if ready else ""
    if not line.startswith("PORT"):
        raise RuntimeError("fleet worker failed to start")
    parts = line.split()
    warm_s = float(parts[3]) if len(parts) >= 4 else 0.0
    return int(parts[1]), warm_s


def _fleet_spawn(n: int, floor_ms: float, max_batch: int = 512,
                 extra_env: dict = None):
    """Spawn ``n`` serve-mode fleet workers (tests/fleet_worker.py) →
    list of (proc, port)."""
    workers = []
    env = _fleet_worker_env(floor_ms, extra=extra_env)
    worker_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tests", "fleet_worker.py")
    for i in range(n):
        proc = subprocess.Popen(
            [sys.executable, worker_py, "--mode", "serve",
             "--seed", str(i), "--max-batch", str(max_batch),
             "--dispatch-floor-ms", str(floor_ms)],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        workers.append(proc)
    out = []
    deadline = time.monotonic() + 120.0
    for proc in workers:
        try:
            port, _warm = _await_port(proc, deadline)
        except RuntimeError:
            _fleet_teardown([(p, None) for p in workers])
            raise
        out.append((proc, port))
    return out


def _fleet_teardown(workers) -> None:
    for proc, _port in workers:
        try:
            proc.stdin.close()
        except Exception:
            pass
    for proc, _port in workers:
        try:
            proc.wait(timeout=10)
        except Exception:
            proc.kill()


def _fleet_scrape(port: int) -> tuple:
    """ONE ``/metrics`` fetch + parse per worker per bookkeeping point
    → (``pio_serve_batch_size`` cumulative buckets {le: count},
    ``pio_serve_compile_cache_size`` value) — parsed with the SAME
    exposition grammar the federation layer uses (obs/expofmt)."""
    import urllib.request

    from incubator_predictionio_tpu.obs import expofmt

    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as resp:
        text = resp.read().decode()
    _meta, samples = expofmt.parse_exposition(text)
    buckets, _s, _total = expofmt.histogram_series(
        samples, "pio_serve_batch_size")
    cache = samples.get(("pio_serve_compile_cache_size", frozenset()),
                        0.0)
    return {le: v for le, v in buckets}, float(cache)


def _stage_p99(walls) -> float:
    """One ramp stage's p99: the MEDIAN of the p99s of three
    consecutive sub-windows. The plain full-stage p99 is set by a
    handful of worst samples, and on a small shared box one transient
    scheduling burst flips it by 2×+ run to run — the median-of-thirds
    estimator reports the stage's steady tail instead of its single
    worst second (all stages use the same estimator, so the flatness
    ratio compares like with like)."""
    arr = np.asarray(walls, np.float64)
    thirds = np.array_split(arr, 3)
    p99s = [float(np.quantile(t, 0.99)) for t in thirds if len(t)]
    return float(np.median(p99s))


def _bucket_quantile(cum: dict, q: float):
    """Quantile by linear interpolation over de-cumulated bucket counts
    (the registry's own quantile rule, over scraped buckets)."""
    bounds = sorted(cum.items())
    total = bounds[-1][1] if bounds else 0.0
    if total <= 0:
        return None
    target = q * total
    lo, prev = 0.0, 0.0
    for bound, c in bounds:
        if c >= target:
            in_bucket = c - prev
            if bound == float("inf"):
                return lo
            return lo + (bound - lo) * (
                (target - prev) / in_bucket if in_bucket else 0.0)
        prev, lo = c, bound
    return lo


async def _fleet_request(reader, writer, body: bytes,
                         path: bytes = b"/queries.json"):
    """One framed query request/response on a kept-alive connection →
    (status, wall seconds). The ONE copy of the fleet generators' HTTP
    framing (closed-loop burst and open-loop ramp share it); 503 sheds
    are results, not errors — the Retry-After contract is part of the
    plane under test. ``path`` carries a per-tenant ``?accessKey=`` in
    the multi-tenant leg."""
    t0 = time.perf_counter()
    writer.write(
        b"POST " + path + b" HTTP/1.1\r\nHost: bench\r\n"
        b"Content-Type: application/json\r\n"
        + f"X-PIO-Trace-Id: {_bench_trace_id()}\r\n"
          f"Content-Length: {len(body)}\r\n\r\n".encode()
        + body)
    await writer.drain()
    head = await reader.readuntil(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    clen = next(
        (int(line.split(b":")[1]) for line in head.split(b"\r\n")
         if line.lower().startswith(b"content-length")), 0)
    if clen:
        await reader.readexactly(clen)
    return status, time.perf_counter() - t0


async def _fleet_closed_loop(port: int, n_clients: int, per_client: int,
                             results: list,
                             path: bytes = b"/queries.json") -> None:
    """Closed-loop burst: every client fires its next query the moment
    the previous answers (the max-goodput shape)."""
    import asyncio

    async def one(cid: int) -> None:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            for j in range(per_client):
                body = json.dumps({
                    "user": f"u{(cid * per_client + j) % 2000}",
                    "num": 10}).encode()
                status, dt = await _fleet_request(reader, writer, body,
                                                 path=path)
                results.append((status, dt, False))
        finally:
            writer.close()

    await asyncio.gather(*[one(c) for c in range(n_clients)])


async def _fleet_open_loop(port: int, rate_rps: float, duration_s: float,
                           results: list, period_s: float = 2.0,
                           path: bytes = b"/queries.json") -> None:
    """Open-loop stage: connections send on a fixed schedule (offered
    load is the independent variable), so below saturation the latency
    distribution reflects the serving plane, not Little's-law queueing
    at the generator."""
    import asyncio

    # per-connection send period must comfortably exceed the worst
    # plausible RTT or a slow response silently throttles the offered
    # rate and bunches arrivals (coordinated omission) — the caller
    # scales period_s with the simulated dispatch floor
    conns = max(8, int(rate_rps * period_s))
    per_conn = max(int(rate_rps * duration_s / conns), 1)
    period = conns / rate_rps

    async def one(cid: int) -> None:
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        try:
            # golden-ratio phase jitter: near-uniform send phases over
            # the whole period (a modulo-N jitter bunches hundreds of
            # conns into N bursts, and the burst shows up as measured
            # tail latency)
            next_t = time.perf_counter() + period * ((cid * 0.618) % 1.0)
            for j in range(per_conn):
                now = time.perf_counter()
                if next_t > now:
                    await asyncio.sleep(next_t - now)
                next_t += period
                body = json.dumps({
                    "user": f"u{(cid * per_conn + j) % 2000}",
                    "num": 10}).encode()
                status, dt = await _fleet_request(reader, writer, body,
                                                 path=path)
                # EVERY response is recorded (shed/offered accounting
                # must see first requests too — the stage-boundary herd
                # is exactly when sheds happen); the True flag marks a
                # connection's first request so only the LATENCY sample
                # excludes its connect + herd transient
                results.append((status, dt, j == 0))
        finally:
            writer.close()

    await asyncio.gather(*[one(c) for c in range(conns)])


def bench_fleet(budget_s: float) -> dict:
    """Serving-fleet leg: N real worker processes behind the
    continuous-batching scheduler, measured in two sub-legs.

    1. **Goodput burst** (no dispatch floor): closed-loop clients
       against every worker at once → ``fleet_qps`` /
       ``fleet_qps_per_worker`` — the REAL per-process serving
       capacity the capacity model (obs/capacity.py) learns from.
    2. **Scheduler ramp** (``fleet_dispatch_floor_ms`` simulated
       per-dispatch device wall — the fixed cost that makes fusing a
       deeper queue win on a real accelerator): open-loop offered-rate
       stages. Queue-depth-adaptive batching must demonstrably engage
       (``fleet_batch_p50`` over the PEAK stage's dispatches, from the
       workers' scraped ``pio_serve_batch_size`` deltas) while p99
       stays flat across the ramp (``fleet_p99_flat_x`` =
       peak-stage p99 / first-stage p99), with zero steady-state
       recompiles (``fleet_recompiles_steady`` — compile-cache gauge
       delta across the peak stage). A final over-saturation burst
       lets the SLO shed path engage (``fleet_shed_rate``).

    Guarded like bench_shard: any failure nulls the fleet_* keys,
    never the record."""
    import asyncio

    out = dict.fromkeys(FLEET_KEYS)
    # the full leg costs ~60-90 s on a quiet box (2 spawn rounds + warm
    # + 3 ramp stages + overload); the floor leaves real margin and the
    # leg DEADLINE below bounds every wait so a loaded box cannot eat
    # the supervised child's window (the bench_shard discipline)
    if budget_s < 180.0:
        log("fleet leg skipped: bench deadline too close")
        return out
    leg_deadline = time.monotonic() + min(
        budget_s - 60.0,
        float(os.environ.get("PIO_BENCH_FLEET_TIMEOUT_S", "300")))

    def left(cap: float) -> float:
        return max(min(cap, leg_deadline - time.monotonic()), 5.0)
    n_workers = int(os.environ.get("PIO_BENCH_FLEET_WORKERS", "2"))
    # floor 500 ms keeps the batch-linear host work (parse + render,
    # ~1 ms/query on the CPU sim) small next to the simulated dispatch
    # wall at every ramp stage, so the p99-flatness measurement
    # reflects the scheduler, not CPU render costs growing with batch
    floor_ms = float(os.environ.get("PIO_BENCH_FLEET_FLOOR_MS", "500"))
    # peak sized for sustained queue depth ≈ rate × floor ≈ 80 (> the
    # old fixed 64 with margin) while staying under the host's
    # admission knee, where tail waits would jump a whole extra
    # dispatch cycle and the flatness figure would measure host
    # contention instead of the scheduler
    ramp = [float(r) for r in os.environ.get(
        "PIO_BENCH_FLEET_RAMP_RPS", "60,100,160").split(",") if r]
    stage_s = float(os.environ.get("PIO_BENCH_FLEET_STAGE_S", "10"))
    #: per-connection send period for the open-loop generators: must
    #: dominate the worst-case RTT (several dispatch floors) or slow
    #: responses bunch the offered schedule (coordinated omission) —
    #: but not much more, since conns = rate × period and a huge conn
    #: count makes the generator itself the bottleneck on small boxes
    period_s = max(2.0, 4.0 * floor_ms / 1000.0)
    out["fleet_workers"] = n_workers
    out["fleet_dispatch_floor_ms"] = floor_ms

    # -- sub-leg 1: goodput burst (real dispatch cost, no floor) ------------
    # run the SAME closed-loop burst against a recorder-off baseline
    # fleet and then the production config (recorder sampling at 1 Hz +
    # histogram trace exemplars — both on by default): the p99 ratio is
    # the flight recorder's serving-overhead pin (≤ 1.1×, asserted in
    # test_bench_e2e). Two measured bursts per config with a min-p99
    # reduction: scheduler noise on a shared box only ever INFLATES a
    # p99, so the min of repeated measurements is the honest estimate
    # of each config's floor — applied symmetrically to both configs.
    recorder_cfgs = (
        ("off", {"PIO_RECORDER": "0", "PIO_EXEMPLARS": "0"}),
        ("on", {"PIO_RECORDER": "1", "PIO_EXEMPLARS": "1"}),
    )
    p99_by_cfg: dict = {}
    for cfg_name, cfg_env in recorder_cfgs:
        workers = _fleet_spawn(n_workers, floor_ms=0.0,
                               extra_env=cfg_env)
        try:
            # untimed warm mini-burst: connects + kernel caches settle
            results: list = []

            async def warm_burst() -> None:
                await asyncio.gather(*[
                    _fleet_closed_loop(port, 16, 5, results)
                    for _proc, port in workers])

            asyncio.run(asyncio.wait_for(warm_burst(),
                                         timeout=left(60.0)))
            p99s = []
            for _rep in range(2):
                results = []
                t0 = time.perf_counter()

                async def burst() -> None:
                    await asyncio.gather(*[
                        _fleet_closed_loop(port, 64, 25, results)
                        for _proc, port in workers])

                asyncio.run(asyncio.wait_for(burst(),
                                             timeout=left(120.0)))
                wall = time.perf_counter() - t0
                served = [d for s, d, _f in results if s == 200]
                if served:
                    p99s.append(_stage_p99(served))
                if cfg_name == "on":
                    # the headline capacity figures come from the
                    # PRODUCTION config (recorder on), best rep
                    qps = round(len(served) / wall, 1)
                    if out["fleet_qps"] is None or qps > out["fleet_qps"]:
                        out["fleet_qps"] = qps
                        out["fleet_qps_per_worker"] = round(
                            len(served) / wall / n_workers, 1)
            if p99s:
                p99_by_cfg[cfg_name] = min(p99s)
        finally:
            _fleet_teardown(workers)
    if p99_by_cfg.get("off") and p99_by_cfg.get("on"):
        out["recorder_overhead_p99_x"] = round(
            p99_by_cfg["on"] / p99_by_cfg["off"], 3)

    # -- sub-leg 2: scheduler ramp against the simulated dispatch wall ------
    workers = _fleet_spawn(n_workers, floor_ms=floor_ms)
    try:
        # untimed warm pass at the base rate: the rung ladder and the
        # EWMA dispatch wall settle BEFORE the first measured stage, so
        # the flatness baseline is steady-state behavior, not the
        # adaptation transient
        results = []

        async def warm() -> None:
            await asyncio.gather(*[
                _fleet_open_loop(port, ramp[0], 3.0, results,
                                 period_s=period_s)
                for _proc, port in workers])

        asyncio.run(asyncio.wait_for(warm(), timeout=left(60.0)))
        stage_p99: list = []
        shed_total = 0
        offered_total = 0
        peak_batch_p50 = None
        recompiles = None
        for si, rate in enumerate(ramp):
            peak = si == len(ramp) - 1
            if peak:
                pre = [_fleet_scrape(port) for _p, port in workers]
                h0 = [h for h, _c in pre]
                c0 = sum(c for _h, c in pre)
            results = []

            async def stage() -> None:
                await asyncio.gather(*[
                    _fleet_open_loop(port, rate, stage_s, results,
                                     period_s=period_s)
                    for _proc, port in workers])

            asyncio.run(asyncio.wait_for(
                stage(), timeout=left(max(6 * stage_s, 60.0))))
            # completion order ≈ time order: the sub-window estimator
            # wants the stage's chronology, not a sorted tail. Latency
            # samples exclude first-per-connection transients; the
            # shed/offered tallies count EVERYTHING.
            served = [d for s, d, f in results if s == 200 and not f]
            shed_total += sum(1 for s, _d, _f in results if s == 503)
            offered_total += len(results)
            if served:
                stage_p99.append(_stage_p99(served))
            if peak:
                post = [_fleet_scrape(port) for _p, port in workers]
                h1 = [h for h, _c in post]
                c1 = sum(c for _h, c in post)
                merged: dict = {}
                for a, b in zip(h0, h1):
                    for le, v in b.items():
                        merged[le] = merged.get(le, 0.0) \
                            + v - a.get(le, 0.0)
                peak_batch_p50 = _bucket_quantile(merged, 0.5)
                recompiles = int(c1 - c0)
                if served:
                    # the headline figures use the same robust stage
                    # estimator as the flatness ratio
                    out["fleet_p99_s"] = round(stage_p99[-1], 4)
                    out["fleet_p50_ms"] = round(
                        float(np.median(served)) * 1e3, 1)
        # over-saturation burst: give the shed path real pressure
        results = []

        async def overload() -> None:
            await asyncio.gather(*[
                _fleet_open_loop(port, 4 * ramp[-1], 3.0, results,
                                 period_s=period_s)
                for _proc, port in workers])

        try:
            if time.monotonic() < leg_deadline:
                asyncio.run(asyncio.wait_for(overload(),
                                             timeout=left(90.0)))
        except asyncio.TimeoutError:
            pass
        shed_total += sum(1 for s, _d, _f in results if s == 503)
        offered_total += len(results)
        out["fleet_p99_ramp_s"] = [round(p, 4) for p in stage_p99]
        out["fleet_offered_rps_ramp"] = ramp
        if len(stage_p99) >= 2 and stage_p99[0] > 0:
            out["fleet_p99_flat_x"] = round(
                stage_p99[-1] / stage_p99[0], 3)
        out["fleet_batch_p50"] = (round(peak_batch_p50, 1)
                                  if peak_batch_p50 else None)
        out["fleet_recompiles_steady"] = recompiles
        out["fleet_shed_total"] = shed_total
        out["fleet_shed_rate"] = round(
            shed_total / max(offered_total, 1), 4)
    finally:
        _fleet_teardown(workers)

    # -- incident stage: over-saturation with the recorder ON must land
    # ONE validated bundle autonomously ------------------------------------
    # A dedicated 2-worker set tuned so the breach is DETERMINISTIC:
    # shed disabled (the shed path was proven above; this stage's job
    # is the capture plane) and a planted sub-microsecond serve_p99
    # objective, so EVERY served query is a bad observation → the
    # worker's own SLO engine (armed by the recorder route +
    # PIO_INCIDENT_DIR) crosses fast burn within a recorder tick and
    # the capture engine freezes the bundle with zero bench-side help.
    if time.monotonic() + 60.0 < leg_deadline:
        import tempfile

        inc_dir = tempfile.mkdtemp(prefix="pio_bench_incidents_")
        workers = _fleet_spawn(2, floor_ms=0.0, extra_env={
            "PIO_INCIDENT_DIR": inc_dir,
            "PIO_RECORDER": "1",
            "PIO_RECORDER_HZ": "5",
            "PIO_SERVE_SHED": "0",
            "PIO_SLO_SERVE_P99_S": "0.000001",
            "PIO_INCIDENT_COOLDOWN_S": "300",
        })
        try:
            results = []

            async def breach_load() -> None:
                await asyncio.gather(*[
                    _fleet_closed_loop(port, 8, 10, results)
                    for _proc, port in workers])

            asyncio.run(asyncio.wait_for(breach_load(),
                                         timeout=left(90.0)))
            bundle_path = None
            poll_until = min(time.monotonic() + 25.0, leg_deadline)
            while time.monotonic() < poll_until:
                found = sorted(f for f in os.listdir(inc_dir)
                               if f.endswith(".json"))
                if found:
                    bundle_path = os.path.join(inc_dir, found[0])
                    break
                time.sleep(0.5)
            captured = False
            if bundle_path is not None:
                # the artifact must also pass the report tool's schema
                # gate — a bundle nobody can render is not a capture
                check = subprocess.run(
                    [sys.executable,
                     os.path.join(os.path.dirname(
                         os.path.abspath(__file__)), "scripts",
                         "incident_report.py"),
                     bundle_path, "--check"],
                    capture_output=True, timeout=60)
                captured = check.returncode == 0
            out["fleet_incident_captured"] = captured
        except Exception as e:  # noqa: BLE001 — leg guard, never the record
            log(f"fleet incident stage failed: {e}")
        finally:
            _fleet_teardown(workers)
    else:
        log("fleet incident stage skipped: leg deadline too close")

    log(f"fleet: {n_workers} workers qps={out['fleet_qps']} "
        f"batch_p50={out['fleet_batch_p50']} "
        f"p99_flat={out['fleet_p99_flat_x']}x "
        f"shed_rate={out['fleet_shed_rate']} "
        f"recompiles={out['fleet_recompiles_steady']} "
        f"recorder_overhead={out['recorder_overhead_p99_x']}x "
        f"incident={out['fleet_incident_captured']}")
    return out


#: fleet front-door leg (docs/production.md "Fleet front door"): the
#: health-checked router proven ADVERSARIALLY — a worker killed
#: mid-ramp, a warm-cache worker joined mid-ramp, and one rolling
#: fleet-wide reload mid-traffic, with zero non-shed 5xx and zero
#: drain drops as the acceptance bars
FRONTDOOR_KEYS = (
    "frontdoor_workers", "frontdoor_qps", "frontdoor_p99_ramp_s",
    "frontdoor_offered_rps_ramp", "frontdoor_p99_flat_x",
    "frontdoor_nonshed_5xx", "frontdoor_shed_total",
    "frontdoor_retries", "frontdoor_reloaded", "frontdoor_drain_dropped",
    "frontdoor_join_cold_s", "frontdoor_join_warm_s",
    "frontdoor_join_to_first_dispatch_s",
)


def _frontdoor_spawn(seed: int, cache_dir: str, chaos: str = "",
                     max_batch: int = 512, floor_ms: float = 0.0):
    """One serve-mode worker wired to the FLEET-SHARED persistent XLA
    compile cache → (proc, port, warm_s). The min-compile-time floor is
    zeroed so even the CPU sim's fast ladder compiles populate the
    cache — the join pre-warm delta stays measurable off-TPU."""
    env = _fleet_worker_env(floor_ms)
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0.0"
    worker_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tests", "fleet_worker.py")
    cmd = [sys.executable, worker_py, "--mode", "serve",
           "--seed", str(seed), "--max-batch", str(max_batch),
           "--dispatch-floor-ms", str(floor_ms),
           "--compile-cache", cache_dir]
    if chaos:
        cmd += ["--chaos", chaos]
    proc = subprocess.Popen(cmd, env=env, stdin=subprocess.PIPE,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)
    try:
        port, warm_s = _await_port(proc, time.monotonic() + 120.0)
    except RuntimeError:
        _fleet_teardown([(proc, None)])
        raise
    return proc, port, warm_s


def bench_frontdoor(budget_s: float) -> dict:
    """Fleet front-door leg: one address over real worker processes,
    chaos-proven. The ramp runs THROUGH the front door while the leg
    injects every fault the router must absorb:

    - stage 1: steady baseline (the p99 denominator);
    - stage 2: a rolling fleet-wide ``/reload`` fires mid-traffic
      (drain → warm-before-swap → re-admit, one worker at a time), and
      the victim worker hard-exits on its own ``--chaos kill-after``
      timer (in-flight connection resets — the single-retry path);
      the moment the victim dies a REPLACEMENT worker is spawned
      against the fleet-shared compile cache and joined mid-traffic
      (``frontdoor_join_to_first_dispatch_s`` = spawn → its first
      routed query);
    - stage 3: the healed fleet at the same offered rate (recovery
      must hold, not just survive the transient).

    Bars: ``frontdoor_nonshed_5xx`` == 0 (every failure either retried
    to a healthy peer or shed with the 503 + Retry-After contract),
    ``frontdoor_drain_dropped`` == 0 (rolling reload drops nothing),
    ``frontdoor_p99_flat_x`` ≤ 1.5 across the chaos. The cold/warm
    ladder-warmup delta off the shared cache is recorded
    (``frontdoor_join_cold_s`` vs ``frontdoor_join_warm_s``).

    Guarded like bench_fleet: any failure nulls the frontdoor_* keys,
    never the record."""
    import asyncio
    import shutil
    import tempfile
    import threading

    from incubator_predictionio_tpu.serving.frontdoor import (
        FrontDoor,
        FrontDoorConfig,
    )

    out = dict.fromkeys(FRONTDOOR_KEYS)
    if budget_s < 120.0:
        log("frontdoor leg skipped: bench deadline too close")
        return out
    leg_deadline = time.monotonic() + min(
        budget_s - 45.0,
        float(os.environ.get("PIO_BENCH_FRONTDOOR_TIMEOUT_S", "240")))

    def left(cap: float) -> float:
        return max(min(cap, leg_deadline - time.monotonic()), 5.0)

    # a FLAT offered rate across the stages: bench_fleet already pins
    # p99-vs-load, so holding load constant makes the flatness ratio
    # measure CHAOS alone (stage 1 = quiet baseline, stages 2-3 =
    # kill + join + rolling reload at the same offered rate)
    ramp = [float(r) for r in os.environ.get(
        "PIO_BENCH_FRONTDOOR_RAMP_RPS", "100,100,100").split(",") if r]
    stage_s = float(os.environ.get("PIO_BENCH_FRONTDOOR_STAGE_S", "8"))
    # a small simulated dispatch floor makes per-query latency
    # deterministic (floor-dominated) instead of scheduler-jitter-
    # dominated, so the p99 ratio resolves chaos, not CPU noise
    floor_ms = float(os.environ.get("PIO_BENCH_FRONTDOOR_FLOOR_MS", "25"))
    cache_dir = tempfile.mkdtemp(prefix="pio-frontdoor-cache-")
    workers = []   # (proc, port) for teardown
    fd = None
    # join_thread races the finally-block teardown: the replacement
    # worker must either land in `workers` BEFORE teardown iterates it
    # or not spawn at all — otherwise an early stage failure leaks a
    # jax subprocess into the rest of the bench run
    spawn_lock = threading.Lock()
    leg_done = threading.Event()
    try:
        # worker A cold (fresh shared cache), worker B warm from A's
        # compiles; B is the VICTIM — its kill-after timer (armed at
        # its own serving start) lands ~0.6 into stage 2
        kill_after = 3.0 + 1.6 * stage_s + 1.0
        proc_a, port_a, warm_cold = _frontdoor_spawn(
            0, cache_dir, floor_ms=floor_ms)
        workers.append((proc_a, port_a))
        proc_b, port_b, warm_warm = _frontdoor_spawn(
            1, cache_dir, chaos=f"kill-after={kill_after:.1f}",
            floor_ms=floor_ms)
        workers.append((proc_b, port_b))
        out["frontdoor_join_cold_s"] = round(warm_cold, 3)
        out["frontdoor_join_warm_s"] = round(warm_warm, 3)
        out["frontdoor_workers"] = 2

        fd = FrontDoor(
            [("127.0.0.1", port_a), ("127.0.0.1", port_b)],
            FrontDoorConfig(request_timeout_s=8.0, attempt_timeout_s=3.0,
                            probe_interval_s=0.5, open_cooldown_s=1.0))
        fport = fd.start_background()

        results: list = []
        reload_out: dict = {}
        join_out: dict = {}

        def reload_thread() -> None:
            time.sleep(0.5)  # let stage 2 traffic establish first
            try:
                reload_out.update(fd.rolling_reload(timeout=left(120.0)))
            except Exception as e:  # noqa: BLE001 — nulls the keys
                log(f"frontdoor rolling reload failed ({e!r})")

        def join_thread() -> None:
            # the elasticity path: the moment the victim dies, spawn a
            # replacement against the WARM shared cache and measure
            # spawn → first query the front door routes to it
            proc_b.wait()
            t0 = time.perf_counter()
            with spawn_lock:
                if leg_done.is_set():
                    return  # teardown already ran; don't leak a worker
                try:
                    proc_c, port_c, _w = _frontdoor_spawn(
                        2, cache_dir, floor_ms=floor_ms)
                except Exception as e:  # noqa: BLE001
                    log(f"frontdoor join worker failed to spawn ({e!r})")
                    return
                workers.append((proc_c, port_c))
            name = fd.add_worker("127.0.0.1", port_c)
            while time.monotonic() < leg_deadline:
                served = next(
                    (w["requests"] for w in fd.stats()["workers"]
                     if w["name"] == name), 0)
                if served > 0:
                    join_out["join_s"] = time.perf_counter() - t0
                    return
                time.sleep(0.05)

        # untimed warm pass: ladder rungs + EWMA walls settle before
        # the measured baseline (every response still counts toward
        # the 5xx/shed tallies — chaos accounting is total)
        async def run_stage(rate: float, dur: float) -> None:
            await _fleet_open_loop(fport, rate, dur, results,
                                   period_s=2.0)

        asyncio.run(asyncio.wait_for(run_stage(ramp[0], 3.0),
                                     timeout=left(60.0)))
        warm_end = len(results)  # qps counts measured stages only
        stage_p99: list = []
        chaos_threads: list = []
        stage_walls = 0.0
        for si, rate in enumerate(ramp):
            if si == 1:
                for fn in (reload_thread, join_thread):
                    t = threading.Thread(target=fn, daemon=True)
                    t.start()
                    chaos_threads.append(t)
            stage_results_start = len(results)
            t_stage = time.perf_counter()
            asyncio.run(asyncio.wait_for(
                run_stage(rate, stage_s),
                timeout=left(max(6 * stage_s, 60.0))))
            stage_walls += time.perf_counter() - t_stage
            served = [d for s, d, f in results[stage_results_start:]
                      if s == 200 and not f]
            if served:
                stage_p99.append(_stage_p99(served))
        for t in chaos_threads:
            t.join(timeout=left(60.0))

        ok_total = sum(1 for s, _d, _f in results[warm_end:] if s == 200)
        out["frontdoor_qps"] = round(ok_total / max(stage_walls, 1e-9), 1)
        out["frontdoor_p99_ramp_s"] = [round(p, 4) for p in stage_p99]
        out["frontdoor_offered_rps_ramp"] = ramp
        if len(stage_p99) >= 2 and stage_p99[0] > 0:
            out["frontdoor_p99_flat_x"] = round(
                max(stage_p99[1:]) / stage_p99[0], 3)
        out["frontdoor_nonshed_5xx"] = sum(
            1 for s, _d, _f in results if s >= 500 and s != 503)
        out["frontdoor_shed_total"] = sum(
            1 for s, _d, _f in results if s == 503)
        out["frontdoor_retries"] = fd.counts["retries"]
        out["frontdoor_reloaded"] = reload_out.get("reloaded")
        out["frontdoor_drain_dropped"] = reload_out.get("dropped")
        if "join_s" in join_out:
            out["frontdoor_join_to_first_dispatch_s"] = round(
                join_out["join_s"], 2)
    finally:
        with spawn_lock:
            leg_done.set()
        if fd is not None:
            fd.stop()
        _fleet_teardown(workers)
        shutil.rmtree(cache_dir, ignore_errors=True)
    log(f"frontdoor: p99_flat={out['frontdoor_p99_flat_x']}x "
        f"nonshed_5xx={out['frontdoor_nonshed_5xx']} "
        f"drain_dropped={out['frontdoor_drain_dropped']} "
        f"retries={out['frontdoor_retries']} "
        f"join={out['frontdoor_join_to_first_dispatch_s']}s "
        f"(warmup cold={out['frontdoor_join_cold_s']}s "
        f"warm={out['frontdoor_join_warm_s']}s)")
    return out


TENANT_KEYS = (
    "tenant_workers", "tenant_victim_solo_p99_s",
    "tenant_victim_flood_p99_s", "tenant_victim_p99_x",
    "tenant_victim_shed_rate", "tenant_aggressor_shed_total",
    "tenant_aggressor_shed_rate", "tenant_isolation",
    "tenant_reload_nonshed_5xx", "tenant_reloaded",
)


#: stage-B aggressor flood driver for bench_tenants — run as a
#: SEPARATE stdlib-only subprocess (``python -c``) so the flood
#: generator never shares an event loop, a GIL, or an import graph
#: with the victim's timing loop. Params via env (FLOOD_TARGETS,
#: FLOOD_PATH, FLOOD_CLIENTS, FLOOD_BACKOFF_S); floods keep-alive
#: closed-loop with a shed backoff until SIGTERM, then prints its
#: {total, shed, other} counts as one JSON line and exits.
_TENANT_FLOOD_SRC = r"""
import asyncio, json, os, signal, sys

targets = [t.rsplit(":", 1)
           for t in os.environ["FLOOD_TARGETS"].split(",")]
path = os.environ["FLOOD_PATH"]
clients = int(os.environ["FLOOD_CLIENTS"])
backoff = float(os.environ["FLOOD_BACKOFF_S"])
counts = {"total": 0, "shed": 0, "other": 0}


async def one(cid, stop):
    host, port = targets[cid % len(targets)]
    reader = writer = None
    j = 0
    while not stop.is_set():
        try:
            if writer is None:
                reader, writer = await asyncio.open_connection(
                    host, int(port))
            body = json.dumps({"user": "u%d" % ((cid * 977 + j) % 2000),
                               "num": 10}).encode()
            j += 1
            writer.write(("POST %s HTTP/1.1\r\nHost: bench\r\n"
                          "Content-Type: application/json\r\n"
                          "Content-Length: %d\r\n\r\n"
                          % (path, len(body))).encode() + body)
            await writer.drain()
            line = await reader.readline()
            if not line:
                raise ConnectionError("closed")
            status = int(line.split()[1])
            clen = 0
            while True:
                h = await reader.readline()
                if h in (b"\r\n", b"", b"\n"):
                    break
                if h.lower().startswith(b"content-length:"):
                    clen = int(h.split(b":", 1)[1])
            if clen:
                await reader.readexactly(clen)
            counts["total"] += 1
            if status == 503:
                counts["shed"] += 1
                await asyncio.sleep(backoff)
            elif status != 200:
                counts["other"] += 1
        except asyncio.CancelledError:
            break
        except Exception:
            if writer is not None:
                try:
                    writer.close()
                except Exception:
                    pass
            reader = writer = None
            await asyncio.sleep(0.1)


async def main():
    stop = asyncio.Event()
    asyncio.get_running_loop().add_signal_handler(
        signal.SIGTERM, stop.set)
    tasks = [asyncio.create_task(one(c, stop)) for c in range(clients)]
    await stop.wait()
    for t in tasks:
        t.cancel()
    await asyncio.gather(*tasks, return_exceptions=True)
    print(json.dumps(counts))
    sys.stdout.flush()


asyncio.run(main())
"""


def bench_tenants(budget_s: float) -> dict:
    """Multi-tenant noisy-neighbor leg: two co-resident tenants on a
    real 2-worker fleet behind the front door, per-tenant accessKey
    auth end to end (serving/tenancy.py).

    - stage A: the VICTIM tenant alone at a modest open-loop rate —
      its solo p99 is the denominator;
    - stage B: the same victim rate while the AGGRESSOR tenant floods
      closed-loop past its admission quota. Weighted-fair dispatch +
      per-tenant quota shedding mean the aggressor sheds ITS OWN
      traffic (503 + Retry-After) while the victim's p99 stays inside
      its own objective;
    - stage C: a TENANT-SCOPED rolling reload of the aggressor's
      deploy fires mid-victim-traffic (``/reload?tenant=aggressor``
      through the front door's drain choreography) — the victim keeps
      serving with zero non-shed 5xx.

    Bars (tests/test_bench_e2e.py): ``tenant_victim_p99_x`` ≤ 1.5,
    ``tenant_victim_shed_rate`` == 0, ``tenant_isolation`` is True
    (aggressor shed > 0 AND victim shed == 0, from the workers' own
    per-tenant /status blocks), ``tenant_reload_nonshed_5xx`` == 0.
    Guarded like bench_fleet: any failure nulls the tenant_* keys,
    never the record."""
    import asyncio
    import threading
    import urllib.request

    from incubator_predictionio_tpu.serving import tenancy
    from incubator_predictionio_tpu.serving.frontdoor import (
        FrontDoor,
        FrontDoorConfig,
    )

    out = dict.fromkeys(TENANT_KEYS)
    if budget_s < 120.0:
        log("tenants leg skipped: bench deadline too close")
        return out
    leg_deadline = time.monotonic() + min(
        budget_s - 45.0,
        float(os.environ.get("PIO_BENCH_TENANT_TIMEOUT_S", "240")))

    def left(cap: float) -> float:
        return max(min(cap, leg_deadline - time.monotonic()), 5.0)

    stage_s = float(os.environ.get("PIO_BENCH_TENANT_STAGE_S", "8"))
    # same rationale as bench_frontdoor: a simulated dispatch floor
    # makes per-query latency floor-dominated, so the victim's p99
    # ratio resolves ISOLATION, not CPU scheduling noise
    floor_ms = float(os.environ.get("PIO_BENCH_TENANT_FLOOR_MS", "25"))
    victim_rps = float(os.environ.get(
        "PIO_BENCH_TENANT_VICTIM_RPS", "60"))
    flood_clients = int(os.environ.get(
        "PIO_BENCH_TENANT_FLOOD_CLIENTS", "12"))
    # the tenant registry BOTH planes parse: the workers admit/shed by
    # it, and the in-process front door authenticates against it. The
    # aggressor's quota is far below its closed-loop concurrency so
    # the flood sheds at admission; the victim's weight buys it the
    # dispatch tie-break under contention.
    spec = ("victim:bench-victim-key:weight=8;"
            "aggressor:bench-aggressor-key:weight=1,quota=2")
    vpath = b"/queries.json?accessKey=bench-victim-key"
    apath = b"/queries.json?accessKey=bench-aggressor-key"

    prev_spec = os.environ.get("PIO_TENANTS")
    os.environ["PIO_TENANTS"] = spec
    tenancy.reset_registry()
    workers = []
    fd = None
    try:
        # 3 dispatcher threads per worker: the floor-padded dispatches
        # sleep, so extra threads hide a victim dispatch behind the
        # aggressor's in-flight one (the documented device-path use of
        # the knob). With the scheduler's weighted slot caps the
        # aggressor holds at most ceil(3·1/9)=1 slot, so the victim
        # keeps ≥2 concurrent slots under flood — the same headroom
        # its solo baseline enjoys — instead of eating a full
        # in-flight flood dispatch before its own turn
        workers = _fleet_spawn(2, floor_ms,
                               extra_env={"PIO_TENANTS": spec,
                                          "PIO_SERVE_WORKERS": "3"})
        out["tenant_workers"] = len(workers)
        fd = FrontDoor(
            [("127.0.0.1", port) for _proc, port in workers],
            FrontDoorConfig(request_timeout_s=8.0, attempt_timeout_s=3.0,
                            probe_interval_s=0.5, open_cooldown_s=1.0))
        fport = fd.start_background()

        # untimed warm pass: ladder rungs + EWMA walls settle before
        # the measured solo baseline
        asyncio.run(asyncio.wait_for(
            _fleet_open_loop(fport, victim_rps, 3.0, [], path=vpath),
            timeout=left(60.0)))

        # stage A: victim solo baseline
        solo: list = []
        asyncio.run(asyncio.wait_for(
            _fleet_open_loop(fport, victim_rps, stage_s, solo,
                             path=vpath),
            timeout=left(max(6 * stage_s, 60.0))))

        # stage B: victim at the same rate + aggressor flood. The
        # flood runs in a SEPARATE dependency-free subprocess aimed
        # straight at the workers (not the in-process front door): on
        # a small box, flood coroutines sharing the bench event loop
        # would bill their own scheduling delay to the victim's
        # measured tail — the victim's p99 must resolve SERVER-side
        # isolation, not generator contention. The flood still crosses
        # the workers' accessKey auth and per-tenant quota admission;
        # stage B waits for shed evidence in the workers' /status
        # tenants blocks before the victim's measured pass begins.
        flood_v: list = []
        flood_counts: dict = {}
        flood_env = dict(os.environ)
        flood_env.update({
            "FLOOD_TARGETS": ",".join(
                f"127.0.0.1:{port}" for _proc, port in workers),
            "FLOOD_PATH": apath.decode("ascii"),
            "FLOOD_CLIENTS": str(flood_clients),
            "FLOOD_BACKOFF_S": "0.5",
        })
        flood_proc = subprocess.Popen(
            [sys.executable, "-c", _TENANT_FLOOD_SRC],
            env=flood_env, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL)
        try:
            ramp_deadline = time.monotonic() + left(20.0)
            while time.monotonic() < ramp_deadline:
                shed = 0
                for _proc, port in workers:
                    try:
                        with urllib.request.urlopen(
                                f"http://127.0.0.1:{port}/",
                                timeout=5) as resp:
                            info = json.loads(resp.read())
                        shed += int(((info.get("tenants") or {})
                                     .get("aggressor") or {})
                                    .get("shed") or 0)
                    except Exception:  # noqa: BLE001 — still ramping
                        pass
                if shed > 0:
                    break
                time.sleep(0.25)
            asyncio.run(asyncio.wait_for(
                _fleet_open_loop(fport, victim_rps, stage_s, flood_v,
                                 path=vpath),
                timeout=left(max(6 * stage_s, 60.0))))
        finally:
            flood_proc.terminate()
            try:
                flood_stdout, _ = flood_proc.communicate(timeout=15)
                flood_counts = json.loads(flood_stdout or b"{}")
            except Exception:  # noqa: BLE001 — counts are best-effort
                flood_proc.kill()
                flood_proc.wait(timeout=10)

        # stage C: tenant-scoped rolling reload of the AGGRESSOR mid-
        # victim-traffic — only the aggressor's co-resident deploy is
        # swapped; the victim rides the drain choreography untouched
        reload_out: dict = {}

        def reload_thread() -> None:
            time.sleep(0.5)  # let stage C traffic establish first
            try:
                reload_out.update(fd.rolling_reload(
                    timeout=left(120.0), tenant="aggressor"))
            except Exception as e:  # noqa: BLE001 — nulls the keys
                log(f"tenant rolling reload failed ({e!r})")

        reload_v: list = []
        t = threading.Thread(target=reload_thread, daemon=True)
        t.start()
        asyncio.run(asyncio.wait_for(
            _fleet_open_loop(fport, victim_rps, stage_s, reload_v,
                             path=vpath),
            timeout=left(max(6 * stage_s, 60.0))))
        t.join(timeout=left(60.0))

        solo_served = [d for s, d, f in solo if s == 200 and not f]
        flood_served = [d for s, d, f in flood_v
                        if s == 200 and not f]
        if solo_served and flood_served:
            p_solo = _stage_p99(solo_served)
            p_flood = _stage_p99(flood_served)
            out["tenant_victim_solo_p99_s"] = round(p_solo, 4)
            out["tenant_victim_flood_p99_s"] = round(p_flood, 4)
            if p_solo > 0:
                out["tenant_victim_p99_x"] = round(p_flood / p_solo, 3)
        vic_all = solo + flood_v + reload_v
        if vic_all:
            out["tenant_victim_shed_rate"] = round(
                sum(1 for s, _d, _f in vic_all if s == 503)
                / len(vic_all), 4)
        if flood_counts.get("total"):
            out["tenant_aggressor_shed_rate"] = round(
                flood_counts.get("shed", 0) / flood_counts["total"], 4)
        out["tenant_reload_nonshed_5xx"] = sum(
            1 for s, _d, _f in reload_v if s >= 500 and s != 503)
        out["tenant_reloaded"] = reload_out.get("reloaded")

        # scheduler-side isolation evidence: per-tenant shed totals
        # from each worker's own /status tenants block (the bounded-
        # registry figures the dashboard renders) — the aggressor shed,
        # the victim never did
        agg_shed = vic_shed = 0
        for _proc, port in workers:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/", timeout=10) as resp:
                info = json.loads(resp.read())
            blocks = info.get("tenants") or {}
            agg_shed += int((blocks.get("aggressor") or {})
                            .get("shed") or 0)
            vic_shed += int((blocks.get("victim") or {})
                            .get("shed") or 0)
        out["tenant_aggressor_shed_total"] = agg_shed
        if out["tenant_victim_shed_rate"] is not None:
            out["tenant_isolation"] = bool(
                agg_shed > 0 and vic_shed == 0
                and out["tenant_victim_shed_rate"] == 0)
    finally:
        if fd is not None:
            fd.stop()
        _fleet_teardown(workers)
        if prev_spec is None:
            os.environ.pop("PIO_TENANTS", None)
        else:
            os.environ["PIO_TENANTS"] = prev_spec
        tenancy.reset_registry()
    log(f"tenants: victim p99 {out['tenant_victim_solo_p99_s']}s solo "
        f"-> {out['tenant_victim_flood_p99_s']}s flooded "
        f"({out['tenant_victim_p99_x']}x), "
        f"victim shed_rate={out['tenant_victim_shed_rate']} "
        f"aggressor shed={out['tenant_aggressor_shed_total']} "
        f"isolation={out['tenant_isolation']} "
        f"reload 5xx={out['tenant_reload_nonshed_5xx']}")
    return out


#: self-driving freshness leg (docs/production.md "Self-driving
#: freshness"): the SLO-burn controller alone — zero human retrains —
#: holds fleet staleness under the declared bound across a compressed
#: serve-while-aging ramp, every action audit-trailed under a trace ID
#: that reaches the rolling-reload spans
CONTROLLER_KEYS = (
    "controller_workers", "controller_staleness_bound_s",
    "controller_staleness_max_s", "controller_staleness_held",
    "controller_actions", "controller_decision_to_fresh_s",
    "controller_false_triggers", "controller_trace_linked",
    "controller_evaluations",
)


def _controller_staleness(port: int):
    """One worker /metrics scrape → its pio_model_staleness_seconds
    reading (None when unscrapeable — a draining worker mid-reload)."""
    import urllib.request

    from incubator_predictionio_tpu.obs import expofmt

    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
            text = resp.read().decode()
    except Exception:
        return None
    _meta, samples = expofmt.parse_exposition(text)
    v = samples.get(("pio_model_staleness_seconds", frozenset()))
    return float(v) if v is not None else None


def bench_controller(budget_s: float) -> dict:
    """Self-driving freshness leg: two planted fleet workers behind the
    front door, the freshness controller (obs/controller.py) in ``act``
    mode over a COMPRESSED staleness bound, and NO human retrains. The
    controller consumes the fleet staleness gauge through the federated
    SLO engine, projects headroom, and must trigger its continuation-
    retrain + rolling-hot-swap choreography early enough that the
    sampled fleet-max staleness never crosses the bound
    (``controller_staleness_held``). Each action's decision record
    carries a trace ID; the leg verifies it reached the front door's
    reload hop (``controller_trace_linked``) — the audit-trail
    acceptance bar. ``controller_false_triggers`` counts actions fired
    while staleness was still under half the bound (none expected:
    hysteresis + the horizon rule exist to prevent exactly that).

    The retrain actuator here is a planted stand-in (the O(delta)
    continuation-retrain wall is bench_retrain's claim; this leg
    measures the CONTROL LOOP) and the model swap is the workers' real
    warm-before-swap ``/reload`` through the front door's rolling
    choreography. Guarded like the other fleet legs: any failure nulls
    the controller_* keys, never the record."""
    import asyncio
    import logging as _logging
    import threading

    from incubator_predictionio_tpu.obs import federate
    from incubator_predictionio_tpu.obs import slo as obs_slo
    from incubator_predictionio_tpu.obs.controller import (
        ControllerConfig,
        FreshnessController,
        http_reload_fn,
    )
    from incubator_predictionio_tpu.serving.frontdoor import (
        FrontDoor,
        FrontDoorConfig,
    )

    out = dict.fromkeys(CONTROLLER_KEYS)
    if budget_s < 120.0:
        log("controller leg skipped: bench deadline too close")
        return out
    leg_deadline = time.monotonic() + min(
        budget_s - 45.0,
        float(os.environ.get("PIO_BENCH_CONTROLLER_TIMEOUT_S", "180")))

    def left(cap: float) -> float:
        return max(min(cap, leg_deadline - time.monotonic()), 5.0)

    bound_s = float(os.environ.get("PIO_BENCH_CONTROLLER_BOUND_S", "10"))
    run_s = float(os.environ.get("PIO_BENCH_CONTROLLER_RUN_S", "30"))
    rate = float(os.environ.get("PIO_BENCH_CONTROLLER_RPS", "30"))
    out["controller_staleness_bound_s"] = bound_s

    workers = _fleet_spawn(2, floor_ms=0.0)
    fd = None
    ctl = None
    # defined before the try so the finally can always stop the
    # sampler: a mid-leg failure must not leak a daemon thread
    # scraping dead worker ports for the rest of the bench run
    sample_stop = threading.Event()
    sampler_t = None
    # in-process span capture: the trace-linkage bar needs the front
    # door's /reload span lines, which land on the pio.trace logger of
    # THIS process (the workers' spans live in their own stderr)
    spans: list = []

    class _SpanTap(_logging.Handler):
        def emit(self, record: _logging.LogRecord) -> None:
            try:
                spans.append(json.loads(record.getMessage()))
            except Exception:
                pass

    tap = _SpanTap()
    span_logger = _logging.getLogger("pio.trace")
    prev_level = span_logger.level
    span_logger.addHandler(tap)
    span_logger.setLevel(_logging.INFO)
    try:
        fd = FrontDoor(
            [("127.0.0.1", p) for _proc, p in workers],
            FrontDoorConfig(request_timeout_s=8.0,
                            attempt_timeout_s=3.0,
                            probe_interval_s=0.25,
                            drain_timeout_s=10.0,
                            reload_timeout_s=60.0))
        fport = fd.start_background()
        # initial deploy: the workers have been aging since their spawn
        # walls (ladder warmup), so swap in a fresh model before the
        # measured ramp — the run then starts the way a real deploy
        # does, and every staleness excursion the sampler sees is the
        # CONTROLLER's to prevent
        fd.rolling_reload(timeout=left(60.0))

        # the controller's fleet view: the two workers (staleness
        # gauge) plus the front door itself (client-observed
        # pio_query_latency_seconds — the serve_p99 objective evaluates
        # what clients saw through the door)
        targets = [federate.Target(f"w{i}",
                                   f"http://127.0.0.1:{p}/metrics")
                   for i, (_proc, p) in enumerate(workers)]
        targets.append(federate.Target(
            "frontdoor", f"http://127.0.0.1:{fport}/metrics"))
        engine = obs_slo.SLOEngine(
            specs=(
                obs_slo.SLOSpec(
                    name="staleness",
                    metric="pio_model_staleness_seconds",
                    threshold=bound_s, target=0.99, kind="gauge",
                    description="compressed bench staleness bound"),
                obs_slo.SLOSpec(
                    name="serve_p99",
                    metric="pio_query_latency_seconds",
                    threshold=0.25, target=0.99,
                    description="front-door-observed serving wall"),
            ),
            registry=federate.FleetRegistry(
                targets_fn=lambda: targets, max_age_s=0.1),
            min_tick_interval_s=0.0, export_gauges=False)

        def planted_retrain() -> str:
            # continuation-retrain stand-in: the O(delta) retrain wall
            # is bench_retrain's pinned claim; this leg measures the
            # control loop + swap choreography around it
            time.sleep(0.2)
            return "planted-continuation"

        ctl = FreshnessController(
            engine=engine,
            retrain_fn=planted_retrain,
            reload_fn=http_reload_fn(
                f"http://127.0.0.1:{fport}/reload", timeout_s=60.0),
            config=ControllerConfig(
                interval_s=0.5, breach_evals=2,
                cooldown_s=4.0, horizon_s=0.4 * bound_s, ring=1024),
            mode="act")
        ctl.start()

        # serve-while-aging ramp: open-loop load through the front door
        # while a sampler tracks the fleet-max staleness the whole time
        samples: list = []

        def sampler() -> None:
            while not sample_stop.is_set():
                vals = [_controller_staleness(p)
                        for _proc, p in workers]
                vals = [v for v in vals if v is not None]
                if vals:
                    samples.append((time.time(), max(vals)))
                sample_stop.wait(0.25)

        sampler_t = threading.Thread(target=sampler, daemon=True)
        sampler_t.start()
        results: list = []

        async def load() -> None:
            await _fleet_open_loop(fport, rate, run_s, results,
                                   period_s=2.0)

        asyncio.run(asyncio.wait_for(load(),
                                     timeout=left(max(4 * run_s, 60.0))))
        sample_stop.set()
        sampler_t.join(timeout=10)
        ctl.stop()

        stats = ctl.stats()
        actions = [d for d in ctl.decisions(limit=1024)
                   if d.get("kind") == "evaluation"
                   and (d.get("outcome") or {}).get("actuated")]
        out["controller_workers"] = len(workers)
        out["controller_actions"] = stats["actions"]
        out["controller_evaluations"] = sum(
            1 for d in ctl.decisions(limit=1024)
            if d.get("kind") == "evaluation")
        if samples:
            peak = max(v for _t, v in samples)
            out["controller_staleness_max_s"] = round(peak, 2)
            out["controller_staleness_held"] = bool(peak <= bound_s)
        # false trigger = an action fired while the fleet was
        # MEASURABLY still comfortably fresh (under half the bound) —
        # hysteresis and the horizon rule exist to make this zero. An
        # unscrapeable gauge (None: both workers mid-drain) is not
        # evidence of freshness, so it never counts as false
        out["controller_false_triggers"] = sum(
            1 for d in actions
            if (d.get("inputs") or {}).get("stalenessMaxS") is not None
            and d["inputs"]["stalenessMaxS"] < 0.5 * bound_s)
        # decision → fresh: decision wall stamp to the first staleness
        # sample showing the swap landed (fleet max back under the
        # trigger point)
        walls = []
        for d in actions:
            t0 = d["ts"]
            trigger_level = (d.get("inputs") or {}).get(
                "stalenessMaxS") or bound_s
            after = [(t, v) for t, v in samples if t > t0]
            for t, v in after:
                if v < min(trigger_level, 0.5 * bound_s):
                    walls.append(t - t0)
                    break
        if walls:
            out["controller_decision_to_fresh_s"] = round(
                float(np.median(walls)), 2)
        # audit-trail bar: every action's trace ID shows up on the
        # front door's /reload HTTP span — the CROSS-HOP evidence (the
        # controller's own controller.reload span would be emitted even
        # if header forwarding broke, so it deliberately does not
        # count; worker-side propagation is pinned in
        # tests/test_controller.py)
        if actions:
            linked = []
            for d in actions:
                tid = d["traceId"]
                linked.append(any(
                    s.get("traceId") == tid
                    and s.get("span") == "http.request"
                    and s.get("server") == "frontdoor"
                    and s.get("route") == "/reload"
                    for s in spans))
            out["controller_trace_linked"] = all(linked)
    finally:
        sample_stop.set()
        if sampler_t is not None:
            sampler_t.join(timeout=10)
        span_logger.removeHandler(tap)
        span_logger.setLevel(prev_level)
        if ctl is not None:
            ctl.stop()
        if fd is not None:
            fd.stop()
        _fleet_teardown(workers)
    log(f"controller: actions={out['controller_actions']} "
        f"staleness_max={out['controller_staleness_max_s']}s "
        f"(bound {bound_s}s, held={out['controller_staleness_held']}) "
        f"decision_to_fresh={out['controller_decision_to_fresh_s']}s "
        f"false_triggers={out['controller_false_triggers']} "
        f"trace_linked={out['controller_trace_linked']}")
    return out


KNOB_KEYS = (
    "knob_workers", "knob_evaluations", "knob_steps",
    "knob_converged", "knob_recall_final", "knob_false_adjustments",
    "knob_rollbacks", "knob_incident_ring", "knob_trace_linked",
)


def bench_knobs(budget_s: float) -> dict:
    """Self-tuning serving leg (docs/production.md "Self-tuning
    serving"): the knob controller (obs/knobs.py) in ``act`` mode over
    a COMPRESSED timeline, actuating through the REAL fleet seam — a
    front door fanning ``POST /knobs`` to two real worker
    subprocesses — while a planted world model drives the signals it
    reads.

    The planted scenario, in order:

    1. catalogue-growth ramp: the recall gauge sags as the planted
       catalogue "grows" under a fixed nprobe; every doubling the
       controller actuates claws part of it back. The controller must
       hill-climb ``PIO_SERVE_MIPS_NPROBE`` until recall clears the
       target again (``knob_converged``);
    2. traffic-mix flip: queue wait jumps while latency stays under
       the objective — the batch ladder cap must climb, and no knob
       may reverse a direction it committed to during the ramp
       (``knob_false_adjustments`` counts same-knob direction
       reversals: hysteresis + cooldown exist to make this zero);
    3. planted SLO breach INSIDE the newest step's cooldown: the burn
       engine's breach listener must trigger the audited rollback to
       last-known-good (``knob_rollbacks`` — exactly one), and the
       incident bundle frozen by the same breach must carry the knob
       decision ring (``knob_incident_ring``).

    The world model reads the controller's BELIEVED vector
    (``ctl.values()`` — belief commits only when the fan-out
    succeeded), so the feedback loop only closes through the real
    door→worker actuation path. ``knob_trace_linked`` holds when every
    actuated decision's trace ID shows up on the front door's /knobs
    HTTP span — the same cross-hop audit bar as the freshness leg.
    Guarded like the other fleet legs: any failure nulls the knob_*
    keys, never the record."""
    import logging as _logging
    import math
    import shutil
    import tempfile
    import threading

    from incubator_predictionio_tpu.obs import metrics as obs_metrics
    from incubator_predictionio_tpu.obs import slo as obs_slo
    from incubator_predictionio_tpu.obs.controller import export_ring_fn
    from incubator_predictionio_tpu.obs.knobs import (
        KnobConfig,
        KnobController,
        default_knobs,
        http_knobs_fn,
    )
    from incubator_predictionio_tpu.obs.recorder import (
        FlightRecorder,
        IncidentCapture,
    )
    from incubator_predictionio_tpu.serving.frontdoor import (
        FrontDoor,
        FrontDoorConfig,
    )

    out = dict.fromkeys(KNOB_KEYS)
    if budget_s < 120.0:
        log("knobs leg skipped: bench deadline too close")
        return out
    leg_deadline = time.monotonic() + min(
        budget_s - 45.0,
        float(os.environ.get("PIO_BENCH_KNOBS_TIMEOUT_S", "120")))

    workers = _fleet_spawn(2, floor_ms=0.0)
    fd = None
    cap = None
    inc_dir = tempfile.mkdtemp(prefix="pio_bench_knobinc_")
    spans: list = []

    class _SpanTap(_logging.Handler):
        def emit(self, record: _logging.LogRecord) -> None:
            try:
                spans.append(json.loads(record.getMessage()))
            except Exception:
                pass

    tap = _SpanTap()
    span_logger = _logging.getLogger("pio.trace")
    prev_level = span_logger.level
    span_logger.addHandler(tap)
    span_logger.setLevel(_logging.INFO)
    try:
        fd = FrontDoor(
            [("127.0.0.1", p) for _proc, p in workers],
            FrontDoorConfig(request_timeout_s=8.0,
                            attempt_timeout_s=3.0,
                            probe_interval_s=0.25))
        fport = fd.start_background()

        # the planted signal plane: a LOCAL registry + flight recorder
        # carrying exactly the input series the controller consumes in
        # production — the world model writes them, the controller only
        # ever reads them back through the recorder's window API
        reg = obs_metrics.Registry()
        lat_h = reg.histogram("pio_query_latency_seconds", "planted",
                              buckets=(0.05, 0.1, 0.25, 0.5, 1.0))
        queue_h = reg.histogram("pio_serve_queue_wait_seconds",
                                "planted",
                                buckets=(0.01, 0.05, 0.1, 0.25))
        reg.counter("pio_serve_shed_total", "planted")
        recall_g = reg.gauge("pio_serve_mips_recall", "planted")
        rec = FlightRecorder(registry=reg, hz=4.0, window_s=60.0)

        target, margin = 0.95, 0.02
        cooldown_s = 2.5
        ctl = KnobController(
            specs=default_knobs(),
            apply_fn=http_knobs_fn(f"http://127.0.0.1:{fport}/knobs",
                                   timeout_s=15.0),
            recorder_fn=lambda: rec,
            config=KnobConfig(interval_s=0.25, hysteresis_evals=2,
                              cooldown_s=cooldown_s, window_s=8.0,
                              ring=1024, recall_target=target,
                              recall_margin=margin),
            mode="act")

        engine = obs_slo.SLOEngine(
            specs=(obs_slo.SLOSpec(
                name="serve_p99",
                metric="pio_query_latency_seconds",
                threshold=0.25, target=0.99,
                description="compressed bench serving wall"),),
            registry=reg, min_tick_interval_s=0.0,
            export_gauges=False)
        ctl.install(engine)
        cap = IncidentCapture(directory=inc_dir, recorder=rec,
                              window_s=60.0, targets_fn=lambda: [],
                              knobs_fn=export_ring_fn(ctl))
        cap.install(engine)

        def world(phase: str, ramp: float) -> float:
            """One tick of the planted world → current recall. The
            catalogue ramp costs up to 0.12 recall at the default
            nprobe; every actuated doubling buys 0.04 back (capped
            under target+margin so a converged run never invites a
            step-down — a reversal would be a REAL flapping bug)."""
            nprobe = ctl.values()["PIO_SERVE_MIPS_NPROBE"]
            recall = min(target + 0.5 * margin,
                         0.97 - 0.12 * ramp
                         + 0.04 * math.log2(max(nprobe, 64) / 64.0))
            recall_g.set(recall)
            lat_h.observe(0.4 if phase == "breach" else 0.2, 50)
            queue_h.observe(0.15 if phase == "flip" else 0.01, 50)
            rec.sample_now()
            return recall

        def left() -> float:
            return leg_deadline - time.monotonic()

        # phase 1: catalogue-growth ramp (6 s), then hold until the
        # climb converges
        recall = 0.0
        t0 = time.monotonic()
        while left() > 30.0:
            recall = world("ramp", min((time.monotonic() - t0) / 6.0,
                                       1.0))
            ctl.evaluate_once()
            if time.monotonic() - t0 > 7.0 and recall >= target:
                break
            time.sleep(0.12)
        out["knob_recall_final"] = round(recall, 4)
        out["knob_converged"] = bool(
            recall >= target
            and ctl.values()["PIO_SERVE_MIPS_NPROBE"] > 64)

        # phase 2: traffic-mix flip — queue pressure with latency
        # still under the objective; exit on the ladder-cap step
        cap_before = ctl.values()["PIO_SERVE_MAX_BATCH"]
        t0 = time.monotonic()
        stepped = False
        while left() > 20.0 and time.monotonic() - t0 < 10.0:
            world("flip", 1.0)
            d = ctl.evaluate_once()
            if d.get("knob") == "max_batch" \
                    and (d.get("outcome") or {}).get("actuated"):
                stepped = True
                break
            time.sleep(0.12)
        # a baseline burn-engine snapshot BEFORE the planted breach:
        # the fast-window delta is measured against it
        engine.evaluate()

        # phase 3: planted breach INSIDE the fresh step's cooldown
        if stepped:
            t0 = time.monotonic()
            while left() > 10.0 and time.monotonic() - t0 < 5.0:
                world("breach", 1.0)
                engine.evaluate()      # breach → on_breach listeners
                d = ctl.evaluate_once()
                if d.get("action") == "rollback":
                    break
                time.sleep(0.12)
        stats = ctl.stats()
        out["knob_workers"] = len(workers)
        out["knob_rollbacks"] = stats["rollbacks"]
        if stepped and stats["rollbacks"] == 1:
            # the rollback restored the pre-step ladder cap but kept
            # the converged MIPS climb (last-known-good is the vector
            # the newest step departed from)
            assert ctl.values()["PIO_SERVE_MAX_BATCH"] == cap_before

        ring = list(reversed(ctl.decisions(limit=1024)))  # oldest first
        evaluations = [d for d in ring if d.get("kind") == "evaluation"]
        out["knob_evaluations"] = len(evaluations)
        acted = [d for d in evaluations
                 if (d.get("outcome") or {}).get("actuated")]
        steps = [d for d in acted
                 if d.get("action") in ("step_up", "step_down")]
        out["knob_steps"] = len(steps)
        # false adjustment = a knob stepping back against a direction
        # it committed to earlier in the SAME run (audited rollbacks
        # are deliberate reversals, so they don't count)
        reversals = 0
        last_dir: dict = {}
        for d in steps:
            sign = 1 if d["action"] == "step_up" else -1
            if last_dir.get(d["knob"], sign) != sign:
                reversals += 1
            last_dir[d["knob"]] = sign
        out["knob_false_adjustments"] = reversals
        # cross-hop audit bar: every actuated decision's trace ID on
        # the front door's /knobs HTTP span
        if acted:
            out["knob_trace_linked"] = all(
                any(s.get("traceId") == d["traceId"]
                    and s.get("span") == "http.request"
                    and s.get("server") == "frontdoor"
                    and s.get("route") == "/knobs"
                    for s in spans)
                for d in acted)
        # the breach-frozen bundle must carry the knob decision ring
        deadline = time.monotonic() + 10.0
        bundle = None
        while time.monotonic() < deadline:
            names = [n for n in os.listdir(inc_dir)
                     if n.endswith(".json")]
            if names:
                with open(os.path.join(inc_dir, sorted(names)[-1]),
                          encoding="utf-8") as f:
                    bundle = json.load(f)
                break
            time.sleep(0.25)
        if bundle is not None:
            out["knob_incident_ring"] = bool(
                any(d.get("action") in ("step_up", "step_down")
                    for d in bundle.get("knobs") or []))
    finally:
        span_logger.removeHandler(tap)
        span_logger.setLevel(prev_level)
        if cap is not None:
            cap.stop()
        if fd is not None:
            fd.stop()
        _fleet_teardown(workers)
        shutil.rmtree(inc_dir, ignore_errors=True)
    log(f"knobs: steps={out['knob_steps']} "
        f"converged={out['knob_converged']} "
        f"(recall_final={out['knob_recall_final']}) "
        f"false_adjustments={out['knob_false_adjustments']} "
        f"rollbacks={out['knob_rollbacks']} "
        f"incident_ring={out['knob_incident_ring']} "
        f"trace_linked={out['knob_trace_linked']}")
    return out


INGEST_KEYS = (
    "ingest_qps_single", "ingest_qps_sharded", "ingest_shards",
    "ingest_host_cpus",
    "ingest_replication_lag_p99_events",
    "ingest_soak_dropped_events", "ingest_soak_staleness_held",
)


def _ingest_append_qps(shards: int, n_threads: int = 4,
                       batches_per_thread: int = 10,
                       batch_events: int = 10_000) -> float:
    """Concurrent columnar append throughput (events/s) into a fresh
    cpplog store with ``shards`` writer shards. The same DAO call the
    REST batch fast path lands on; with >1 shard the per-shard native
    appends overlap because ctypes releases the GIL for the write."""
    import tempfile
    import threading

    import numpy as np

    from incubator_predictionio_tpu.data.storage import StorageClientConfig
    from incubator_predictionio_tpu.data.storage import cpplog
    from incubator_predictionio_tpu.data.storage.base import (
        IdTable,
        Interactions,
    )

    with tempfile.TemporaryDirectory(prefix="pio_bench_shingest_") as tmp:
        prev = os.environ.get("PIO_LOG_SHARDS")
        os.environ["PIO_LOG_SHARDS"] = str(shards)
        try:
            cfg = StorageClientConfig(parallel=False,
                                      properties={"PATH": tmp})
            client = cpplog.StorageClient(cfg)
            dao = cpplog.CppLogEvents(client, cfg, prefix="b_")
            dao.init(1)
        finally:
            if prev is None:
                os.environ.pop("PIO_LOG_SHARDS", None)
            else:
                os.environ["PIO_LOG_SHARDS"] = prev
        # pre-build every batch OUTSIDE the timed window (the generator
        # shares the core with the appends). Distinct users per thread
        # keep the key-hash spray busy on every shard.
        item_tab = IdTable.from_list([f"i{k}" for k in range(512)])
        rng = np.random.default_rng(7)
        work = []
        for t in range(n_threads):
            batches = []
            for b in range(batches_per_thread):
                users = [f"u{t}_{b}_{k}" for k in range(batch_events)]
                batches.append(Interactions(
                    user_idx=np.arange(batch_events, dtype=np.int32),
                    item_idx=rng.integers(
                        0, 512, batch_events).astype(np.int32),
                    values=np.ones(batch_events, np.float32),
                    user_ids=IdTable.from_list(users),
                    item_ids=item_tab))
            work.append(batches)

        errors: list = []

        def pump(batches) -> None:
            try:
                for inter in batches:
                    dao.insert_interactions(inter, 1)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=pump, args=(w,))
                   for w in work]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errors:
            raise errors[0]
        total = n_threads * batches_per_thread * batch_events
        got = dao.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating")
        assert len(got) == total, (len(got), total)
        client.close()
        return total / wall


def bench_ingest(budget_s: float) -> dict:
    """Planet-scale ingest leg (docs/production.md "Planet-scale
    ingest"): multi-writer sharded append throughput vs the single-
    writer baseline in the SAME run, follower replication lag under
    sustained leader writes, and an ingest soak — event POSTs sprayed
    by the IngestFrontDoor across two live event-server writers over a
    sharded log, with a rolling zero-downtime writer reload mid-stream
    and a tail subscriber holding the freshness bound. Guarded like the
    other fleet legs: any failure nulls the ingest_* keys, never the
    record."""
    import tempfile
    import threading
    import urllib.request

    import numpy as np

    out = dict.fromkeys(INGEST_KEYS)
    if budget_s < 90.0:
        log("ingest leg skipped: bench deadline too close")
        return out
    shards = int(os.environ.get("PIO_BENCH_INGEST_SHARDS", "4"))
    out["ingest_shards"] = shards
    # the sharded-vs-single ratio is a PARALLELISM measurement: on a
    # 1-core host the fan-out has no headroom by construction, so the
    # record carries the host's core count for honest downstream bars
    out["ingest_host_cpus"] = os.cpu_count() or 1

    # -- A. sharded vs single-writer append throughput --------------------
    out["ingest_qps_single"] = round(_ingest_append_qps(1), 1)
    out["ingest_qps_sharded"] = round(_ingest_append_qps(shards), 1)
    log(f"ingest append: single={out['ingest_qps_single']:.0f} ev/s "
        f"sharded({shards})={out['ingest_qps_sharded']:.0f} ev/s "
        f"({out['ingest_qps_sharded'] / out['ingest_qps_single']:.2f}x)")

    # -- B. async replication lag under sustained leader writes -----------
    from incubator_predictionio_tpu.data.storage import StorageClientConfig
    from incubator_predictionio_tpu.data.storage import cpplog
    from incubator_predictionio_tpu.data.storage.base import (
        IdTable,
        Interactions,
    )
    from incubator_predictionio_tpu.data.storage.server import (
        ReplicationTail,
        StorageServer,
    )

    with tempfile.TemporaryDirectory(prefix="pio_bench_repl_") as tmp:
        prev = os.environ.get("PIO_LOG_SHARDS")
        os.environ["PIO_LOG_SHARDS"] = str(shards)
        try:
            lcfg = StorageClientConfig(parallel=False,
                                       properties={"PATH": tmp + "/lead"})
            lclient = cpplog.StorageClient(lcfg)
            ldao = cpplog.CppLogEvents(lclient, lcfg, prefix="b_")
            ldao.init(1)
            fcfg = StorageClientConfig(parallel=False,
                                       properties={"PATH": tmp + "/foll"})
            fclient = cpplog.StorageClient(fcfg)
            fdao = cpplog.CppLogEvents(fclient, fcfg, prefix="b_")
        finally:
            if prev is None:
                os.environ.pop("PIO_LOG_SHARDS", None)
            else:
                os.environ["PIO_LOG_SHARDS"] = prev
        leader_srv = StorageServer(cpplog, lclient, lcfg,
                                   host="127.0.0.1", port=0)
        lport = leader_srv.start_background()
        tail = ReplicationTail(f"http://127.0.0.1:{lport}", fdao, [1],
                               interval_s=0.05, prefix="b_")
        tail.start()
        item_tab = IdTable.from_list([f"i{k}" for k in range(128)])
        stop_w = threading.Event()

        def writer() -> None:
            b = 0
            rng = np.random.default_rng(11)
            while not stop_w.is_set():
                n = 5_000
                ldao.insert_interactions(Interactions(
                    user_idx=np.arange(n, dtype=np.int32),
                    item_idx=rng.integers(0, 128, n).astype(np.int32),
                    values=np.ones(n, np.float32),
                    user_ids=IdTable.from_list(
                        [f"r{b}_{k}" for k in range(n)]),
                    item_ids=item_tab), 1)
                b += 1
                time.sleep(0.01)

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        lags: list = []
        t_end = time.monotonic() + 5.0
        try:
            while time.monotonic() < t_end:
                try:
                    lags.append(tail._lag_total(1))
                except Exception:
                    pass
                time.sleep(0.05)
        finally:
            stop_w.set()
            wt.join(timeout=10)
        caught = tail.wait_caught_up(timeout_s=30.0)
        tail.stop()
        leader_srv.stop()
        fclient.close()
        if lags and caught:
            out["ingest_replication_lag_p99_events"] = int(
                np.percentile(np.asarray(lags, np.float64), 99))
        log(f"ingest replication: lag_p99="
            f"{out['ingest_replication_lag_p99_events']} events "
            f"over {len(lags)} samples, caught_up={caught}")

    # -- C. front-door ingest soak with rolling writer reload -------------
    from incubator_predictionio_tpu.data.storage import (
        AccessKey,
        App,
        Storage,
    )
    from incubator_predictionio_tpu.servers.event_server import (
        EventServer,
        EventServerConfig,
    )
    from incubator_predictionio_tpu.serving.frontdoor import (
        FrontDoorConfig,
        IngestFrontDoor,
    )

    run_s = float(os.environ.get("PIO_BENCH_INGEST_SOAK_S", "8"))
    stale_bound_s = 5.0
    with tempfile.TemporaryDirectory(prefix="pio_bench_soak_") as tmp:
        prev = os.environ.get("PIO_LOG_SHARDS")
        os.environ["PIO_LOG_SHARDS"] = str(shards)
        door = None
        writers = []
        try:
            Storage.configure({
                "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
                "PIO_STORAGE_SOURCES_EV_TYPE": "cpplog",
                "PIO_STORAGE_SOURCES_EV_PATH": tmp,
                "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
                "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
                "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
                "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
                "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
            })
            app_id = Storage.get_meta_data_apps().insert(
                App(0, "bench-soak"))
            Storage.get_meta_data_access_keys().insert(
                AccessKey("soakkey", app_id))
            Storage.get_events().init(app_id)
            writers = [EventServer(EventServerConfig(ip="127.0.0.1",
                                                     port=0))
                       for _ in range(2)]
            ports = [w.start_background() for w in writers]
            door = IngestFrontDoor(
                [("127.0.0.1", p) for p in ports],
                FrontDoorConfig(server_key="soakkey",
                                request_timeout_s=15.0,
                                attempt_timeout_s=8.0,
                                drain_timeout_s=10.0,
                                reload_timeout_s=30.0))
            dport = door.start_background()
            url = (f"http://127.0.0.1:{dport}/batch/events.json"
                   "?accessKey=soakkey")
            accepted: list = []
            pump_errors: list = []
            stop_p = threading.Event()

            def pump(tid: int) -> None:
                b = 0
                while not stop_p.is_set():
                    body = json.dumps([
                        {"event": "rate", "entityType": "user",
                         "entityId": f"s{tid}_{b}_{k}",
                         "targetEntityType": "item",
                         "targetEntityId": f"i{k % 64}",
                         "properties": {"rating": 1.0}}
                        for k in range(50)]).encode()
                    try:
                        req = urllib.request.Request(
                            url, body,
                            {"Content-Type": "application/json"})
                        with urllib.request.urlopen(
                                req, timeout=20) as resp:
                            res = json.loads(resp.read())
                        accepted.append(sum(
                            1 for r in res if r.get("status") == 201))
                    except Exception as e:  # noqa: BLE001
                        pump_errors.append(repr(e))
                        return
                    b += 1

            # tail subscriber: append→visibility staleness across the
            # rolling reload (one poll's rows bound by oldest append)
            events_dao = Storage.get_events()
            stale_max = [0.0]
            stop_s = threading.Event()

            def subscriber() -> None:
                cursor = events_dao.tail_cursor(app_id=app_id)
                while not stop_s.is_set():
                    stop_s.wait(0.25)
                    try:
                        _i, _t, ams, cursor, reset = \
                            events_dao.read_interactions_since(
                                cursor, app_id=app_id,
                                event_names=("rate",),
                                value_prop="rating")
                    except Exception:
                        continue
                    if reset or not len(ams):
                        continue
                    oldest = int(ams.min())
                    if oldest > 0:
                        stale_max[0] = max(
                            stale_max[0],
                            time.time() - oldest / 1000.0)

            pumps = [threading.Thread(target=pump, args=(t,))
                     for t in range(3)]
            sub = threading.Thread(target=subscriber, daemon=True)
            for t in pumps:
                t.start()
            sub.start()
            t_half = time.monotonic() + run_s / 2
            while time.monotonic() < t_half:
                time.sleep(0.1)
            reload_out = door.rolling_reload(timeout=60)
            time.sleep(max(run_s / 2 - 0.1, 0.1))
            stop_p.set()
            for t in pumps:
                t.join(timeout=30)
            stop_s.set()
            sub.join(timeout=10)
            if pump_errors:
                raise RuntimeError(
                    f"soak pump failed: {pump_errors[0]}")
            sent = sum(accepted)
            landed = len(Storage.get_events().scan_interactions(
                app_id=app_id, entity_type="user",
                target_entity_type="item", event_names=("rate",),
                value_prop="rating"))
            out["ingest_soak_dropped_events"] = sent - landed
            out["ingest_soak_staleness_held"] = bool(
                stale_max[0] <= stale_bound_s)
            log(f"ingest soak: {sent} accepted, {landed} landed "
                f"(dropped={out['ingest_soak_dropped_events']}), "
                f"reloaded={reload_out['reloaded']}/2, "
                f"staleness_max={stale_max[0]:.2f}s "
                f"(bound {stale_bound_s}s, "
                f"held={out['ingest_soak_staleness_held']})")
        finally:
            if door is not None:
                door.stop()
            for w in writers:
                w.stop()
            Storage.reset()
            if prev is None:
                os.environ.pop("PIO_LOG_SHARDS", None)
            else:
                os.environ["PIO_LOG_SHARDS"] = prev
    return out


def bench_scan_probe(store_dir: str) -> dict:
    """Sequential vs sharded event-log scan at bench scale, projection
    cache bypassed, plus the pipelined scan→prep leg — the host-pipeline
    sub-metrics (shard count, per-shard walls, native-lock-held wall,
    scan/prep overlap). The headline ``ingest_wall_s`` keeps measuring
    the production warm path (cache serve); this stage measures the cold
    scan machinery those rounds would otherwise never see."""
    from incubator_predictionio_tpu.data.storage import StorageClientConfig
    from incubator_predictionio_tpu.data.storage import cpplog
    from incubator_predictionio_tpu.ops.sparse import StreamingPrep

    cfg = StorageClientConfig(properties={"PATH": store_dir})
    client = cpplog.StorageClient(cfg)
    events = cpplog.CppLogEvents(client, cfg, prefix="bench_")
    out: dict = {}
    old_shards = os.environ.get("PIO_SCAN_SHARDS")
    try:
        t0 = time.perf_counter()
        client.handle("bench_", 1, None)
        out["scan_open_s"] = round(time.perf_counter() - t0, 2)

        # true single-thread leg — the acceptance baseline. PIO_SCAN_
        # SHARDS=1 still uses the scanner's internal auto threading (the
        # pre-sharding production path), so the 1-thread wall is measured
        # through the raw native call with n_threads pinned to 1.
        with client.lock:
            h = events._handle(1, None)
            raw = client.lib.pio_evlog_entry_count(h)
            pin = client.pin("bench_", 1, None)
        try:
            t0 = time.perf_counter()
            inter, _, _ = events._scan_native(
                h, None, None, "user", "item", ["rate"], {}, "rating",
                1.0, min_entry_idx=0, max_entry_idx=raw, n_threads=1)
            out["scan_wall_1thread_s"] = round(time.perf_counter() - t0, 2)
            del inter
        finally:
            client.unpin(pin)

        os.environ["PIO_SCAN_SHARDS"] = "1"
        t0 = time.perf_counter()
        inter = events.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating",
            use_cache=False, seed_cache=False)
        seq_s = time.perf_counter() - t0
        n_seq = len(inter)
        del inter

        if old_shards is None:
            os.environ.pop("PIO_SCAN_SHARDS", None)
        else:
            os.environ["PIO_SCAN_SHARDS"] = old_shards
        prep = StreamingPrep()
        stats: dict = {}
        t0 = time.perf_counter()
        inter = events.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating",
            use_cache=False, seed_cache=False, stats=stats,
            shard_sink=prep.add_shard)
        sharded_s = time.perf_counter() - t0
        buckets = prep.finish(
            inter, reordered=bool(stats.get("scan_reordered")))
        pipelined_s = time.perf_counter() - t0
        assert len(inter) == n_seq, (len(inter), n_seq)
        del inter, buckets
        out.update({
            "scan_wall_seq_s": round(seq_s, 2),
            "scan_wall_sharded_s": round(sharded_s, 2),
            "scan_speedup_vs_seq": round(seq_s / max(sharded_s, 1e-9), 2),
            "scan_speedup_vs_1thread": round(
                out["scan_wall_1thread_s"] / max(sharded_s, 1e-9), 2),
            "scan_shards": stats.get("scan_shards"),
            "scan_shard_walls_s": stats.get("scan_shard_walls_s"),
            "scan_lock_held_s": stats.get("scan_lock_held_s"),
            "scan_merge_wall_s": stats.get("scan_merge_wall_s"),
            "scan_prep_pipelined_wall_s": round(pipelined_s, 2),
            "scan_prep_overlap_s": round(prep.overlap_s, 3),
        })
        log(f"scan probe: seq={seq_s:.1f}s sharded={sharded_s:.1f}s "
            f"(shards={stats.get('scan_shards')}, "
            f"lock-held={stats.get('scan_lock_held_s')}s) "
            f"pipelined scan+prep={pipelined_s:.1f}s "
            f"(overlap {prep.overlap_s:.2f}s)")
    finally:
        if old_shards is None:
            os.environ.pop("PIO_SCAN_SHARDS", None)
        else:
            os.environ["PIO_SCAN_SHARDS"] = old_shards
        client.close()
    return out


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def run_cpu_baseline() -> None:
    """`--cpu`: re-measure CPU_BASELINE_TRAIN_S on the host backend with
    the pinned all-f32 schedule (BASELINE.md convention: bf16 is emulated
    — slower — on the host, so letting the bf16 schedule leak into a
    --cpu re-measure would inflate vs_baseline unfairly)."""
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    rng = np.random.default_rng(7)
    log(f"dataset: {N_USERS}x{N_ITEMS}, nnz={NNZ}, rank={RANK}, "
        f"sweeps={ITERATIONS} (all f32 — CPU convention)")
    users, items, ratings, heldout, truth = make_dataset(rng)
    with tempfile.TemporaryDirectory(prefix="pio_bench_") as tmpdir:
        events, client, seed_s = seed_store(tmpdir, users, items, ratings)
        log(f"seed: {NNZ} events in {seed_s:.1f}s")
        client.close()
        inter, ingest_s = scan_store(tmpdir)
    assert len(inter) == NNZ, len(inter)
    u_b, i_b, n_users, n_items, prep_s = prep_buckets(inter)
    state, t = measure_train((u_b, i_b, n_users, n_items), 0,
                             cache_probe=False)
    log(f"CPU baseline measured: warm train = {t['train_s']:.1f}s "
        "(update CPU_BASELINE_TRAIN_S)")
    print(json.dumps({
        "metric": "als_ml20m_train_wall_s_cpu",
        "value": round(t["train_s"], 2),
        "unit": "s",
        "vs_baseline": 1.0,
    }))


def run_tpu_child(store_dir: str, out_path: str, claim_path: str,
                  parent_pid: int = 0) -> None:
    """All accelerator work, in a disposable process. First act: dial the
    chip (this is the call a stale lease blocks forever — the parent's
    recycle window covers it). On success, touch the claim file so the
    parent switches from 'dial watchdog' to 'run watchdog'."""
    from incubator_predictionio_tpu.utils.lease import install_sigterm_exit

    import jax

    # honor an explicit platform override (tests run this child on the
    # CPU backend) — the env var alone is not enough: the axon register
    # hook initializes its backend from config, not JAX_PLATFORMS
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # NO SIGTERM handler before the dial: a waiter blocked inside the
    # PJRT constructor can only be stopped by the default OS-level kill
    # (a Python handler never fires inside a blocked C call), and the
    # parent's recycle depends on being able to kill waiters
    jax.devices()  # the dial
    # claimed from here on: SIGTERM must now tear the process down via
    # normal interpreter shutdown — an abrupt death while HOLDING the
    # chip wedges the single-tenant lease for hours
    install_sigterm_exit()
    # Abandoned-waiter pile-up guard. TERM-ignoring waiters (the dial
    # retry loop swallows signals inside the C call) queue up on a wedged
    # lease; when it finally frees they claim it ONE AFTER ANOTHER. Only
    # the first claimer should run: a later claimer whose fragment
    # already exists — or whose bench parent is gone entirely — must exit
    # NOW, releasing the chip instead of re-running the whole TPU leg
    # against nobody.
    if os.path.exists(out_path):
        log("tpu child: fragment already landed by an earlier child; "
            "exiting to free the chip")
        return
    # explicit PID handshake, not getppid()==1: the bench itself can BE
    # pid 1 (container entrypoint), and orphans reparent to a subreaper
    # rather than init under systemd/tini
    if parent_pid and os.getppid() != parent_pid:
        log("tpu child: bench parent is gone (orphaned waiter); "
            "exiting to free the chip")
        return
    with open(claim_path, "w") as f:
        f.write(str(os.getpid()))
    log(f"tpu child: accelerator up ({jax.devices()[0]})")

    rng = np.random.default_rng(7)
    users, items, ratings, heldout, truth = make_dataset(rng)
    del users, items, ratings  # events already seeded by the parent

    inter, ingest_s = scan_store(store_dir)
    assert len(inter) == NNZ, len(inter)
    log(f"ingest scan: {ingest_s:.1f}s ({NNZ / ingest_s / 1e6:.2f}M ev/s)")

    from incubator_predictionio_tpu.ops import als
    from incubator_predictionio_tpu.ops.sparse import build_both_sides

    # pipelined prep→device: each side's bucket/heavy trees are uploaded
    # (H2D) from the prep worker the moment that side finishes padding,
    # overlapping the other side's bucket fill. prep_wall_s therefore now
    # INCLUDES the device upload that used to run untimed after prep;
    # prep_h2d_s records the upload share.
    n_users, n_items = len(inter.user_ids), len(inter.item_ids)
    side_box: dict = {}

    def _on_side(side, light, heavy):
        t0 = time.perf_counter()
        side_box[side] = (als._buckets_tree(light), als._heavy_tree(heavy))
        side_box[side + "_h2d_s"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    (u_b_light, u_b_heavy), (i_b_light, i_b_heavy) = build_both_sides(
        inter.user_idx, inter.item_idx, inter.values, n_users, n_items,
        on_side=_on_side)
    prep_s = time.perf_counter() - t0
    h2d_s = side_box["user_h2d_s"] + side_box["item_h2d_s"]
    log(f"prep+H2D (bucketed padded rows; per-side device upload "
        f"overlaps the other side's padding): {prep_s:.1f}s "
        f"(H2D {h2d_s:.1f}s, users={n_users}, items={n_items})")

    buckets = ((u_b_light, u_b_heavy), (i_b_light, i_b_heavy),
               n_users, n_items)
    trees = (side_box["user"][0], side_box["item"][0],
             side_box["user"][1], side_box["item"][1], n_users, n_items)
    use_kernel, kernel_rows, kernel_probe = select_als_kernel(
        buckets, trees=trees)
    state, t = measure_train(buckets, BF16_SWEEPS, use_kernel=use_kernel,
                             trees=trees, kernel_rows=kernel_rows)
    train_s = t["train_s"]
    fit = als.rmse(state, inter.user_idx, inter.item_idx, inter.values)
    # FLOPs over the rows the child ACTUALLY trained (the scan compacts
    # ids, so at sub-ML-20M shapes len(user_ids) < N_USERS and the env
    # shape would overcount solves ~3x; at the full shape every user has
    # events and this is identical to als_flops_per_run)
    flops = als.train_flops(NNZ, n_users, n_items, RANK, ITERATIONS,
                            BF16_SWEEPS)
    mfu = flops / train_s / PEAK_FLOPS_F32
    mfu_bf16 = flops / train_s / PEAK_FLOPS_BF16
    heldout_rmse, prec10 = quality_metrics(state, inter, heldout, truth, rng)
    log(f"device={jax.devices()[0]} compile={t['compile_s_cold']:.1f}s "
        f"warm={train_s:.2f}s rmse={fit:.3f} "
        f"heldout_rmse={heldout_rmse:.3f} (noise floor {NOISE_SIGMA}) "
        f"p@10={prec10:.3f} flops={flops:.3e} mfu={mfu:.3f}")

    attn = bench_attention()
    serve = bench_serving(state, inter)
    # steady-state retrain leg last: a failure here must never cost the
    # train/serve numbers already measured
    retrain_frag = dict.fromkeys(RETRAIN_KEYS)
    try:
        retrain_frag.update(
            bench_retrain(store_dir, state, inter, heldout, truth))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"retrain leg failed ({e!r}); retrain_* keys null this round")
    speed_frag = dict.fromkeys(SPEED_KEYS)
    try:
        speed_frag.update(bench_speed(store_dir, state, inter))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"speed leg failed ({e!r}); speed_* keys null this round")

    fragment = {
        # the CHILD's provenance overrides the parent's: the child is
        # the process that actually touched the accelerator, so its
        # backend/device view is the one the trajectory should carry
        "bench_env": bench_env(),
        "value": round(train_s, 3),
        "vs_baseline": round(CPU_BASELINE_TRAIN_S / train_s, 1),
        "train_rmse": round(float(fit), 3),
        "heldout_rmse": round(heldout_rmse, 3),
        "precision_at_10_vs_truth": round(prec10, 3),
        "mfu": round(mfu, 4),
        "mfu_bf16_peak": round(mfu_bf16, 4),
        # live pio_mfu{phase=train} gauge over the same timed warm run —
        # must agree with the offline mfu within 10% (the
        # bench↔telemetry cross-check; test_bench_e2e asserts the
        # ratio, computed against the UNROUNDED offline figure)
        "obs_mfu_train": t["obs_mfu_train"],
        "obs_mfu_vs_offline": (
            round(t["obs_mfu_train"] / mfu, 4)
            if t["obs_mfu_train"] and mfu > 0 else None),
        "obs_device_train_s": t["obs_device_train_s"],
        "obs_device_train_dispatches": t["obs_device_train_dispatches"],
        "train_fused_wall_s": t["train_fused_wall_s"],
        "compile_s_cold": t["compile_s_cold"],
        "compile_s_warm_cache": t["compile_s_warm_cache"],
        "ingest_wall_s": round(ingest_s, 1),
        "prep_wall_s": round(prep_s, 1),
        "prep_h2d_s": round(h2d_s, 1),
        "e2e_train_wall_s": round(ingest_s + prep_s + train_s, 1),
        **kernel_probe,
        **attn,
        **retrain_frag,
        **speed_frag,
        "serve_p50_ms": serve["p50_ms"],
        "serve_p99_ms": serve["p99_ms"],
        "serve_qps": serve["qps_sequential"],
        "serve_qps_concurrent": serve["qps_concurrent"],
        "serve_max_batch": serve["max_batch"],
        # registry cross-check for the stages the CHILD ran (serving,
        # compiles; the retrain leg ships its own obs_train_* delta);
        # the ingest-side obs_* keys belong to the parent — never
        # shipped from here, even as None (update() overwrites)
        **{k: v for k, v in obs_snapshot().items()
           if k.startswith(("obs_query_", "obs_compile_"))},
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(fragment, f)
    os.replace(tmp, out_path)


def supervise_tpu_child(store_dir: str, out_path: str,
                        claim_event=None, deadline_mono=None,
                        last_rc=None) -> bool:
    """Spawn/recycle the TPU child until it lands a fragment or the
    ACCEL_WAIT_S budget runs out. Returns True iff `out_path` exists
    (checked on every exit path — an abandoned SIGTERM-ignoring child
    that completes late still counts). Sets `claim_event` the moment any
    child claims the chip so the parent can cancel fallback work.

    ``deadline_mono`` (time.monotonic value) caps the CUMULATIVE claim
    wait: past it the supervisor returns so the orchestrator can emit
    its record before the driver's kill — terminating an unclaimed dial
    waiter (safe: it holds nothing), but leaving a claimed child running
    (a holder is never cut down; it finishes and exits on its own).

    A child that has not claimed the chip within its window is stopped
    with SIGTERM (it is *waiting* on the lease, not holding it — killing
    a waiter cannot wedge the chip; killing a holder can, which is why a
    claimed child gets the long run window and is never force-killed
    while healthy) and respawned with a doubled window: only a fresh
    process gets a fresh PJRT dial.

    ``last_rc``: optional single-slot list; the most recent child exit
    code observed lands in it, so the record's ``skipped_reason`` can
    carry the REAL rc instead of a guessed one."""
    deadline = time.monotonic() + ACCEL_WAIT_S
    if deadline_mono is not None:
        deadline = min(deadline, deadline_mono)
    window = 180.0
    attempt = 0
    fast_fails = 0
    while time.monotonic() < deadline:
        attempt += 1
        claim_path = f"{out_path}.claim{attempt}"
        t_spawn = time.monotonic()
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--tpu-child",
             store_dir, out_path, claim_path, str(os.getpid())],
            stdout=sys.stderr, stderr=sys.stderr)
        claimed = False
        win_end = min(time.monotonic() + window, deadline)
        while True:
            if (not claimed and proc.poll() is None
                    and os.path.exists(out_path)):
                # an earlier abandoned child landed the fragment while
                # this attempt was still dialing — stop the waiter (TERM;
                # it is not holding the lease) and take the result. A
                # CLAIMED child is never cut down here: its own fragment
                # write precedes a slow PJRT teardown, and a TERM in that
                # window is the abrupt-death-while-holding hazard
                log("fragment landed via an abandoned child; stopping "
                    f"attempt {attempt}")
                proc.terminate()
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    pass
                return True
            rc = proc.poll()
            if rc is not None:
                if last_rc is not None:
                    last_rc[:] = [rc]
                if rc == 0 and os.path.exists(out_path):
                    return True
                log(f"tpu child attempt {attempt} exited rc={rc} "
                    f"(claimed={claimed})")
                if claimed and attempt >= 2:
                    # the chip worked but the bench itself failed twice —
                    # a real error, not a lease wait; stop burning budget
                    return os.path.exists(out_path)
                if not claimed and time.monotonic() - t_spawn < 30:
                    # died before even reaching the dial (import error,
                    # bad store path …) — respawning cannot fix that
                    fast_fails += 1
                    if fast_fails >= 3:
                        log("tpu child crashes immediately; giving up on "
                            "the accelerator path")
                        return os.path.exists(out_path)
                break
            if not claimed and os.path.exists(claim_path):
                claimed = True
                if claim_event is not None:
                    claim_event.set()
                win_end = time.monotonic() + TPU_RUN_TIMEOUT_S
                log(f"tpu child claimed the accelerator "
                    f"(attempt {attempt}); run window "
                    f"{TPU_RUN_TIMEOUT_S:.0f}s")
            if claimed and time.monotonic() >= deadline:
                # global deadline with the TPU leg mid-run: the record
                # must go out NOW. The claimed child is left running —
                # a chip holder is never cut down — and its late
                # fragment simply goes unused this round.
                log("bench deadline reached during the TPU run; emitting "
                    "the record without waiting (child left running)")
                return os.path.exists(out_path)
            if time.monotonic() >= win_end:
                log(f"tpu child attempt {attempt} "
                    + ("overran its run window"
                       if claimed else
                       f"did not claim within {window:.0f}s — likely a "
                       "stale chip lease; recycling for a fresh dial"))
                proc.terminate()  # SIGTERM, never SIGKILL (lease safety)
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    log("tpu child ignored SIGTERM for 60s; abandoning it "
                        "(NOT escalating to SIGKILL — that wedges the "
                        "lease)")
                break
            time.sleep(2)
        window = min(window * 2, 960.0)
    log(f"accelerator never became available within {ACCEL_WAIT_S:.0f}s")
    return os.path.exists(out_path)


def run_degraded(inter, heldout, truth, rng, cancel=None):
    """TPU never landed: measure train quality on the pinned all-f32 CPU
    schedule at a reduced shape so the record still carries real RMSE /
    ranking numbers (flagged degraded), then serve from those factors.

    `cancel` (threading.Event) aborts between stages: when a TPU child
    claims the chip mid-fallback, this thread stops at the next stage
    boundary so parent CPU load stops perturbing the child's timed
    sections as soon as possible (a jitted stage in flight can't be
    interrupted)."""
    n_sub = min(DEGRADED_NNZ, len(inter.user_idx))
    log(f"DEGRADED mode: CPU all-f32 schedule on a {n_sub}-event "
        f"subsample (full-shape host walls already measured)")
    sub = np.random.default_rng(11).choice(
        len(inter.user_idx), n_sub, replace=False)
    sub.sort()

    class _Sub:
        user_idx = inter.user_idx[sub]
        item_idx = inter.item_idx[sub]
        values = inter.values[sub]
        user_ids = inter.user_ids
        item_ids = inter.item_ids

    from incubator_predictionio_tpu.ops import als

    def cancelled() -> bool:
        if cancel is not None and cancel.is_set():
            log("degraded fallback cancelled — a TPU child claimed the "
                "chip")
            return True
        return False

    if cancelled():
        return None
    u_b, i_b, n_users, n_items, prep_s = prep_buckets(_Sub)
    if cancelled():
        return None
    state, t = measure_train((u_b, i_b, n_users, n_items), 0,
                             cache_probe=False)
    fit = als.rmse(state, _Sub.user_idx, _Sub.item_idx, _Sub.values)
    if cancelled():
        return None
    heldout_rmse, prec10 = quality_metrics(state, _Sub, heldout, truth, rng)
    log(f"degraded train: warm={t['train_s']:.1f}s fit={fit:.3f} "
        f"heldout={heldout_rmse:.3f} p@10={prec10:.3f}")
    if cancelled():
        return None
    serve = bench_serving(state, _Sub)
    # vs_baseline against the baseline scaled to the degraded nnz (the
    # train wall is ~linear in nnz at fixed shape) — an honest ~1.0, not
    # a fake speedup
    scaled_base = CPU_BASELINE_TRAIN_S * n_sub / NNZ
    return {
        "value": round(t["train_s"], 3),
        "vs_baseline": round(scaled_base / t["train_s"], 2),
        "obs_mfu_train": t.get("obs_mfu_train"),
        "obs_device_train_s": t.get("obs_device_train_s"),
        "obs_device_train_dispatches": t.get("obs_device_train_dispatches"),
        "train_rmse": round(float(fit), 3),
        "heldout_rmse": round(heldout_rmse, 3),
        "precision_at_10_vs_truth": round(prec10, 3),
        "degraded_nnz": n_sub,
        "serve_p50_ms": serve["p50_ms"],
        "serve_p99_ms": serve["p99_ms"],
        "serve_qps": serve["qps_sequential"],
        "serve_qps_concurrent": serve["qps_concurrent"],
        "serve_max_batch": serve["max_batch"],
    }


def run_orchestrator() -> None:
    """Default entry: host-side stages in THIS process (jax pinned to
    CPU — the parent never dials the chip), TPU stages in a supervised
    child. Always prints one parsed JSON record; exit 0 even in degraded
    mode (a degraded record is a result, not an error)."""
    import atexit
    import shutil
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")

    t_bench0 = time.monotonic()
    emit_by = t_bench0 + BENCH_DEADLINE_S - EMIT_MARGIN_S
    # wall-clock deadline for the CHILD (monotonic clocks don't cross
    # process boundaries): optional legs (retrain) skip themselves when
    # the record must go out soon
    os.environ["PIO_BENCH_EMIT_BY_EPOCH"] = str(
        time.time() + BENCH_DEADLINE_S - EMIT_MARGIN_S)

    rng = np.random.default_rng(7)
    log(f"dataset: {N_USERS}x{N_ITEMS}, nnz={NNZ}, rank={RANK}, "
        f"sweeps={ITERATIONS} ({BF16_SWEEPS} bf16 + "
        f"{ITERATIONS - BF16_SWEEPS} f32-polish), planted rank "
        f"{PLANT_RANK} + noise {NOISE_SIGMA}")
    users, items, ratings, heldout, truth = make_dataset(rng)

    store_dir = tempfile.mkdtemp(prefix="pio_bench_store_")
    atexit.register(shutil.rmtree, store_dir, True)
    frag_path = os.path.join(store_dir, "tpu_fragment.json")

    # -- THE record, created before any stage runs. Every stage fills it
    # in place, so at any instant it is the best-available parsed record
    # — and the SIGTERM handler below can flush it if the DRIVER's
    # deadline (not ours) lands first. BENCH_r05 ended rc=124 with
    # parsed:null because an already-computed degraded record was still
    # waiting for the orchestrator's own emit point when the driver
    # killed the process; now the kill itself emits. Stable key set
    # across modes: every key a prior round's record had is present
    # (None when the mode can't measure it), so round-over-round
    # comparisons never hit a missing key on a degraded round.
    record = {
        "metric": "als_ml20m_train_wall_s",
        "value": None,
        "unit": "s",
        "vs_baseline": None,
        "degraded": True,
        # provenance (obs/capacity.py reads these): what machine/software
        # produced this row of the trajectory, and — when the round could
        # not measure the accelerator — a STRUCTURED reason, so no record
        # is ever unexplainable (the BENCH_r04/r05 parsed:null class)
        "bench_env": bench_env(),
        "skipped_reason": None,
        "train_rmse": None,
        "heldout_rmse": None,
        "noise_floor": NOISE_SIGMA,
        "precision_at_10_vs_truth": None,
        # pre-declared so the degraded-fallback thread's record.update
        # never INSERTS a key: a dict resize racing the SIGTERM
        # handler's json.dumps would raise mid-flush (value swaps are
        # GIL-atomic; popped again when a child fragment lands)
        "degraded_nnz": None,
        "mfu": None,
        "mfu_bf16_peak": None,
        "compile_s_cold": None,
        "compile_s_warm_cache": None,
        "seed_wall_s": None,
        "ingest_wall_s": None,
        "prep_wall_s": None,
        "prep_h2d_s": None,  # child-only (pipelined prep→device upload)
        # host-pipeline sub-metrics (bench_scan_probe): sharded-scan
        # walls, native-lock-held wall, scan→prep overlap
        **{k: None for k in (
            "scan_open_s", "scan_wall_1thread_s", "scan_wall_seq_s",
            "scan_wall_sharded_s", "scan_speedup_vs_seq",
            "scan_speedup_vs_1thread", "scan_shards",
            "scan_shard_walls_s", "scan_lock_held_s",
            "scan_merge_wall_s", "scan_prep_pipelined_wall_s",
            "scan_prep_overlap_s")},
        "e2e_train_wall_s": None,
        "ingest_http_eps": None,
        "ingest_http_eps_cap500": None,
        "movielens_rmse": None,
        "movielens_rmse_bound": None,
        "serve_p50_ms": None,
        "serve_p99_ms": None,
        "serve_qps": None,
        "serve_qps_concurrent": None,
        "serve_max_batch": None,
        # child-fragment fields (overwritten when the child lands; a
        # degraded round carries the honest null markers so every
        # deterministic key a successful round emits is present)
        "als_kernel": None,
        "als_kernel_rows": None,
        "als_kernel_sweep_xla_s": None,
        "flash_kernel_active": None,
        "train_fused_wall_s": None,
        "obs_device_train_s": None,
        "obs_device_train_dispatches": None,
        # steady-state retrain leg (child-only; docs/performance.md)
        **dict.fromkeys(RETRAIN_KEYS),
        # speed-layer leg (child-only; docs/production.md "Freshness
        # between retrains")
        **dict.fromkeys(SPEED_KEYS),
        # mesh-sharded training leg (parent-side subprocess on the
        # forced-host-device CPU sim; docs/performance.md "Sharded ALS")
        **dict.fromkeys(SHARD_KEYS),
        **dict.fromkeys(MIPS_KEYS),
        # ≥10M-item MIPS lifecycle leg (in-process; PQ + background
        # rebuild-and-swap; docs/performance.md "Catalogue at tens of
        # millions")
        **dict.fromkeys(MIPS_BIG_KEYS),
        # serving-fleet leg (parent-side worker subprocesses;
        # docs/production.md "Serving fleet")
        **dict.fromkeys(FLEET_KEYS),
        # fleet front-door leg (parent-side router over worker
        # subprocesses; docs/production.md "Fleet front door")
        **dict.fromkeys(FRONTDOOR_KEYS),
        # multi-tenant noisy-neighbor leg (two tenants on a real
        # 2-worker fleet; docs/production.md "Multi-tenant platform")
        **dict.fromkeys(TENANT_KEYS),
        # self-driving freshness leg (controller over fleet workers +
        # front door; docs/production.md "Self-driving freshness")
        **dict.fromkeys(CONTROLLER_KEYS),
        # self-tuning serving leg (knob controller over fleet workers +
        # front door; docs/production.md "Self-tuning serving")
        **dict.fromkeys(KNOB_KEYS),
        # planet-scale ingest leg (sharded writers + replication +
        # front-door soak; docs/production.md "Planet-scale ingest")
        **dict.fromkeys(INGEST_KEYS),
        "accel_waited_s": None,
        "accel_outcome": "never_available",
        "sasrec_epoch_s": None,
        **{f"attn_{kind}_ms_{s // 1024}k": None
           for s in (int(v) for v in os.environ.get(
               "PIO_BENCH_ATTN_SEQS", "4096,8192,32768").split(",") if v)
           for kind in ("flash", "xla")},
        "nnz": NNZ,
        "rank": RANK,
        "sweeps": ITERATIONS,
        "bf16_sweeps": BF16_SWEEPS,
        # telemetry cross-check (docs/observability.md): stable None
        # defaults; child-fragment values and the parent registry
        # snapshot below fill what each process actually ran
        **dict.fromkeys(OBS_KEYS),
    }
    emitted: list = []

    def _emit_record(from_signal: bool = False) -> None:
        # contract: ONE complete JSON line on stdout. `emitted` is set
        # only AFTER the full line is flushed: a SIGTERM landing while
        # the main emit is mid-write still re-emits (the handler
        # prefixes a newline so any partial main-thread write becomes
        # its own garbage line and the record line stays parseable —
        # the worst case is a duplicated valid line, never a missing
        # one, which was the parsed:null class). The dumps retry guards
        # a worker thread mutating the record mid-serialization: value
        # swaps are GIL-atomic (all keys pre-declared above), but one
        # retry keeps even an unexpected resize from costing the round
        # its record.
        if emitted:
            return
        try:
            line = json.dumps(record)
        except RuntimeError:
            line = json.dumps(dict(record))
        sys.stdout.write(("\n" if from_signal else "") + line + "\n")
        sys.stdout.flush()
        emitted.append(True)

    def _deadline_flush(signum, frame):
        # the DRIVER's kill (timeout → SIGTERM, the rc=124 path): flush
        # the best-available record NOW — a late child fragment is
        # picked up if one landed — and exit cleanly. Machine-readable
        # metrics from every run, even one the driver cut short.
        try:
            if os.path.exists(frag_path):
                with open(frag_path) as f:
                    record.update(json.load(f))
                record["degraded"] = False
                record["skipped_reason"] = None
        except Exception:
            pass
        if record.get("degraded") and record.get("skipped_reason") is None:
            record["skipped_reason"] = {
                "class": "driver_deadline",
                "stage": "tpu_child",
                "detail": "driver SIGTERM before the bench's own emit "
                          "point; best-available degraded record flushed",
                "rc": 124,
            }
        log("SIGTERM before the bench's own emit point: flushing the "
            "best-available record")
        _emit_record(from_signal=True)
        os._exit(0)

    import signal

    signal.signal(signal.SIGTERM, _deadline_flush)

    # -- 1. SEED (host) ----------------------------------------------------
    events, client, seed_s = seed_store(store_dir, users, items, ratings)
    client.close()
    record["seed_wall_s"] = round(seed_s, 1)
    log(f"seed: {NNZ} events in {seed_s:.1f}s "
        f"({NNZ / seed_s / 1e6:.2f}M ev/s)")

    # -- 2a. SCAN PROBES (host): the sharded-scan sub-metrics. The
    #        ingest stage below serves from the projection cache (the
    #        production warm path), so the native scan machinery is
    #        measured here explicitly — sequential vs sharded, cache
    #        bypassed, plus the pipelined scan→prep leg. Runs before the
    #        ingest stage so its transient full-shape arrays are freed
    #        before the parent holds its own copy, and GUARDED: a probe
    #        failure nulls the sub-metrics, never costs the record (the
    #        BENCH_r05 recordless-exit class)
    try:
        record.update(bench_scan_probe(store_dir))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"scan probe failed ({e!r}); sub-metrics null this round")

    # -- 2+3. INGEST + PREP (host, parent's own copy for the degraded
    #         record; the child measures its own on the TPU path) ----------
    inter, ingest_s = scan_store(store_dir)
    assert len(inter) == NNZ, len(inter)
    record["ingest_wall_s"] = round(ingest_s, 1)
    log(f"ingest scan: {ingest_s:.1f}s ({NNZ / ingest_s / 1e6:.2f}M ev/s)")
    prep_probe = prep_buckets(inter)
    prep_s = prep_probe[4]
    del prep_probe
    record["prep_wall_s"] = round(prep_s, 1)
    log(f"prep (bucketed padded rows): {prep_s:.1f}s")

    # -- 6. INGEST-HTTP (host; needs no accelerator) -----------------------
    record["ingest_http_eps"] = bench_ingest_http()
    record["ingest_http_eps_cap500"] = bench_ingest_http(batch_size=500)

    # -- 6b. REAL-DATA QUALITY BOUND (host CPU; tiny) ----------------------
    record.update(bench_movielens_quality())

    # -- 6c. MESH-SHARDED TRAINING LEG (host CPU, own subprocess with
    #        the backend forced to 8 virtual devices) ----------------------
    try:
        record.update(bench_shard(emit_by - time.monotonic()))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"shard leg failed ({e!r}); shard_* keys null this round")

    # -- 6d. SERVING-FLEET LEG (host CPU, real worker subprocesses +
    #        parent-side load generators) ----------------------------------
    try:
        record.update(bench_fleet(emit_by - time.monotonic()))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"fleet leg failed ({e!r}); fleet_* keys null this round")

    # -- 6d2. FLEET FRONT-DOOR LEG (host CPU, in-process router over
    #         worker subprocesses; chaos-injected) ------------------------
    try:
        record.update(bench_frontdoor(emit_by - time.monotonic()))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"frontdoor leg failed ({e!r}); frontdoor_* keys null "
            "this round")

    # -- 6d3. SELF-DRIVING FRESHNESS LEG (host CPU, controller over
    #         fleet workers + front door; zero human retrains) ------------
    try:
        record.update(bench_controller(emit_by - time.monotonic()))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"controller leg failed ({e!r}); controller_* keys null "
            "this round")

    # -- 6d4. SELF-TUNING SERVING LEG (host CPU, knob controller over
    #         fleet workers + front door; planted world model) ----------
    try:
        record.update(bench_knobs(emit_by - time.monotonic()))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"knobs leg failed ({e!r}); knob_* keys null this round")

    # -- 6d5. MULTI-TENANT NOISY-NEIGHBOR LEG (host CPU, two tenants on
    #         a real 2-worker fleet behind the front door) ---------------
    try:
        record.update(bench_tenants(emit_by - time.monotonic()))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"tenants leg failed ({e!r}); tenant_* keys null this round")

    # -- 6e. TWO-STAGE MIPS SERVING LEG (in-process; planted catalogue
    #        past ML-20M scale, exhaustive stays the oracle) ---------------
    try:
        record.update(bench_mips(emit_by - time.monotonic()))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"mips leg failed ({e!r}); mips_* keys null this round")

    # -- 6e2. MIPS CATALOGUE-AT-SCALE LEG (in-process; ≥10M items under
    #         PQ with a background rebuild-and-swap mid-serve; skips on
    #         budget via its own cost model — the 1-core box never pays
    #         for it by accident) --------------------------------------
    try:
        record.update(bench_mips_big(emit_by - time.monotonic()))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"mips big leg failed ({e!r}); mips_big_* keys null")

    # -- 6f. PLANET-SCALE INGEST LEG (host CPU; sharded writers vs
    #        single-writer in the same run, replication lag, front-door
    #        soak with a rolling zero-downtime writer reload). LAST of
    #        the host legs: its soak saturates the CPU, and the timed
    #        legs before it must not inherit that heat or lose budget
    #        to it (it budget-skips to null keys gracefully). ------------
    try:
        record.update(bench_ingest(emit_by - time.monotonic()))
    except Exception as e:  # noqa: BLE001 — sub-metrics are optional
        log(f"ingest leg failed ({e!r}); ingest_* keys null this round")

    # -- 4/5/7. TRAIN + ATTENTION + SERVE: supervised TPU child ------------
    # (started after the host stages so parent CPU load never perturbs the
    # child's timed sections — on a 1-core driver box that skew is real).
    # If no child claims the chip within DEGRADED_START_S, the parent
    # starts computing the degraded record in parallel with the remaining
    # wait; the overlap bounds the worst-case bench wall at roughly
    # host stages + ACCEL_WAIT_S instead of their sum plus the fallback.
    import threading

    sup_done = threading.Event()
    claim_seen = threading.Event()
    sup_ok: list = []
    child_last_rc: list = []

    def _supervise() -> None:
        try:
            sup_ok.append(
                supervise_tpu_child(store_dir, frag_path, claim_seen,
                                    deadline_mono=emit_by - 5.0,
                                    last_rc=child_last_rc))
        finally:
            sup_done.set()

    t_sup0 = time.monotonic()
    threading.Thread(target=_supervise, daemon=True).start()

    degraded_result: list = []
    t_deg = None
    # start the fallback at DEGRADED_START_S — or earlier when the global
    # deadline demands it: the degraded record needs DEGRADED_BUDGET_S to
    # compute, and a record MUST be on stdout before the driver's kill
    # (the BENCH_r05 failure mode). Worst case the fallback overlaps the
    # dial wait from the first second; cancel-on-claim keeps the CPU
    # perturbation window as short as possible.
    deg_start_wait = max(0.0, min(
        DEGRADED_START_S,
        (emit_by - DEGRADED_BUDGET_S) - time.monotonic()))

    def _run_degraded_into_record() -> None:
        res = run_degraded(inter, heldout, truth, rng, cancel=claim_seen)
        degraded_result.append(res)
        if res:
            # fold into the live record the moment it exists, so a
            # driver kill from here on flushes REAL train-quality
            # numbers (the child fragment, if one still lands, is
            # applied after and overrides)
            record.update(res)
            record["bf16_sweeps"] = 0  # degraded = all-f32 CPU schedule
            if record["ingest_wall_s"] is not None \
                    and record["prep_wall_s"] is not None:
                record["e2e_train_wall_s"] = round(
                    record["ingest_wall_s"] + record["prep_wall_s"]
                    + record["value"], 1)

    if not sup_done.wait(deg_start_wait) and not claim_seen.is_set():
        log(f"no accelerator claim after {deg_start_wait:.0f}s — "
            "computing the degraded record in parallel with the wait")
        t_deg = threading.Thread(target=_run_degraded_into_record,
                                 daemon=True)
        t_deg.start()
    if not sup_done.wait(max(emit_by - time.monotonic(), 0.0)):
        log("bench deadline: abandoning the supervisor thread and "
            "emitting the record now")
    accel_waited_s = time.monotonic() - t_sup0
    child_ok = bool(sup_ok and sup_ok[0]) or os.path.exists(frag_path)
    if not child_ok and t_deg is not None:
        # never start a second run_degraded while the thread lives — the
        # two would race on the process-global Storage registry; wait it
        # out up to the deadline instead
        t_deg.join(timeout=max(emit_by - time.monotonic(), 5.0))
        if t_deg.is_alive():
            log("degraded fallback still running at the deadline — "
                "emitting the record without train-quality keys")
    # how long the supervised-child leg ran and how it ended — makes
    # a wedged-lease round diagnosable from the record alone.
    # child_ok counts as claiming evidence too: a fragment can land
    # via an abandoned child whose claim file the supervisor no
    # longer polls
    record["accel_waited_s"] = round(accel_waited_s, 1)
    record["accel_outcome"] = ("claimed"
                               if claim_seen.is_set() or child_ok
                               else "never_available")
    if child_ok and os.path.exists(frag_path):
        with open(frag_path) as f:
            record.update(json.load(f))
        record["degraded"] = False
        record["skipped_reason"] = None
        record["bf16_sweeps"] = BF16_SWEEPS
        # a degraded fallback may have folded in before the child landed
        # — the fragment overrode every shared key; drop its marker
        record.pop("degraded_nnz", None)
        record["e2e_train_wall_s"] = round(
            record["ingest_wall_s"] + record["prep_wall_s"]
            + record["value"], 1)
    else:
        record["degraded"] = True
        # the structured why (satellite of the capacity model): this
        # round's accelerator story, machine-readable — the r04 class
        # ("accelerator init still blocked") ends up here instead of an
        # unexplained parsed:null; rc is the last child exit actually
        # observed, null when no child ever exited in view
        record["skipped_reason"] = {
            "class": ("accelerator_unavailable"
                      if record["accel_outcome"] == "never_available"
                      else "tpu_child_failed"),
            "stage": "tpu_child",
            "detail": (f"accel_outcome={record['accel_outcome']} after "
                       f"{record['accel_waited_s']}s wait; degraded CPU "
                       "record emitted in its place"),
            "rc": child_last_rc[0] if child_last_rc else None,
        }
        record["bf16_sweeps"] = 0  # degraded runs the all-f32 CPU schedule
        if degraded_result and degraded_result[0]:
            pass  # already folded into the record by the fallback thread
        elif t_deg is not None and t_deg.is_alive():
            pass  # fallback thread hung — never race a second run
        elif time.monotonic() + DEGRADED_BUDGET_S <= emit_by:
            # no fallback ran, or it was cancelled by a claim from a child
            # that then failed — the thread is dead and there is still
            # budget before the deadline, so run it fresh
            deg = run_degraded(inter, heldout, truth, rng)
            if deg:
                record.update(deg)
                # full-shape read/prep walls + degraded-shape train wall:
                # the degraded flag marks the mixed provenance
                record["e2e_train_wall_s"] = round(
                    record["ingest_wall_s"] + record["prep_wall_s"]
                    + record["value"], 1)
        else:
            log("no time left for a fresh degraded run before the "
                "deadline — emitting the record without train-quality "
                "keys")
    # parent-side registry snapshot: fills the obs_* keys for the stages
    # THIS process ran (ingest HTTP always; serving too on a degraded
    # round) without overriding anything the child fragment measured
    for k, v in obs_snapshot().items():
        if record.get(k) is None:
            record[k] = v
    _emit_record()


#: the reference's own bundled MovieLens sample (user::item::rating, 1.5k
#: real ratings) — the only real interaction dataset in this egress-free
#: environment. Loaded AT RUN TIME from the read-only reference tree
#: (never copied into the repo); the stage reports null when absent.
MOVIELENS_SAMPLE = os.environ.get(
    "PIO_BENCH_MOVIELENS",
    "/root/reference/examples/experimental/data/movielens.txt")
#: regression bound for the real-data stage: measured 1.076/1.058/1.024
#: across seeds 0..2 (rank 8, λ=0.1, 10 sweeps, 80/20 split; the sample
#: is 30 users × 100 items, rating std 1.19 — the model beats the
#: constant predictor by ~10%, which is what 1.2k training ratings
#: support). 1.20 is ~11% headroom over the worst seed and below the
#: 1.31 a mis-regularized run measures — tight enough to catch a solver
#: regression, loose enough for seed noise.
MOVIELENS_RMSE_BOUND = float(
    os.environ.get("PIO_BENCH_MOVIELENS_BOUND", "1.20"))


def load_movielens_sample():
    """→ (users, items, vals, n_users, n_items) from the sample file, or
    None when missing/unparseable (the stage must never crash the
    orchestrator's always-emit-a-record contract — the path is
    env-overridable and an operator may point it at a file in another
    format)."""
    try:
        with open(MOVIELENS_SAMPLE) as f:
            rows = [line.strip().split("::") for line in f if line.strip()]
        users = np.asarray([int(r[0]) for r in rows], np.int32)
        items = np.asarray([int(r[1]) for r in rows], np.int32)
        vals = np.asarray([float(r[2]) for r in rows], np.float32)
    except (OSError, ValueError, IndexError) as e:
        log(f"movielens sample unusable at {MOVIELENS_SAMPLE} ({e}); "
            "real-data stage skipped")
        return None
    # dense reindex (ids in the file are sparse)
    uu, users = np.unique(users, return_inverse=True)
    ii, items = np.unique(items, return_inverse=True)
    return (users.astype(np.int32), items.astype(np.int32), vals,
            len(uu), len(ii))


#: the stage's own hyperparameters: 1.2k training ratings cannot support
#: the bench shape's rank-128/λ=0.03 config (it would overfit to
#: noise) — this is a SEPARATE tiny-data solver-health bound, tuned for
#: the sample (rank 8, λ=0.1 measured best of a small grid), NOT a
#: validation of the big bench's λ. The planted stage owns that.
MOVIELENS_RANK = 8
MOVIELENS_L2 = 0.1


def bench_movielens_quality():
    """Real-data RMSE regression bound (VERDICT r4 item 4): train on 80%
    of the reference's bundled MovieLens sample, report heldout RMSE and
    whether it clears the pinned bound. Synthetic planted quality proves
    recovery against a KNOWN floor; this proves the solver stays healthy
    on real human ratings (at the sample's own tuned tiny-data
    hyperparameters — see MOVIELENS_RANK/MOVIELENS_L2). → dict of record
    keys (nulls if the sample file is unavailable)."""
    from incubator_predictionio_tpu.ops import als

    out = {"movielens_rmse": None, "movielens_rmse_bound": None}
    loaded = load_movielens_sample()
    if loaded is None:
        return out
    users, items, vals, n_users, n_items = loaded
    rng = np.random.default_rng(0)
    perm = rng.permutation(len(vals))
    cut = int(0.8 * len(vals))
    tr, te = perm[:cut], perm[cut:]
    state, _ = als.als_train(
        users[tr], items[tr], vals[tr], n_users, n_items,
        rank=MOVIELENS_RANK, iterations=10, l2=MOVIELENS_L2, seed=0)
    rmse_te = als.rmse(state, users[te], items[te], vals[te])
    ok = rmse_te <= MOVIELENS_RMSE_BOUND
    log(f"movielens sample ({len(vals)} real ratings): heldout RMSE "
        f"{rmse_te:.3f} (bound {MOVIELENS_RMSE_BOUND}) "
        f"{'OK' if ok else 'REGRESSION'}")
    return {
        "movielens_rmse": round(float(rmse_te), 3),
        "movielens_rmse_bound": MOVIELENS_RMSE_BOUND,
    }


def bench_attention():
    """Driver-verified attention numbers (r3 verdict item 9): flash
    (Pallas) vs the XLA blockwise scan at 8k/32k, plus one SASRec
    train-epoch wall — so kernel claims land in BENCH json, and a Mosaic
    rejection (flash_available() False → XLA fallback serving the flash
    call via interpret-free blockwise) is visible instead of silent."""
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops.attention import blockwise_attention
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        flash_attention,
        flash_available,
    )

    out = {"flash_kernel_active": bool(flash_available())}
    if not out["flash_kernel_active"]:
        log("attention: Mosaic rejected the flash family on this backend "
            "— XLA blockwise path serves (numbers below are XLA vs XLA)")
    h, d = 8, 64
    # 4096 rides along to place the flash/scan crossover (the per-length
    # block table serves ≥8192; 4k is the scan's side of the line today)
    seqs_env = os.environ.get("PIO_BENCH_ATTN_SEQS", "4096,8192,32768")
    # enough calls to amortize the tunneled platform's per-dispatch floor
    # (~2.7 ms amortized, ~30 ms for a short burst — a 3-call loop would
    # measure dispatch, not the kernel; the same trap round 3 fell into
    # with block_until_ready)
    reps = int(os.environ.get("PIO_BENCH_ATTN_REPS", 20))
    for s in (int(v) for v in seqs_env.split(",") if v):
        key = jax.random.key(0)
        q, k, v = (
            jax.random.normal(kk, (1, s, h, d), jnp.bfloat16)
            for kk in jax.random.split(key, 3)
        )

        def timed(fn):
            r = fn(q, k, v, causal=True)
            np.asarray(r[0:1, 0:1, 0:1, 0:1])  # dependent fetch = sync
            t0 = time.perf_counter()
            for _ in range(reps):
                r = fn(q, k, v, causal=True)
            np.asarray(r[0:1, 0:1, 0:1, 0:1])
            return (time.perf_counter() - t0) / reps

        t_flash = timed(flash_attention)
        t_xla = timed(blockwise_attention)
        out[f"attn_flash_ms_{s // 1024}k"] = round(t_flash * 1e3, 2)
        out[f"attn_xla_ms_{s // 1024}k"] = round(t_xla * 1e3, 2)
        log(f"attention S={s}: flash={t_flash * 1e3:.2f}ms "
            f"xla={t_xla * 1e3:.2f}ms ({t_xla / t_flash:.2f}x)")

    from incubator_predictionio_tpu.ops.transformer import sasrec_fit

    rng = np.random.default_rng(5)
    seqs = rng.integers(1, 2000, (512, 128)).astype(np.int32)
    t0 = time.perf_counter()
    sasrec_fit(seqs, n_items=2000, d_model=64, n_heads=2, n_layers=2,
               epochs=1, batch_size=128)
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    sasrec_fit(seqs, n_items=2000, d_model=64, n_heads=2, n_layers=2,
               epochs=1, batch_size=128)
    warm = time.perf_counter() - t0
    out["sasrec_epoch_s"] = round(warm, 2)
    log(f"sasrec: 1-epoch wall first={first:.1f}s warm={warm:.2f}s "
        f"(512x128 seqs, d=64)")
    return out


async def _http_post_loop(port, path, bodies) -> None:
    """One async keep-alive connection POSTing each body in turn — the
    shared load-generator leg of the ingest and serving benches. Every
    request carries the bench's trace ID (one per process, prefixed
    ``bench-``) so the servers' span logs attribute the load to this
    bench run — the bench→servers hop of the cross-process trace
    contract (docs/observability.md "Fleet")."""
    import asyncio

    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        for body in bodies:
            writer.write(
                f"POST {path} HTTP/1.1\r\nHost: bench\r\n"
                "Content-Type: application/json\r\n"
                f"X-PIO-Trace-Id: {_bench_trace_id()}\r\n"
                f"Content-Length: {len(body)}\r\n\r\n".encode()
                + body)
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            status_line = head.split(b"\r\n", 1)[0]
            if b" 200 " not in status_line:
                raise RuntimeError(f"request failed: {status_line!r}")
            clen = next(
                (int(line.split(b":")[1])
                 for line in head.split(b"\r\n")
                 if line.lower().startswith(b"content-length")), None)
            if clen is None:
                raise RuntimeError("response without Content-Length")
            await reader.readexactly(clen)
    finally:
        writer.close()


def bench_ingest_http(batch_size: int = 50):
    """REST ingest throughput through the real EventServer into the cpplog
    backend: async keep-alive clients posting ``batch_size``-event batches
    to POST /batch/events.json. 50 is the reference's wire-contract cap
    (EventServer.scala:269-289's hot path); a second pass at 500 measures
    the raised --batch-cap headroom the bulk-loader path advertises.
    Returns events/s."""
    import asyncio
    import tempfile

    from incubator_predictionio_tpu.data.storage import (
        AccessKey,
        App,
        Storage,
    )
    from incubator_predictionio_tpu.servers.event_server import (
        EventServer,
        EventServerConfig,
    )

    n_clients = int(os.environ.get("PIO_BENCH_INGEST_CLIENTS", 32))
    # 100 batches/client (160k events at the contract cap) ≈ 2 s: long
    # enough that connection setup and first-append warmup stop shaving
    # ~20% off the number. The batch COUNT stays constant across caps —
    # a bigger cap means more events and a comparable (slightly longer)
    # wall, keeping both measurements sustained-rate, not burst
    batches_per_client = int(os.environ.get("PIO_BENCH_INGEST_BATCHES",
                                            100))

    with tempfile.TemporaryDirectory(prefix="pio_bench_ingest_") as tmpdir:
        Storage.configure({
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_SOURCES_EV_TYPE": "cpplog",
            "PIO_STORAGE_SOURCES_EV_PATH": tmpdir,
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        })
        apps = Storage.get_meta_data_apps()
        app_id = apps.insert(App(0, "bench-ingest"))
        Storage.get_meta_data_access_keys().insert(
            AccessKey("benchkey", app_id))
        srv = EventServer(EventServerConfig(ip="127.0.0.1", port=0,
                                            max_batch=batch_size))
        port = srv.start_background()

        def batch_body(cid: int, b: int) -> bytes:
            return json.dumps([
                {
                    "event": "rate",
                    "entityType": "user",
                    "entityId": f"u{cid}_{b}_{k}",
                    "targetEntityType": "item",
                    "targetEntityId": f"i{k}",
                    "properties": {"rating": float(1 + k % 5)},
                }
                for k in range(batch_size)
            ]).encode()

        path = "/batch/events.json?accessKey=benchkey"
        # pre-render every request body OUTSIDE the timed window: the
        # load generator shares the box (often the core) with the server,
        # and its json.dumps would otherwise count against the server's
        # measured throughput
        bodies = [
            [batch_body(c, b) for b in range(batches_per_client)]
            for c in range(n_clients)
        ]

        async def load() -> float:
            t0 = time.perf_counter()
            await asyncio.wait_for(
                asyncio.gather(*[
                    _http_post_loop(port, path, bodies[c])
                    for c in range(n_clients)
                ]),
                timeout=600.0)
            return time.perf_counter() - t0

        wall = asyncio.run(load())
        total = n_clients * batches_per_client * batch_size
        landed = Storage.get_events().scan_interactions(
            app_id=app_id, event_names=("rate",), value_prop="rating")
        assert len(landed) == total, (len(landed), total)
        eps = total / wall
        log(f"ingest-http: {total} events in {wall:.1f}s "
            f"({eps:.0f} ev/s, {n_clients} clients x "
            f"{batches_per_client} batches of {batch_size})")
        srv.stop()
        Storage.reset()
        return round(eps, 1)


def bench_serving(state, inter):
    """Deploy the trained factors behind the real PredictionServer and
    measure the device serving path over HTTP: sequential p50/p99/QPS and
    128-async-client concurrent QPS (the micro-batcher fuses those into
    batch_predict dispatches — CreateServer.scala:523's 'TODO')."""
    import threading
    import urllib.request

    from incubator_predictionio_tpu.data.bimap import BiMap
    from incubator_predictionio_tpu.data.storage import (
        EngineInstance,
        Storage,
    )
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        ALSModel,
        RecommendationServing,
    )
    from incubator_predictionio_tpu.servers.prediction_server import (
        PredictionServer,
        ServerConfig,
    )
    from incubator_predictionio_tpu.utils.times import now_utc

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    model = ALSModel(
        user_factors=state.user_factors,   # device-resident
        item_factors=state.item_factors,
        user_bimap=BiMap({u: i for i, u in enumerate(inter.user_ids)}),
        item_bimap=BiMap({t: i for i, t in enumerate(inter.item_ids)}),
        item_years={}, item_categories={},
    )
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=RANK))
    now = now_utc()
    instance = EngineInstance(
        id="bench", status="COMPLETED", start_time=now, end_time=now,
        engine_id="bench", engine_version="1", engine_variant="bench",
        engine_factory="bench")
    server = PredictionServer.__new__(PredictionServer)
    # direct state injection: the bench measures the serving path, not the
    # checkpoint restore (engine=None is never touched by /queries.json)
    server.engine = None
    # micro_batch default = the scheduler's ladder cap
    # (PIO_SERVE_MAX_BATCH): the serving leg measures the adaptive
    # plane, not a hand-pinned fuse width; the env knob remains for
    # fixed-width comparisons
    mb = os.environ.get("PIO_BENCH_SERVE_MICRO_BATCH")
    server.config = (
        ServerConfig(ip="127.0.0.1", port=0, micro_batch=int(mb))
        if mb else ServerConfig(ip="127.0.0.1", port=0))
    from incubator_predictionio_tpu.servers.plugins import PluginContext
    from incubator_predictionio_tpu.servers.prediction_server import (
        _AsyncPoster,
        _MicroBatcher,
    )
    from incubator_predictionio_tpu.utils.http import HttpServer
    from incubator_predictionio_tpu.workflow.workflow import (
        make_runtime_context,
    )
    server.plugin_context = PluginContext()
    server.ctx = make_runtime_context(None)
    server._lock = threading.Lock()
    server.engine_instance = instance
    server.engine_params = None
    server.algorithms = [algo]
    server.serving = RecommendationServing()
    server.models = [model]
    server.start_time = now
    server.request_count = 0
    server.avg_serving_sec = 0.0
    server.last_serving_sec = 0.0
    server.max_batch_served = 0
    server._conf_server_key = None
    server.http = HttpServer(server._build_router(), "127.0.0.1", 0)
    server._speed_overlays = []
    # shed=False: this leg measures raw device serving throughput, and
    # its closed-loop burst deliberately drives queue depths whose
    # projection would cross the default serve_p99 objective — a 503
    # here would abort the whole child leg (the load loop raises on
    # non-200). Shed behavior is bench_fleet's jurisdiction.
    server._batcher = _MicroBatcher(server._handle_batch,
                                    server.config.micro_batch,
                                    workers=server.config.serve_workers,
                                    shed=False)
    server._feedback_poster = _AsyncPoster("feedback")
    server._log_poster = _AsyncPoster("log", workers=1)
    port = server.http.start_background()

    def query_once(user: str) -> None:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json",
            data=json.dumps({"user": user, "num": 10}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            resp.read()

    # warm the serving dispatch (compiles the scoring kernels)
    query_once("u1")
    query_once("u2")

    # sequential latency distribution
    n_seq = int(os.environ.get("PIO_BENCH_SERVE_N", 200))
    lat = []
    t_seq0 = time.perf_counter()
    for i in range(n_seq):
        t0 = time.perf_counter()
        query_once(f"u{i % N_USERS}")
        lat.append(time.perf_counter() - t0)
    seq_wall = time.perf_counter() - t_seq0
    lat_ms = np.sort(np.asarray(lat)) * 1e3
    p50 = float(lat_ms[int(0.50 * (n_seq - 1))])
    p99 = float(lat_ms[int(0.99 * (n_seq - 1))])
    qps_seq = n_seq / seq_wall

    # concurrent: async keep-alive clients (thread-per-client load
    # generators are GIL-bound ~400 QPS and under-measure the server; 128
    # async connections measured best — 647 vs 426 at 64 and 281 at 256);
    # the micro-batcher fuses the in-flight queries
    n_clients = int(os.environ.get("PIO_BENCH_SERVE_CLIENTS", 128))
    per_client = int(os.environ.get("PIO_BENCH_SERVE_CONC", 25))
    # warm the batched kernel shapes (powers of two up to the PADDED batch
    # cap — batch_score_top_k pads B to the next power of two, so a
    # non-power-of-two micro_batch still lands on 1 << ceil(log2(cap))) so
    # the concurrent window measures serving, not XLA compiles
    from incubator_predictionio_tpu.models.recommendation.engine import Query
    cap = 1 << max(server.config.micro_batch - 1, 0).bit_length()
    size = 1
    while size <= cap:
        algo.batch_predict(model, [
            (i, Query(user=f"u{i % N_USERS}", num=10)) for i in range(size)])
        size *= 2

    import asyncio

    async def _load() -> float:
        def bodies(cid: int):
            return (
                json.dumps({
                    "user": f"u{(cid * per_client + j) % N_USERS}",
                    "num": 10}).encode()
                for j in range(per_client)
            )
        t0 = time.perf_counter()
        # per-phase deadline replacing the old per-request urlopen timeout
        await asyncio.wait_for(
            asyncio.gather(*[
                _http_post_loop(port, "/queries.json", bodies(c))
                for c in range(n_clients)
            ]),
            timeout=max(120.0, 0.5 * n_clients * per_client))
        return time.perf_counter() - t0

    conc_wall = asyncio.run(_load())
    qps_conc = n_clients * per_client / conc_wall
    max_batch = server.max_batch_served
    log(f"serving: p50={p50:.2f}ms p99={p99:.2f}ms seq={qps_seq:.0f}qps "
        f"conc{n_clients}={qps_conc:.0f}qps max_batch={max_batch}")
    server.stop()
    Storage.reset()
    return {
        "p50_ms": round(p50, 2),
        "p99_ms": round(p99, 2),
        "qps_sequential": round(qps_seq, 1),
        "qps_concurrent": round(qps_conc, 1),
        "max_batch": int(max_batch),
    }


if __name__ == "__main__":
    if "--cpu" in sys.argv:
        run_cpu_baseline()
    elif "--shard-child" in sys.argv:
        run_shard_child()
    elif "--tpu-child" in sys.argv:
        i = sys.argv.index("--tpu-child")
        run_tpu_child(sys.argv[i + 1], sys.argv[i + 2], sys.argv[i + 3],
                      int(sys.argv[i + 4]) if len(sys.argv) > i + 4 else 0)
    else:
        run_orchestrator()
