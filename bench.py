"""Headline benchmark: the north-star metric at MovieLens-20M scale.

BASELINE.json's north star is `pio train` wall-clock + deployed query
latency on the Recommendation template at ML-20M scale (≈138k users ×
27k items, 20M ratings, rank 128) — the reference delegates training to
Spark MLlib ALS and serves queries from a driver-local factor map
(CreateServer.scala:498-650). This bench runs the full TPU-native path:

1. SEED    — 20M synthetic rating events written through the native
             columnar bulk import (eventlog.cc pio_evlog_append_interactions)
2. INGEST  — `scan_interactions` streams them back as columnar COO + id
             tables, fully in C++ (the PEvents/HBase-scan role)
3. PREP    — degree-bucketed padded rows (ops/sparse.py, the native
             csr_builder)
4. TRAIN   — fused single-dispatch ALS (ops/als.py), compile + warm timing;
             MFU from the analytic FLOP count over the warm wall-clock
5. SERVE   — the real PredictionServer (HTTP + micro-batcher): sequential
             p50 and 128-async-client concurrent QPS on the device
             serving path

Prints exactly ONE JSON line on stdout: the headline metric
(`als_ml20m_train_wall_s`, vs the measured single-core CPU baseline) plus
the sub-metrics as extra keys (ingest/seed/prep walls, mfu, serving p50 /
QPS) so the driver's parsed record carries the whole story.

`--cpu` reruns the train stage on the host CPU backend to (re)measure the
baseline constant. `PIO_BENCH_NNZ` shrinks the dataset for smoke runs.
"""

import json
import os
import sys
import time

import numpy as np

# ---------------------------------------------------------------------------
# Workload: synthetic ML-20M shape (ratings.csv of MovieLens-20M has
# 138,493 users, 26,744 movies, 20,000,263 ratings in 0.5..5.0 steps)
# ---------------------------------------------------------------------------
N_USERS = int(os.environ.get("PIO_BENCH_USERS", 138_493))
N_ITEMS = int(os.environ.get("PIO_BENCH_ITEMS", 26_744))
NNZ = int(os.environ.get("PIO_BENCH_NNZ", 20_000_000))
RANK = int(os.environ.get("PIO_BENCH_RANK", 128))
ITERATIONS = int(os.environ.get("PIO_BENCH_SWEEPS", 10))
#: precision schedule (ops/als.py _mixed_run): bf16 gathers + bf16 Gram
#: batches + single-pass MXU matmuls for the first BF16_SWEEPS sweeps, f32
#: HIGHEST for the rest. The bench default is ALL-bf16: at this exact
#: workload (planted rank-16 + noise 0.35, ML-20M marginals) the all-bf16
#: run measures RMSE parity with all-f32 to 4 decimals on BOTH fit
#: (0.5415 vs 0.5414) and heldout (0.5960 vs 0.5962) at 3.1x the speed
#: (scripts/als_profile.py, v5e). The engine default stays mixed
#: (iterations-2 bf16 + 2 polish) — arbitrary user data may sit far from
#: its noise floor where f32 polish matters; parity is additionally
#: guarded by tests/test_als.py planted-recovery.
BF16_SWEEPS = int(os.environ.get("PIO_BENCH_BF16_SWEEPS", ITERATIONS))
L2 = 0.1

#: Measured on this image's host CPU (JAX CPU backend, warm compile cache)
#: via `python bench.py --cpu` — the stand-in for the reference's
#: single-box Spark-MLlib driver (Spark 1.4 cannot run here; historically
#: it is far slower than a native CPU solver, so this bar is conservative).
#: Value = warm fused-train wall-clock at the full ML-20M shape above with
#: the same CG solver (measured 2026-07-29).
CPU_BASELINE_TRAIN_S = float(os.environ.get("PIO_BENCH_CPU_BASELINE", 571.1))

#: TPU v5e peak: 197 TFLOP/s bf16 / ~98.5 TFLOP/s fp32 on the MXU. The
#: JSON reports BOTH conventions: `mfu` against the fp32 peak (the series
#: every prior round reported — comparable across rounds) and
#: `mfu_bf16_peak` against the bf16 peak, which is the honest utilization
#: figure when the schedule runs all-bf16 sweeps.
PEAK_FLOPS_F32 = float(os.environ.get("PIO_BENCH_PEAK_FLOPS", 98.5e12))
PEAK_FLOPS_BF16 = float(os.environ.get("PIO_BENCH_PEAK_FLOPS_BF16", 197e12))


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


#: planted ground truth: ratings = 3.5 + U·Vᵀ + N(0, NOISE_SIGMA) with a
#: rank-PLANT_RANK U, V. The solver (rank 128 ⊇ 16) can recover the
#: structure, so heldout RMSE has a KNOWN floor (= NOISE_SIGMA) and
#: ranking quality a known ceiling — the r3 verdict's "model quality is
#: asserted, not proven" fix. Marginals stay the r3 power-law (identical
#: bucket shapes → timing comparability across rounds).
PLANT_RANK = int(os.environ.get("PIO_BENCH_PLANT_RANK", 16))
NOISE_SIGMA = float(os.environ.get("PIO_BENCH_NOISE_SIGMA", 0.35))
N_HOLDOUT = int(os.environ.get("PIO_BENCH_HOLDOUT", 200_000))


def _sample_pairs(rng, n):
    """Power-law item popularity matching ML-20M's marginals: the real
    ratings.csv tops out at ≈67k ratings for the most-rated movie; an
    i^-0.55 profile over 27k items puts the top item at ≈90k of 20M —
    same order, and it exercises the heavy-row (split-segment) solver.
    Users get a milder i^-0.3 tail (ML-20M users are min-20, median ≈70,
    max ≈9.3k ratings)."""
    iw = (np.arange(N_ITEMS) + 1.0) ** -0.55
    items = rng.choice(N_ITEMS, n, p=iw / iw.sum()).astype(np.int32)
    uw = (np.arange(N_USERS) + 1.0) ** -0.3
    users = rng.choice(N_USERS, n, p=uw / uw.sum()).astype(np.int32)
    return users, items


def make_dataset(rng):
    """→ (users, items, ratings, heldout (u, i, r), true (U, V)). The
    heldout pairs are fresh draws from the same ground truth — never
    stored, never trained on."""
    u_true = rng.normal(0, 1.0 / np.sqrt(PLANT_RANK),
                        (N_USERS, PLANT_RANK)).astype(np.float32)
    v_true = rng.normal(0, 1.0, (N_ITEMS, PLANT_RANK)).astype(np.float32)

    def rate(users, items):
        signal = np.einsum("nk,nk->n", u_true[users], v_true[items])
        return (3.5 + signal
                + rng.normal(0, NOISE_SIGMA, len(users))).astype(np.float32)

    users, items = _sample_pairs(rng, NNZ)
    ho_u, ho_i = _sample_pairs(rng, N_HOLDOUT)
    return (users, items, rate(users, items),
            (ho_u, ho_i, rate(ho_u, ho_i)), (u_true, v_true))


def quality_metrics(state, inter, heldout, truth, rng):
    """Heldout RMSE vs the known noise floor + precision@10 against the
    ground-truth ranking (sampled users, device-scored).

    The trained factors live in the event-log scan's FIRST-SEEN id order
    (``inter.user_ids``/``inter.item_ids``), not the seed's original
    integer order — translate every ground-truth index through the
    interned id tables before touching the model, or the metrics score a
    permutation of the model (the exact bug this comment guards against:
    p@10 ≈ 10/N_ITEMS ≈ 0)."""
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import als

    ho_u, ho_i, ho_r = heldout
    u_true, v_true = truth
    # IdTable caches its id→index dict on first .index(); reuse it instead
    # of building a parallel lookup (the scan's tables serve the server too)
    u_tab, i_tab = inter.user_ids, inter.item_ids
    u_scan = np.asarray([
        u_tab.index(s) if s in u_tab else -1
        for s in (f"u{k}" for k in range(N_USERS))])
    i_scan = np.asarray([
        i_tab.index(s) if s in i_tab else -1
        for s in (f"i{k}" for k in range(N_ITEMS))])

    # heldout pairs whose user/item never appeared in training have no
    # factor row (possible at smoke-test NNZ); score only the rest
    mask = (u_scan[ho_u] >= 0) & (i_scan[ho_i] >= 0)
    heldout_rmse = als.rmse(
        state, u_scan[ho_u[mask]], i_scan[ho_i[mask]], ho_r[mask])

    # ranking quality over the trainable universe: items present in
    # training (nothing can recommend an item it never saw)
    present_items = np.flatnonzero(i_scan >= 0)
    probe_pool = np.flatnonzero(u_scan >= 0)
    n_probe = min(1000, len(probe_pool))
    probe = rng.choice(probe_pool, n_probe, replace=False)
    true_scores = u_true[probe] @ v_true[present_items].T   # [P, Ip] host
    true_top = np.argsort(-true_scores, axis=1)[:, :10]
    # gather present-item factors in original-item order BEFORE the matmul:
    # everything stays on device in [P, Ip] and dropped columns never score
    probe_factors = jnp.take(
        state.user_factors, jnp.asarray(u_scan[probe]), axis=0)
    present_factors = jnp.take(
        state.item_factors, jnp.asarray(i_scan[present_items]), axis=0)
    model_top = np.asarray(jax.lax.top_k(
        probe_factors @ present_factors.T, 10)[1])
    hits = np.mean([
        len(set(a.tolist()) & set(b.tolist())) / 10.0
        for a, b in zip(model_top, true_top)
    ])
    return float(heldout_rmse), float(hits)


def als_flops_per_run(bf16_sweeps: int = None) -> float:
    """Analytic FLOPs of the fused training run.

    Per half-sweep over `nnz` observations with rank K: the Gram batch is
    2·nnz·K² MACs = 4·nnz·K² FLOPs at HIGHEST precision (the f32 multi-pass
    costs ~3× a bf16 pass; counted at face value — conservative), the rhs
    2·nnz·K, and each of the `rows` CG solves ~iters·2·K² FLOPs (the
    batched-matvec Jacobi-PCG in ops/als.py — about the same count as a
    direct K³/3 Cholesky at K=128, iters=32). Both sides per sweep,
    ITERATIONS sweeps.
    """
    from incubator_predictionio_tpu.ops import als

    k = float(RANK)
    per_side_gram = 2.0 * NNZ * k * k * 2.0   # multiply+add
    per_side_rhs = 2.0 * NNZ * k
    if als._SOLVER == "cg":
        # count the CG budget each phase actually runs (bf16 sweeps use the
        # loose _CG_ITERS_BF16 budget, polish sweeps the full one)
        if bf16_sweeps is None:
            bf16_sweeps = BF16_SWEEPS
        bf16 = min(max(bf16_sweeps, 0), ITERATIONS)
        iters = (bf16 * min(als._CG_ITERS_BF16, als._CG_ITERS)
                 + (ITERATIONS - bf16) * als._CG_ITERS) / max(ITERATIONS, 1)
        per_solve = iters * 2.0 * k * k
    else:
        per_solve = k ** 3 / 3.0 + 2.0 * k * k
    solves = (N_USERS + N_ITEMS) * per_solve
    per_sweep = 2.0 * per_side_gram + 2.0 * per_side_rhs + solves
    return per_sweep * ITERATIONS


def seed_store(tmpdir, users, items, ratings):
    """Write NNZ rating events through the native columnar bulk import."""
    from incubator_predictionio_tpu.data.storage import StorageClientConfig
    from incubator_predictionio_tpu.data.storage import cpplog
    from incubator_predictionio_tpu.data.storage.base import (
        IdTable,
        Interactions,
    )

    cfg = StorageClientConfig(properties={"PATH": tmpdir})
    client = cpplog.StorageClient(cfg)
    events = cpplog.CppLogEvents(client, cfg, prefix="bench_")
    user_tab = IdTable.from_list([f"u{k}" for k in range(N_USERS)])
    item_tab = IdTable.from_list([f"i{k}" for k in range(N_ITEMS)])
    inter = Interactions(
        user_idx=users, item_idx=items, values=ratings,
        user_ids=user_tab, item_ids=item_tab,
    )
    t0 = time.perf_counter()
    n = events.import_interactions(
        inter, 1, event_name="rate", value_prop="rating",
        base_time=None)
    seed_s = time.perf_counter() - t0
    assert n == len(users)
    return events, client, seed_s


def _wait_for_accelerator(total_s: float) -> None:
    """Bounded wait for device init instead of an indefinite hang.

    PJRT client construction blocks forever while another process (or a
    stale lease) holds a single-tenant chip. The bench retries init on
    daemon threads — a stale lease usually expires within minutes — and
    exits with a diagnosis if the window (PIO_BENCH_ACCEL_WAIT_S) runs
    out, so the driver gets a failed bench, not a wedged one. (The CLI's
    cli/main.py _ensure_accelerator is the single-attempt sibling: same
    probe, but an interactive command should fail fast, not sit in a
    retry loop.)"""
    import threading

    deadline = time.monotonic() + total_s
    attempt = 0
    while True:
        attempt += 1
        done = threading.Event()
        err: list = []

        def probe() -> None:
            try:
                import jax

                jax.devices()
            except Exception as e:
                err.append(e)
            finally:
                done.set()

        threading.Thread(target=probe, daemon=True).start()
        if done.wait(min(120.0, max(deadline - time.monotonic(), 1.0))):
            if not err:
                return
            # a raised error is permanent (missing driver, bad config) —
            # only a *blocked* init suggests a lease that may expire
            log(f"accelerator init failed: {err[0]}; aborting")
            raise SystemExit(3)
        log(f"accelerator init still blocked (attempt {attempt}) — "
            "likely a stale chip lease; retrying")
        if time.monotonic() >= deadline:
            log(f"accelerator unavailable after {total_s:.0f}s; aborting")
            raise SystemExit(3)
        time.sleep(10)


def run(platform_cpu: bool = False) -> None:
    import tempfile

    if platform_cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        _wait_for_accelerator(
            float(os.environ.get("PIO_BENCH_ACCEL_WAIT_S", "1200")))
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import als

    rng = np.random.default_rng(7)
    # --cpu forces the all-f32 schedule (BASELINE.md convention); report
    # the schedule the run actually measures
    eff_bf16 = 0 if platform_cpu else BF16_SWEEPS
    log(f"dataset: {N_USERS}x{N_ITEMS}, nnz={NNZ}, rank={RANK}, "
        f"sweeps={ITERATIONS} ({eff_bf16} bf16 + "
        f"{ITERATIONS - eff_bf16} f32-polish), planted rank "
        f"{PLANT_RANK} + noise {NOISE_SIGMA}")
    users, items, ratings, heldout, truth = make_dataset(rng)

    with tempfile.TemporaryDirectory(prefix="pio_bench_") as tmpdir:
        # -- 1. SEED: native columnar bulk import --------------------------
        events, client, seed_s = seed_store(tmpdir, users, items, ratings)
        log(f"seed: {NNZ} events in {seed_s:.1f}s "
            f"({NNZ / seed_s / 1e6:.2f}M ev/s)")

        # -- 2. INGEST: columnar scan back out of the event store ----------
        # the bulk import just materialized the training projection
        # (data/storage/traincache.py), so this scan measures the real
        # warm-train read path: projection load + empty-tail check. Set
        # PIO_TRAINCACHE_MIN_NNZ above NNZ to measure the cold full scan.
        t0 = time.perf_counter()
        inter = events.scan_interactions(
            app_id=1, entity_type="user", target_entity_type="item",
            event_names=("rate",), value_prop="rating")
        ingest_s = time.perf_counter() - t0
        assert len(inter) == NNZ, len(inter)
        log(f"ingest scan: {ingest_s:.1f}s ({NNZ / ingest_s / 1e6:.2f}M ev/s)")
        client.close()

    # -- 3. PREP: degree-bucketed padded rows ------------------------------
    from incubator_predictionio_tpu.ops.sparse import build_both_sides

    # dims come from the scan's interned id tables (dense, first-seen order)
    n_users, n_items = len(inter.user_ids), len(inter.item_ids)
    t0 = time.perf_counter()
    (u_light, u_heavy), (i_light, i_heavy) = build_both_sides(
        inter.user_idx, inter.item_idx, inter.values, n_users, n_items)
    prep_s = time.perf_counter() - t0
    log(f"prep (bucketed padded rows): {prep_s:.1f}s "
        f"(users={n_users}, items={n_items})")

    # -- 4. TRAIN: fused single-dispatch ALS -------------------------------
    u_tree, i_tree = als._buckets_tree(u_light), als._buckets_tree(i_light)
    u_hv, i_hv = als._heavy_tree(u_heavy), als._heavy_tree(i_heavy)

    # the CPU baseline is all-f32 BY CONVENTION (BASELINE.md): bf16 is
    # emulated (slower) on the host, so letting the bf16 schedule leak
    # into a --cpu re-measure would inflate vs_baseline unfairly
    bf16_sweeps = eff_bf16

    def train(state0):
        out = als._mixed_run(
            state0, u_tree, i_tree, L2, ITERATIONS, bf16_sweeps, True,
            jnp.float32, jax.lax.Precision.HIGHEST,
            user_heavy=u_hv, item_heavy=i_hv)
        # sync via a dependent 1-element device fetch: on the tunneled
        # platform jax.block_until_ready returns before execution finishes
        # (verified empirically), which silently turns the timer into a
        # dispatch-latency measurement
        np.asarray(out.user_factors[0:1, 0:1])
        np.asarray(out.item_factors[0:1, 0:1])
        return out

    # persistent compile cache: a FRESH directory so the first compile is
    # honestly cold (and writes the entry); clearing the in-memory
    # executable cache then forces a re-trace that must hit the persistent
    # entry — the compile cost every pio process after the first pays.
    # Both compile numbers subtract the warm execution time (each timed
    # call runs the full training once), so they are pure compile cost.
    from incubator_predictionio_tpu.utils import compile_cache

    import atexit
    import shutil

    xla_cache_dir = tempfile.mkdtemp(prefix="pio_bench_xla_")
    atexit.register(shutil.rmtree, xla_cache_dir, True)
    compile_cache.enable(xla_cache_dir)

    t0 = time.perf_counter()
    state = train(als.als_init(jax.random.key(0), n_users, n_items, RANK))
    first_call_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    state = train(als.als_init(jax.random.key(0), n_users, n_items, RANK))
    train_s = time.perf_counter() - t0
    compile_s = max(first_call_s - train_s, 0.0)
    cache_engaged = bool(os.listdir(xla_cache_dir))
    compile_warm_cache_s = None
    if cache_engaged:
        jax.clear_caches()  # drop in-memory executables; cache dir stays
        t0 = time.perf_counter()
        state = train(als.als_init(jax.random.key(0), n_users, n_items,
                                   RANK))
        compile_warm_cache_s = round(
            max(time.perf_counter() - t0 - train_s, 0.0), 1)
        log(f"compile: cold={compile_s:.1f}s warm-persistent-cache="
            f"{compile_warm_cache_s}s (dir {xla_cache_dir})")
    else:
        # PIO_COMPILE_CACHE=off in the environment, or the cache was
        # rejected: do NOT publish a second cold compile as "warm"
        log("compile: persistent cache did not engage "
            "(PIO_COMPILE_CACHE=off or cache rejected); "
            f"cold={compile_s:.1f}s")
    fit = als.rmse(state, inter.user_idx, inter.item_idx, inter.values)
    flops = als_flops_per_run(bf16_sweeps)
    mfu = flops / train_s / PEAK_FLOPS_F32
    mfu_bf16 = flops / train_s / PEAK_FLOPS_BF16
    heldout_rmse, prec10 = quality_metrics(state, inter, heldout, truth, rng)
    log(f"device={jax.devices()[0]} compile={compile_s:.1f}s "
        f"warm={train_s:.2f}s rmse={fit:.3f} "
        f"heldout_rmse={heldout_rmse:.3f} (noise floor {NOISE_SIGMA}) "
        f"p@10={prec10:.3f} flops={flops:.3e} mfu={mfu:.3f}")

    if platform_cpu:
        log(f"CPU baseline measured: warm train = {train_s:.1f}s "
            "(update CPU_BASELINE_TRAIN_S)")
        print(json.dumps({
            "metric": "als_ml20m_train_wall_s_cpu",
            "value": round(train_s, 2),
            "unit": "s",
            "vs_baseline": 1.0,
        }))
        return

    # -- 5. ATTENTION: driver-verified long-context kernel numbers ---------
    attn = bench_attention()

    # -- 6. INGEST-HTTP: the real EventServer REST batch path --------------
    ingest_http_eps = bench_ingest_http()

    # -- 7. SERVE: the real PredictionServer (HTTP + micro-batcher) --------
    serve = bench_serving(state, inter)

    print(json.dumps({
        "metric": "als_ml20m_train_wall_s",
        "value": round(train_s, 3),
        "unit": "s",
        "vs_baseline": round(CPU_BASELINE_TRAIN_S / train_s, 1),
        "train_rmse": round(float(fit), 3),
        # planted-ground-truth quality (r3 verdict item 5): heldout pairs
        # are fresh draws from the same rank-PLANT_RANK truth, so the
        # recoverable floor is exactly the noise sigma; precision@10 is
        # measured against the TRUE ranking, not observed interactions
        "heldout_rmse": round(heldout_rmse, 3),
        "noise_floor": NOISE_SIGMA,
        "precision_at_10_vs_truth": round(prec10, 3),
        "mfu": round(mfu, 4),
        "mfu_bf16_peak": round(mfu_bf16, 4),
        "compile_s_cold": round(compile_s, 1),
        "compile_s_warm_cache": compile_warm_cache_s,
        "seed_wall_s": round(seed_s, 1),
        "ingest_wall_s": round(ingest_s, 1),
        "prep_wall_s": round(prep_s, 1),
        # the user-visible `pio train` wall: storage read + host prep +
        # the fused device training run (VERDICT r3 item 2)
        "e2e_train_wall_s": round(ingest_s + prep_s + train_s, 1),
        "ingest_http_eps": ingest_http_eps,
        **attn,
        "serve_p50_ms": serve["p50_ms"],
        "serve_p99_ms": serve["p99_ms"],
        "serve_qps": serve["qps_sequential"],
        "serve_qps_concurrent": serve["qps_concurrent"],
        "serve_max_batch": serve["max_batch"],
        "nnz": NNZ,
        "rank": RANK,
        "sweeps": ITERATIONS,
        "bf16_sweeps": BF16_SWEEPS,
    }))


def bench_attention():
    """Driver-verified attention numbers (r3 verdict item 9): flash
    (Pallas) vs the XLA blockwise scan at 8k/32k, plus one SASRec
    train-epoch wall — so kernel claims land in BENCH json, and a Mosaic
    rejection (flash_available() False → XLA fallback serving the flash
    call via interpret-free blockwise) is visible instead of silent."""
    import jax
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops.attention import blockwise_attention
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        flash_attention,
        flash_available,
    )

    out = {"flash_kernel_active": bool(flash_available())}
    if not out["flash_kernel_active"]:
        log("attention: Mosaic rejected the flash family on this backend "
            "— XLA blockwise path serves (numbers below are XLA vs XLA)")
    h, d = 8, 64
    seqs_env = os.environ.get("PIO_BENCH_ATTN_SEQS", "8192,32768")
    # enough calls to amortize the tunneled platform's per-dispatch floor
    # (~2.7 ms amortized, ~30 ms for a short burst — a 3-call loop would
    # measure dispatch, not the kernel; the same trap round 3 fell into
    # with block_until_ready)
    reps = int(os.environ.get("PIO_BENCH_ATTN_REPS", 20))
    for s in (int(v) for v in seqs_env.split(",") if v):
        key = jax.random.key(0)
        q, k, v = (
            jax.random.normal(kk, (1, s, h, d), jnp.bfloat16)
            for kk in jax.random.split(key, 3)
        )

        def timed(fn):
            r = fn(q, k, v, causal=True)
            np.asarray(r[0:1, 0:1, 0:1, 0:1])  # dependent fetch = sync
            t0 = time.perf_counter()
            for _ in range(reps):
                r = fn(q, k, v, causal=True)
            np.asarray(r[0:1, 0:1, 0:1, 0:1])
            return (time.perf_counter() - t0) / reps

        t_flash = timed(flash_attention)
        t_xla = timed(blockwise_attention)
        out[f"attn_flash_ms_{s // 1024}k"] = round(t_flash * 1e3, 2)
        out[f"attn_xla_ms_{s // 1024}k"] = round(t_xla * 1e3, 2)
        log(f"attention S={s}: flash={t_flash * 1e3:.2f}ms "
            f"xla={t_xla * 1e3:.2f}ms ({t_xla / t_flash:.2f}x)")

    from incubator_predictionio_tpu.ops.transformer import sasrec_fit

    rng = np.random.default_rng(5)
    seqs = rng.integers(1, 2000, (512, 128)).astype(np.int32)
    t0 = time.perf_counter()
    sasrec_fit(seqs, n_items=2000, d_model=64, n_heads=2, n_layers=2,
               epochs=1, batch_size=128)
    first = time.perf_counter() - t0
    t0 = time.perf_counter()
    sasrec_fit(seqs, n_items=2000, d_model=64, n_heads=2, n_layers=2,
               epochs=1, batch_size=128)
    warm = time.perf_counter() - t0
    out["sasrec_epoch_s"] = round(warm, 2)
    log(f"sasrec: 1-epoch wall first={first:.1f}s warm={warm:.2f}s "
        f"(512x128 seqs, d=64)")
    return out


async def _http_post_loop(port, path, bodies) -> None:
    """One async keep-alive connection POSTing each body in turn — the
    shared load-generator leg of the ingest and serving benches."""
    import asyncio

    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    try:
        for body in bodies:
            writer.write(
                f"POST {path} HTTP/1.1\r\nHost: bench\r\n"
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n".encode()
                + body)
            await writer.drain()
            head = await reader.readuntil(b"\r\n\r\n")
            status_line = head.split(b"\r\n", 1)[0]
            if b" 200 " not in status_line:
                raise RuntimeError(f"request failed: {status_line!r}")
            clen = next(
                (int(line.split(b":")[1])
                 for line in head.split(b"\r\n")
                 if line.lower().startswith(b"content-length")), None)
            if clen is None:
                raise RuntimeError("response without Content-Length")
            await reader.readexactly(clen)
    finally:
        writer.close()


def bench_ingest_http():
    """REST ingest throughput through the real EventServer into the cpplog
    backend: async keep-alive clients posting 50-event batches to
    POST /batch/events.json (the contract cap, EventServer.scala:269-289's
    hot path). Returns events/s."""
    import asyncio
    import tempfile

    from incubator_predictionio_tpu.data.storage import (
        AccessKey,
        App,
        Storage,
    )
    from incubator_predictionio_tpu.servers.event_server import (
        EventServer,
        EventServerConfig,
    )

    n_clients = int(os.environ.get("PIO_BENCH_INGEST_CLIENTS", 32))
    batches_per_client = int(os.environ.get("PIO_BENCH_INGEST_BATCHES", 25))
    batch_size = 50

    with tempfile.TemporaryDirectory(prefix="pio_bench_ingest_") as tmpdir:
        Storage.configure({
            "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
            "PIO_STORAGE_SOURCES_EV_TYPE": "cpplog",
            "PIO_STORAGE_SOURCES_EV_PATH": tmpdir,
            "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
            "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
            "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EV",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
            "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
        })
        apps = Storage.get_meta_data_apps()
        app_id = apps.insert(App(0, "bench-ingest"))
        Storage.get_meta_data_access_keys().insert(
            AccessKey("benchkey", app_id))
        srv = EventServer(EventServerConfig(ip="127.0.0.1", port=0))
        port = srv.start_background()

        def batch_body(cid: int, b: int) -> bytes:
            return json.dumps([
                {
                    "event": "rate",
                    "entityType": "user",
                    "entityId": f"u{cid}_{b}_{k}",
                    "targetEntityType": "item",
                    "targetEntityId": f"i{k}",
                    "properties": {"rating": float(1 + k % 5)},
                }
                for k in range(batch_size)
            ]).encode()

        path = "/batch/events.json?accessKey=benchkey"

        async def load() -> float:
            t0 = time.perf_counter()
            await asyncio.wait_for(
                asyncio.gather(*[
                    _http_post_loop(port, path, (
                        batch_body(c, b) for b in range(batches_per_client)))
                    for c in range(n_clients)
                ]),
                timeout=600.0)
            return time.perf_counter() - t0

        wall = asyncio.run(load())
        total = n_clients * batches_per_client * batch_size
        landed = Storage.get_events().scan_interactions(
            app_id=app_id, event_names=("rate",), value_prop="rating")
        assert len(landed) == total, (len(landed), total)
        eps = total / wall
        log(f"ingest-http: {total} events in {wall:.1f}s "
            f"({eps:.0f} ev/s, {n_clients} clients x "
            f"{batches_per_client} batches of {batch_size})")
        srv.stop()
        Storage.reset()
        return round(eps, 1)


def bench_serving(state, inter):
    """Deploy the trained factors behind the real PredictionServer and
    measure the device serving path over HTTP: sequential p50/p99/QPS and
    128-async-client concurrent QPS (the micro-batcher fuses those into
    batch_predict dispatches — CreateServer.scala:523's 'TODO')."""
    import threading
    import urllib.request

    from incubator_predictionio_tpu.data.bimap import BiMap
    from incubator_predictionio_tpu.data.storage import (
        EngineInstance,
        Storage,
    )
    from incubator_predictionio_tpu.models.recommendation.engine import (
        ALSAlgorithm,
        ALSAlgorithmParams,
        ALSModel,
        RecommendationServing,
    )
    from incubator_predictionio_tpu.servers.prediction_server import (
        PredictionServer,
        ServerConfig,
    )
    from incubator_predictionio_tpu.utils.times import now_utc

    Storage.configure({
        "PIO_STORAGE_SOURCES_MEM_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_NAME": "m",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME": "e",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "MEM",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_NAME": "d",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "MEM",
    })
    model = ALSModel(
        user_factors=state.user_factors,   # device-resident
        item_factors=state.item_factors,
        user_bimap=BiMap({u: i for i, u in enumerate(inter.user_ids)}),
        item_bimap=BiMap({t: i for i, t in enumerate(inter.item_ids)}),
        item_years={}, item_categories={},
    )
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=RANK))
    now = now_utc()
    instance = EngineInstance(
        id="bench", status="COMPLETED", start_time=now, end_time=now,
        engine_id="bench", engine_version="1", engine_variant="bench",
        engine_factory="bench")
    server = PredictionServer.__new__(PredictionServer)
    # direct state injection: the bench measures the serving path, not the
    # checkpoint restore (engine=None is never touched by /queries.json)
    server.engine = None
    server.config = ServerConfig(ip="127.0.0.1", port=0)
    from incubator_predictionio_tpu.servers.plugins import PluginContext
    from incubator_predictionio_tpu.servers.prediction_server import (
        _AsyncPoster,
        _MicroBatcher,
    )
    from incubator_predictionio_tpu.utils.http import HttpServer
    from incubator_predictionio_tpu.workflow.workflow import (
        make_runtime_context,
    )
    server.plugin_context = PluginContext()
    server.ctx = make_runtime_context(None)
    server._lock = threading.Lock()
    server.engine_instance = instance
    server.engine_params = None
    server.algorithms = [algo]
    server.serving = RecommendationServing()
    server.models = [model]
    server.start_time = now
    server.request_count = 0
    server.avg_serving_sec = 0.0
    server.last_serving_sec = 0.0
    server.max_batch_served = 0
    server._conf_server_key = None
    server.http = HttpServer(server._build_router(), "127.0.0.1", 0)
    server._batcher = _MicroBatcher(server._handle_batch,
                                    server.config.micro_batch)
    server._feedback_poster = _AsyncPoster("feedback")
    server._log_poster = _AsyncPoster("log", workers=1)
    port = server.http.start_background()

    def query_once(user: str) -> None:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/queries.json",
            data=json.dumps({"user": user, "num": 10}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            resp.read()

    # warm the serving dispatch (compiles the scoring kernels)
    query_once("u1")
    query_once("u2")

    # sequential latency distribution
    n_seq = int(os.environ.get("PIO_BENCH_SERVE_N", 200))
    lat = []
    t_seq0 = time.perf_counter()
    for i in range(n_seq):
        t0 = time.perf_counter()
        query_once(f"u{i % N_USERS}")
        lat.append(time.perf_counter() - t0)
    seq_wall = time.perf_counter() - t_seq0
    lat_ms = np.sort(np.asarray(lat)) * 1e3
    p50 = float(lat_ms[int(0.50 * (n_seq - 1))])
    p99 = float(lat_ms[int(0.99 * (n_seq - 1))])
    qps_seq = n_seq / seq_wall

    # concurrent: async keep-alive clients (thread-per-client load
    # generators are GIL-bound ~400 QPS and under-measure the server; 128
    # async connections measured best — 647 vs 426 at 64 and 281 at 256);
    # the micro-batcher fuses the in-flight queries
    n_clients = int(os.environ.get("PIO_BENCH_SERVE_CLIENTS", 128))
    per_client = int(os.environ.get("PIO_BENCH_SERVE_CONC", 25))
    # warm the batched kernel shapes (powers of two up to the PADDED batch
    # cap — batch_score_top_k pads B to the next power of two, so a
    # non-power-of-two micro_batch still lands on 1 << ceil(log2(cap))) so
    # the concurrent window measures serving, not XLA compiles
    from incubator_predictionio_tpu.models.recommendation.engine import Query
    cap = 1 << max(server.config.micro_batch - 1, 0).bit_length()
    size = 1
    while size <= cap:
        algo.batch_predict(model, [
            (i, Query(user=f"u{i % N_USERS}", num=10)) for i in range(size)])
        size *= 2

    import asyncio

    async def _load() -> float:
        def bodies(cid: int):
            return (
                json.dumps({
                    "user": f"u{(cid * per_client + j) % N_USERS}",
                    "num": 10}).encode()
                for j in range(per_client)
            )
        t0 = time.perf_counter()
        # per-phase deadline replacing the old per-request urlopen timeout
        await asyncio.wait_for(
            asyncio.gather(*[
                _http_post_loop(port, "/queries.json", bodies(c))
                for c in range(n_clients)
            ]),
            timeout=max(120.0, 0.5 * n_clients * per_client))
        return time.perf_counter() - t0

    conc_wall = asyncio.run(_load())
    qps_conc = n_clients * per_client / conc_wall
    max_batch = server.max_batch_served
    log(f"serving: p50={p50:.2f}ms p99={p99:.2f}ms seq={qps_seq:.0f}qps "
        f"conc{n_clients}={qps_conc:.0f}qps max_batch={max_batch}")
    server.stop()
    Storage.reset()
    return {
        "p50_ms": round(p50, 2),
        "p99_ms": round(p99, 2),
        "qps_sequential": round(qps_seq, 1),
        "qps_concurrent": round(qps_conc, 1),
        "max_batch": int(max_batch),
    }


if __name__ == "__main__":
    run(platform_cpu="--cpu" in sys.argv)
