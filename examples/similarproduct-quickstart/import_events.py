"""Seed the similar-product quickstart (reference: examples/
scala-parallel-similarproduct/multi/data/import_eventserver.py — $set users
and items, then view/like events)."""
import argparse, json, random, urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--access-key", required=True)
    ap.add_argument("--url", default="http://127.0.0.1:7070")
    args = ap.parse_args()
    random.seed(5)
    events = [{"event": "$set", "entityType": "user", "entityId": f"u{i}"}
              for i in range(10)]
    events += [{"event": "$set", "entityType": "item", "entityId": f"i{i}",
                "properties": {"categories": [f"c{i % 4}", f"c{(i + 1) % 4}"]}}
               for i in range(50)]
    for u in range(10):
        for i in random.sample(range(50), 10):
            events.append({"event": "view", "entityType": "user",
                           "entityId": f"u{u}", "targetEntityType": "item",
                           "targetEntityId": f"i{i}"})
        for i in random.sample(range(50), 3):
            events.append({"event": "like", "entityType": "user",
                           "entityId": f"u{u}", "targetEntityType": "item",
                           "targetEntityId": f"i{i}"})
    for s in range(0, len(events), 50):
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            json.dumps(events[s:s + 50]).encode(),
            {"Content-Type": "application/json"})
        urllib.request.urlopen(req)
    print(f"imported {len(events)} events")


if __name__ == "__main__":
    main()
