"""Seed the recommended-user quickstart (reference: examples/
scala-parallel-similarproduct/recommended-user/data/import_eventserver.py —
$set users, then user-follows-user events)."""
import argparse, json, random, urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--access-key", required=True)
    ap.add_argument("--url", default="http://127.0.0.1:7070")
    args = ap.parse_args()
    random.seed(7)
    events = [{"event": "$set", "entityType": "user", "entityId": f"u{i}"}
              for i in range(50)]
    # two loose communities plus a few random cross-edges
    for u in range(50):
        peers = range(0, 25) if u < 25 else range(25, 50)
        for v in random.sample([p for p in peers if p != u], 8):
            events.append({"event": "follow", "entityType": "user",
                           "entityId": f"u{u}", "targetEntityType": "user",
                           "targetEntityId": f"u{v}"})
        if random.random() < 0.2:
            other = random.randrange(25, 50) if u < 25 else random.randrange(25)
            events.append({"event": "follow", "entityType": "user",
                           "entityId": f"u{u}", "targetEntityType": "user",
                           "targetEntityId": f"u{other}"})
    for s in range(0, len(events), 50):
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            json.dumps(events[s:s + 50]).encode(),
            {"Content-Type": "application/json"})
        urllib.request.urlopen(req)
    print(f"imported {len(events)} events")


if __name__ == "__main__":
    main()
