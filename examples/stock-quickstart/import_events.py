"""Seed the stock quickstart: 60 trading days of synthetic prices for 8
tickers + the SPY market ticker (parity: scala-stock's YahooDataSource
panel shape)."""
import argparse, datetime, json, math, random, urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--access-key", required=True)
    ap.add_argument("--url", default="http://127.0.0.1:7070")
    args = ap.parse_args()
    random.seed(0)
    batch_url = f"{args.url}/batch/events.json?accessKey={args.access_key}"
    tickers = ["SPY"] + [f"T{k}" for k in range(8)]
    price = {t: 100.0 for t in tickers}
    start = datetime.date(2024, 3, 1)
    events = []
    for day in range(60):
        when = (start + datetime.timedelta(days=day)).isoformat()
        for t in tickers:
            price[t] *= math.exp(random.gauss(0.0003, 0.01))
            events.append({
                "event": "price", "entityType": "ticker", "entityId": t,
                "properties": {"price": round(price[t], 4)},
                "eventTime": f"{when}T00:00:00.000Z",
            })
    for s in range(0, len(events), 50):
        req = urllib.request.Request(
            batch_url, json.dumps(events[s:s + 50]).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            # per-event statuses ride inside the 200 batch response
            for i, st in enumerate(json.load(resp)):
                if st.get("status") != 201:
                    raise SystemExit(f"event {s + i} failed: {st}")
    print(f"seeded {len(events)} price events for {len(tickers)} tickers")


if __name__ == "__main__":
    main()
