"""Seed the recommendation quickstart (reference: examples/
scala-parallel-recommendation/custom-query/data/import_eventserver.py —
rate events, MovieLens-style)."""
import argparse, json, random, urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--access-key", required=True)
    ap.add_argument("--url", default="http://127.0.0.1:7070")
    args = ap.parse_args()
    random.seed(0)
    events = []
    for u in range(30):
        for i in random.sample(range(60), 12):
            events.append({"event": "rate", "entityType": "user",
                           "entityId": f"u{u}", "targetEntityType": "item",
                           "targetEntityId": f"i{i}",
                           "properties": {"rating": float(random.randint(1, 5))}})
    for s in range(0, len(events), 50):
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            json.dumps(events[s:s + 50]).encode(),
            {"Content-Type": "application/json"})
        urllib.request.urlopen(req)
    print(f"imported {len(events)} rate events")


if __name__ == "__main__":
    main()
