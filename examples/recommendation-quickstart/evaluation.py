"""Quickstart evaluation objects (parity: the Evaluation.scala +
EngineParamsList of the integration-test recommendation engine).

Run with:
    pio eval evaluation:evaluation evaluation:engine_params_generator
"""

from incubator_predictionio_tpu.core import EngineParams
from incubator_predictionio_tpu.core.evaluation import Evaluation
from incubator_predictionio_tpu.core.params import EngineParamsGenerator
from incubator_predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams,
    DataSourceParams,
    RecommendationEngine,
)
from incubator_predictionio_tpu.models.recommendation.engine import PrecisionAtK

evaluation = Evaluation()
evaluation.engine_metric = (RecommendationEngine().apply(), PrecisionAtK(k=5))


class _Generator(EngineParamsGenerator):
    engine_params_list = [
        EngineParams(
            data_source_params=(
                "", DataSourceParams(app_name="MyApp1", eval_k=2)
            ),
            algorithm_params_list=[
                ("als", ALSAlgorithmParams(rank=rank, num_iterations=8,
                                           lambda_=0.05, seed=3))
            ],
        )
        for rank in (4, 8)
    ]


engine_params_generator = _Generator()
