"""Seed the sequence quickstart: per-user time-ordered view/buy sessions
(no reference counterpart — the reference's closest capability is the
MarkovChain template; this feeds the SASRec-style session model)."""
import argparse, json, random, urllib.request
from datetime import datetime, timedelta, timezone


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--access-key", required=True)
    ap.add_argument("--url", default="http://127.0.0.1:7070")
    args = ap.parse_args()
    random.seed(11)
    t0 = datetime(2021, 6, 1, tzinfo=timezone.utc)
    events = []
    for u in range(40):
        # sessions walk a ring of items so there is sequence signal to learn
        start = random.randint(0, 29)
        for step in range(random.randint(4, 12)):
            item = (start + step) % 30
            events.append({
                "event": "buy" if step % 4 == 3 else "view",
                "entityType": "user", "entityId": f"u{u}",
                "targetEntityType": "item", "targetEntityId": f"i{item}",
                "eventTime": (t0 + timedelta(minutes=u * 60 + step))
                             .isoformat(),
            })
    for s in range(0, len(events), 50):  # EventServer batch cap is 50
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            json.dumps(events[s:s + 50]).encode(),
            {"Content-Type": "application/json"})
        urllib.request.urlopen(req)
    print(f"imported {len(events)} session events")


if __name__ == "__main__":
    main()
