"""Seed the e-commerce quickstart (reference: examples/
scala-parallel-ecommercerecommendation/data/import_eventserver.py — $set
items with categories, view/buy events)."""
import argparse, json, random, urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--access-key", required=True)
    ap.add_argument("--url", default="http://127.0.0.1:7070")
    args = ap.parse_args()
    random.seed(7)
    events = [{"event": "$set", "entityType": "item", "entityId": f"i{i}",
               "properties": {"categories": [f"c{i % 5}"]}}
              for i in range(60)]
    for u in range(12):
        for i in random.sample(range(60), 12):
            events.append({"event": "view", "entityType": "user",
                           "entityId": f"u{u}", "targetEntityType": "item",
                           "targetEntityId": f"i{i}"})
        for i in random.sample(range(60), 3):
            events.append({"event": "buy", "entityType": "user",
                           "entityId": f"u{u}", "targetEntityType": "item",
                           "targetEntityId": f"i{i}"})
    for s in range(0, len(events), 50):
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            json.dumps(events[s:s + 50]).encode(),
            {"Content-Type": "application/json"})
        urllib.request.urlopen(req)
    print(f"imported {len(events)} events")


if __name__ == "__main__":
    main()
