"""Seed labeled points for the regression quickstart.

Writes `$set` events on `point` entities carrying `label` + `features`
properties — the event-store form of the reference examples' lr_data.txt
rows (label f0 f1 ...). Usage:

    python import_points.py --access-key KEY [--url http://localhost:7070]
"""

import argparse
import json
import urllib.request

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--access-key", required=True)
    ap.add_argument("--url", default="http://localhost:7070")
    ap.add_argument("--n", type=int, default=200)
    args = ap.parse_args()
    rng = np.random.default_rng(0)
    w = np.array([2.0, -1.0, 0.5])
    events = []
    for i in range(args.n):
        x = rng.normal(size=3)
        y = float(x @ w + 0.7 + rng.normal(0, 0.1))
        events.append({
            "event": "$set",
            "entityType": "point",
            "entityId": f"p{i}",
            "properties": {"label": y, "features": [float(v) for v in x]},
        })
    for s in range(0, len(events), 50):
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            data=json.dumps(events[s:s + 50]).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 200
    print(f"imported {len(events)} labeled points")


if __name__ == "__main__":
    main()
