"""Seed the classification quickstart (reference: examples/
scala-parallel-classification/.../data/import_eventserver.py — $set events
carrying the attr0-2 features and the 'plan' label)."""
import argparse, json, random, urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--access-key", required=True)
    ap.add_argument("--url", default="http://127.0.0.1:7070")
    ap.add_argument("--n", type=int, default=200)
    args = ap.parse_args()
    random.seed(3)
    events = []
    for i in range(args.n):
        plan = random.randint(0, 2)
        events.append({
            "event": "$set", "entityType": "user", "entityId": f"u{i}",
            "properties": {
                "attr0": plan * 10 + random.randint(0, 9),
                "attr1": random.randint(0, 5) + plan,
                "attr2": random.randint(0, 3),
                "plan": plan,
            },
        })
    for s in range(0, len(events), 50):  # EventServer batch cap is 50
        req = urllib.request.Request(
            f"{args.url}/batch/events.json?accessKey={args.access_key}",
            json.dumps(events[s:s + 50]).encode(),
            {"Content-Type": "application/json"})
        urllib.request.urlopen(req)
    print(f"imported {len(events)} $set user events")


if __name__ == "__main__":
    main()
