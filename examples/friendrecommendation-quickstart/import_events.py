"""Seed the friend-recommendation quickstart (parity: the KDD-cup style
user/item keyword + follow/action graph of
examples/experimental/scala-parallel-friend-recommendation)."""
import argparse, json, random, urllib.request


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--access-key", required=True)
    ap.add_argument("--url", default="http://127.0.0.1:7070")
    args = ap.parse_args()
    random.seed(0)
    batch_url = f"{args.url}/batch/events.json?accessKey={args.access_key}"
    vocab = [f"kw{k}" for k in range(40)]

    def keywords():
        # keyword → weight map, the KDD-cup shape the reference ingests
        # (a bare keyword list also works: uniform weight 1.0)
        return {k: round(random.random(), 3)
                for k in random.sample(vocab, 6)}

    events = []
    for u in range(25):
        events.append({"event": "$set", "entityType": "user",
                       "entityId": f"u{u}",
                       "properties": {"keywords": keywords()}})
    for i in range(30):
        events.append({"event": "$set", "entityType": "item",
                       "entityId": f"i{i}",
                       "properties": {"keywords": keywords()}})
    for u in range(25):
        for i in random.sample(range(30), 4):
            events.append({"event": "action", "entityType": "user",
                           "entityId": f"u{u}", "targetEntityType": "item",
                           "targetEntityId": f"i{i}", "properties": {}})
    for s in range(0, len(events), 50):  # the batch endpoint's cap
        req = urllib.request.Request(
            batch_url, json.dumps(events[s:s + 50]).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as resp:
            # a 200 batch response carries PER-EVENT statuses; a partial
            # failure must not look like a successful seed
            for i, st in enumerate(json.load(resp)):
                if st.get("status") != 201:
                    raise SystemExit(
                        f"event {s + i} failed: {st}")
    print("seeded 25 users, 30 items, 100 action edges")


if __name__ == "__main__":
    main()
