"""Recommendation engine over an EXTERNAL data source — a CSV directory
read directly by the DataSource, bypassing the event store entirely.

Parity: the reference demonstrates swapping PEventStore for a third-party
source in examples/experimental/scala-parallel-recommendation-custom-
datasource (DataSource.scala reads ratings from a custom RDD) and the
mongo-datasource variant (same pattern against MongoDB). The extension
point is identical here: a DataSource subclass owns `read_training`
outright — nothing obliges it to touch `EventStore`. This worked example
reads `<dir>/*.csv` lines of `user,item,rating` and trains the same
TPU ALS stack the event-store template uses (ops/als.py fused sweeps,
ops/topk.py MXU scoring), so everything downstream — `pio train`,
checkpointing, `pio deploy`, /queries.json — is unchanged.

Drive (no event server, no `pio app new` needed):

    cd examples/csv-datasource
    pio build && pio train
    pio deploy --port 8000 &
    curl -X POST http://127.0.0.1:8000/queries.json \
         -d '{"user": "u3", "num": 3}'
"""

from __future__ import annotations

import dataclasses
import glob
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from incubator_predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    Params,
)
from incubator_predictionio_tpu.data.bimap import BiMap


@dataclasses.dataclass(frozen=True)
class Query:
    user: str
    num: int = 4


@dataclasses.dataclass(frozen=True)
class ItemScore:
    item: str
    score: float


@dataclasses.dataclass(frozen=True)
class PredictedResult:
    __camel_case__ = True  # serves {"itemScores": [...]} like the reference

    item_scores: Tuple[ItemScore, ...]


@dataclasses.dataclass(frozen=True)
class CsvDataSourceParams(Params):
    #: directory of *.csv rating files (relative to the engine dir)
    dir: str = "data"


@dataclasses.dataclass
class TrainingData:
    users: np.ndarray           # [nnz] int32
    items: np.ndarray           # [nnz] int32
    ratings: np.ndarray         # [nnz] float32
    user_bimap: BiMap
    item_bimap: BiMap


class CsvDataSource(DataSource):
    """The external-source extension point: read_training owns the read.

    (The event-store templates call EventStore here instead; see
    models/recommendation/engine.py for that side of the pattern.)"""

    def __init__(self, params: CsvDataSourceParams = CsvDataSourceParams()):
        super().__init__(params)

    def read_training(self, ctx) -> TrainingData:
        files = sorted(glob.glob(os.path.join(self.params.dir, "*.csv")))
        if not files:
            raise ValueError(
                f"no *.csv rating files under {self.params.dir!r} "
                f"(cwd {os.getcwd()!r})")
        users: List[str] = []
        items: List[str] = []
        vals: List[float] = []
        for path in files:
            with open(path) as f:
                for ln, line in enumerate(f, 1):
                    line = line.strip()
                    if not line or line.startswith("#"):
                        continue
                    try:
                        u, i, r = line.split(",")
                        vals.append(float(r))
                    except ValueError as e:
                        raise ValueError(
                            f"{path}:{ln}: expected 'user,item,rating' "
                            f"(got {line!r})") from e
                    users.append(u)
                    items.append(i)
        user_bimap = BiMap.string_int(users)
        item_bimap = BiMap.string_int(items)
        return TrainingData(
            users=np.asarray([user_bimap[u] for u in users], np.int32),
            items=np.asarray([item_bimap[i] for i in items], np.int32),
            ratings=np.asarray(vals, np.float32),
            user_bimap=user_bimap,
            item_bimap=item_bimap,
        )


@dataclasses.dataclass(frozen=True)
class ALSParams(Params):
    rank: int = 16
    iterations: int = 8
    l2: float = 0.1
    seed: int = 0


@dataclasses.dataclass
class Model:
    user_factors: np.ndarray
    item_factors: np.ndarray
    user_bimap: BiMap
    item_bimap: BiMap


class CsvALSAlgorithm(Algorithm):
    params_class = ALSParams

    def __init__(self, params: ALSParams = ALSParams()):
        super().__init__(params)

    def train(self, ctx, td: TrainingData) -> Model:
        from incubator_predictionio_tpu.ops.als import als_train

        state, _ = als_train(
            td.users, td.items, td.ratings,
            n_users=len(td.user_bimap), n_items=len(td.item_bimap),
            rank=self.params.rank, iterations=self.params.iterations,
            l2=self.params.l2, seed=self.params.seed)
        return Model(
            user_factors=np.asarray(state.user_factors),
            item_factors=np.asarray(state.item_factors),
            user_bimap=td.user_bimap,
            item_bimap=td.item_bimap,
        )

    def predict(self, model: Model, query: Query) -> PredictedResult:
        from incubator_predictionio_tpu.ops.topk import score_and_top_k

        row: Optional[int] = model.user_bimap.get(query.user)
        if row is None:
            return PredictedResult(item_scores=())
        k = min(query.num, len(model.item_bimap))
        packed = np.asarray(score_and_top_k(
            model.user_factors[row], model.item_factors, k))
        inv = model.item_bimap.inverse  # BiMap[int, str]
        return PredictedResult(item_scores=tuple(
            ItemScore(item=inv[int(i)], score=float(s))
            for s, i in zip(packed[0], packed[1])
        ))


class CsvRecommendationEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            CsvDataSource, IdentityPreparator,
            {"als": CsvALSAlgorithm}, FirstServing,
        )
