"""HelloWorld engine — the reference's first tutorial
(examples/experimental/scala-local-helloworld/HelloWorld.scala): average
temperature per day-of-week from a CSV, queried by day.

A complete user-defined engine in one local file: `pio build/train/deploy`
resolve `engine:HelloWorldEngine` from this directory. Data format (the
reference's ../data/helloworld/data.csv): `Mon,75.5` per line.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from incubator_predictionio_tpu.core import (
    Algorithm,
    DataSource,
    Engine,
    EngineFactory,
    FirstServing,
    IdentityPreparator,
    Params,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext


@dataclasses.dataclass(frozen=True)
class MyQuery:
    day: str


@dataclasses.dataclass(frozen=True)
class MyPredictedResult:
    temperature: float


@dataclasses.dataclass(frozen=True)
class MyDataSourceParams(Params):
    filepath: str = "data.csv"


@dataclasses.dataclass
class MyTrainingData:
    temperatures: List[Tuple[str, float]]


class MyDataSource(DataSource):
    def __init__(self, params: MyDataSourceParams = MyDataSourceParams()):
        super().__init__(params)

    def read_training(self, ctx: RuntimeContext) -> MyTrainingData:
        rows = []
        with open(self.params.filepath) as f:
            for line in f:
                if line.strip():
                    day, temp = line.strip().split(",")
                    rows.append((day, float(temp)))
        return MyTrainingData(temperatures=rows)


@dataclasses.dataclass
class MyModel:
    temperatures: Dict[str, float]


@dataclasses.dataclass(frozen=True)
class MyAlgorithmParams(Params):
    pass


class MyAlgorithm(Algorithm):
    params_class = MyAlgorithmParams
    query_class_ = MyQuery

    def __init__(self, params: MyAlgorithmParams = MyAlgorithmParams()):
        super().__init__(params)

    def train(self, ctx: RuntimeContext, td: MyTrainingData) -> MyModel:
        sums: Dict[str, List[float]] = {}
        for day, temp in td.temperatures:
            sums.setdefault(day, []).append(temp)
        return MyModel(temperatures={
            day: sum(v) / len(v) for day, v in sums.items()
        })

    def predict(self, model: MyModel, query: MyQuery) -> MyPredictedResult:
        if query.day not in model.temperatures:
            # the reference throws on an unknown key too — a fabricated
            # 0.0° would be indistinguishable from real data
            raise ValueError(
                f"unknown day {query.day!r}; trained days: "
                f"{sorted(model.temperatures)}")
        return MyPredictedResult(temperature=model.temperatures[query.day])


class HelloWorldEngine(EngineFactory):
    def apply(self) -> Engine:
        return Engine(
            MyDataSource, IdentityPreparator, {"": MyAlgorithm},
            FirstServing,
        )
