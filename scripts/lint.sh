#!/usr/bin/env bash
# pio-lint convenience wrapper: scan the package against the checked-in
# baseline (docs/lint.md). Extra args pass through, e.g.:
#   scripts/lint.sh --select host-sync,probe-arity
#   scripts/lint.sh --write-baseline   # then hand-justify every entry
#
# CI artifact mode: set PIO_LINT_OUT=<dir> to also drop the
# machine-readable report (lint-report.json) and the text transcript
# (lint-report.txt) there, exit code preserved.
set -euo pipefail
cd "$(dirname "$0")/.."
if [[ -n "${PIO_LINT_OUT:-}" ]]; then
  mkdir -p "$PIO_LINT_OUT"
  python -m incubator_predictionio_tpu.analysis --baseline \
    --json-out "$PIO_LINT_OUT/lint-report.json" "$@" \
    | tee "$PIO_LINT_OUT/lint-report.txt"
  exit "${PIPESTATUS[0]}"
fi
exec python -m incubator_predictionio_tpu.analysis --baseline "$@"
