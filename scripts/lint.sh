#!/usr/bin/env bash
# pio-lint convenience wrapper: scan the package against the checked-in
# baseline (docs/lint.md). Extra args pass through, e.g.:
#   scripts/lint.sh --select host-sync,probe-arity
#   scripts/lint.sh --write-baseline   # then hand-justify every entry
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m incubator_predictionio_tpu.analysis --baseline "$@"
