#!/usr/bin/env python3
"""capacity_report — the bench trajectory as a capacity model + CI gate.

Ingests the repo's ``BENCH_*.json`` / ``MULTICHIP_*.json`` records
(obs/capacity.py normalizes every era's record shape and classifies
unparsed rounds into structured skip reasons), fits the
rows-per-chip-at-fixed-staleness and QPS-per-worker estimates, compares
the newest parsed record against the pinned ``CAPACITY_BASELINE.json``,
and writes the whole thing as machine-readable ``capacity.json``.

    python scripts/capacity_report.py                  # report + write
    python scripts/capacity_report.py --check          # CI gate: exit 1
                                                       # on a regression
    python scripts/capacity_report.py --json -         # payload → stdout

``--check`` fails only on a REGRESSED newest record (or an unreadable
trajectory) — skipped/degraded rounds carry their structured reasons
and pass, because an explained absence is not a regression.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from incubator_predictionio_tpu.obs import capacity  # noqa: E402


def _fmt_capacity(cap: dict) -> str:
    lines = []
    rate = cap.get("rows_per_chip_per_s")
    if rate:
        lines.append(
            f"  rows/chip/s       : {rate:,.0f}  "
            f"(from {cap['train_source_record']}, mfu={cap.get('mfu')})")
        lines.append(
            f"  rows/chip @ {cap['staleness_bound_s']:.0f}s staleness: "
            f"{cap['rows_per_chip_at_staleness']:,}")
    else:
        lines.append("  rows/chip/s       : no non-degraded training "
                     "record in the trajectory")
    qps = cap.get("qps_per_worker")
    if qps:
        lines.append(f"  QPS/worker        : {qps:,.0f}  "
                     f"(from {cap['qps_source_record']}, "
                     f"p99={cap.get('serve_p99_ms')}ms)")
    for title, proj in (cap.get("projections") or {}).items():
        lines.append(f"  {title}: "
                     + ", ".join(f"{k}→{v}" for k, v in proj.items()))
    tenants = cap.get("tenants")
    if tenants:
        pack = tenants.get("binpack") or {}
        lines.append(
            "  tenants           : "
            + ", ".join(
                f"{t}={int(q):,}qps→{tenants['workers_for_qps'][t]}w"
                for t, q in tenants["demand_qps"].items())
            + f"  (packed fleet: {pack.get('workers')} workers, "
              f"from {tenants['source_record']})")
    shard = cap.get("shard")
    if shard:
        lines.append(f"  shard leg         : {shard['devices']} devices "
                     f"({shard.get('mesh_shape')}), "
                     f"wall={shard.get('train_wall_s')}s, "
                     f"mfu={shard.get('mfu')} "
                     f"(from {shard['source_record']})")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="capacity + regression model over the checked-in "
                    "bench records")
    ap.add_argument("--repo-dir", default=_REPO,
                    help="directory holding BENCH_*/MULTICHIP_* records")
    ap.add_argument("--baseline",
                    help=f"baseline file (default: "
                         f"<repo>/{capacity.BASELINE_FILENAME})")
    ap.add_argument("--out", default="capacity.json",
                    help="output path ('-' to skip writing)")
    ap.add_argument("--staleness-s", type=float, default=None,
                    help="staleness bound for the rows/chip projection "
                         "(default: PIO_SLO_STALENESS_S or 3600)")
    ap.add_argument("--json", action="store_true",
                    help="print the full payload as JSON on stdout")
    ap.add_argument("--check", action="store_true",
                    help="CI gate: exit 1 when the newest parsed record "
                         "regressed vs the baseline")
    args = ap.parse_args(argv)

    report = capacity.capacity_report(
        args.repo_dir, baseline_path=args.baseline,
        staleness_s=args.staleness_s)
    report["generated_at"] = round(time.time(), 3)

    if args.out and args.out != "-":
        out_path = (args.out if os.path.isabs(args.out)
                    else os.path.join(os.getcwd(), args.out))
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(f"wrote {out_path}", file=sys.stderr)

    if args.json:
        json.dump(report, sys.stdout, indent=2)
        print()
    else:
        print(f"trajectory: {len(report['records'])} records")
        for rec in report["records"]:
            v = rec["verdict"]
            status = v["status"]
            extra = ""
            if status == "skipped" and rec.get("skipped_reason"):
                extra = f" ({rec['skipped_reason']['class']})"
            elif status == "regressed":
                keys = ",".join(r["key"] for r in v["regressed"])
                extra = f" ({keys})"
            print(f"  {rec['name']:<24} {status}{extra}")
        print("capacity:")
        print(_fmt_capacity(report["capacity"]))
        reg = report["regression"]
        print(f"regression: newest={reg.get('newest')} vs "
              f"baseline={reg.get('baseline')} -> {reg['status']}")

    if args.check:
        reg = report["regression"]
        if reg["status"] == "regressed":
            print("CHECK FAILED: newest record regressed vs baseline: "
                  + ", ".join(f"{r['key']} {r['baseline']}→{r['value']}"
                              for r in reg["regressed"]),
                  file=sys.stderr)
            return 1
        missing = [r["name"] for r in report["records"]
                   if r["verdict"].get("status") == "skipped"
                   and not (r["verdict"].get("reason") or {}).get("class")]
        if missing:
            print(f"CHECK FAILED: unexplained records: {missing}",
                  file=sys.stderr)
            return 1
        print("CHECK OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
