"""Per-configuration ALS schedule timing + quality at ML-20M shape.

Times the fused training run under candidate precision schedules AND
scores each against planted rank-16 ground truth (the bench's data
model), so the mixed-schedule defaults in ops/als.py are measured on
both axes — speed and RMSE parity with the all-f32 run.
Run on the real TPU. Usage: python scripts/als_profile.py [nnz]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NNZ = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000_000
N_USERS, N_ITEMS, RANK, SWEEPS = 138_493, 26_744, 128, 10
PLANT_RANK, NOISE = 16, 0.35


def main():
    from incubator_predictionio_tpu.utils.lease import install_sigterm_exit

    import jax

    # dial as a killable waiter, then make SIGTERM a clean exit so a
    # timeout-kill mid-run cannot wedge the lease we now hold
    jax.devices()
    install_sigterm_exit()
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import als
    from incubator_predictionio_tpu.ops.sparse import (
        build_padded_rows,
        split_heavy,
    )

    rng = np.random.default_rng(7)
    iw = (np.arange(N_ITEMS) + 1.0) ** -0.55
    items = rng.choice(N_ITEMS, NNZ, p=iw / iw.sum()).astype(np.int32)
    uw = (np.arange(N_USERS) + 1.0) ** -0.3
    users = rng.choice(N_USERS, NNZ, p=uw / uw.sum()).astype(np.int32)
    u_true = rng.normal(0, 1.0 / np.sqrt(PLANT_RANK),
                        (N_USERS, PLANT_RANK)).astype(np.float32)
    v_true = rng.normal(0, 1.0, (N_ITEMS, PLANT_RANK)).astype(np.float32)

    def rate(uu, ii):
        sig = np.einsum("nk,nk->n", u_true[uu], v_true[ii])
        return (3.5 + sig + rng.normal(0, NOISE, len(uu))).astype(np.float32)

    vals = rate(users, items)
    ho_u, ho_i = (rng.integers(0, N_USERS, 200_000).astype(np.int32),
                  rng.integers(0, N_ITEMS, 200_000).astype(np.int32))
    ho_r = rate(ho_u, ho_i)
    print(f"data: {NNZ} nnz, planted rank {PLANT_RANK} noise {NOISE}",
          flush=True)

    t0 = time.perf_counter()
    u_light, u_heavy = split_heavy(
        build_padded_rows(users, items, vals, N_USERS))
    i_light, i_heavy = split_heavy(
        build_padded_rows(items, users, vals, N_ITEMS))
    print(f"prep: {time.perf_counter() - t0:.1f}s", flush=True)

    u_tree, i_tree = als._buckets_tree(u_light), als._buckets_tree(i_light)
    u_hv, i_hv = als._heavy_tree(u_heavy), als._heavy_tree(i_heavy)

    def timed(name, bf16_sweeps, precision, polish_cg=None):
        def run():
            st = als.als_init(jax.random.key(0), N_USERS, N_ITEMS, RANK)
            lo = bf16_sweeps
            if lo:
                st = als._als_run_fused(
                    st, u_tree, i_tree, 0.1, 0.0, lo, True,
                    jnp.bfloat16, jax.lax.Precision.DEFAULT, implicit=False,
                    user_heavy=u_hv, item_heavy=i_hv,
                    cg_iters=min(als._CG_ITERS_BF16, als._CG_ITERS),
                    warmstart=als._CG_WARMSTART)
            if SWEEPS - lo:
                st = als._als_run_fused(
                    st, u_tree, i_tree, 0.1, 0.0, SWEEPS - lo, True,
                    jnp.float32, precision, implicit=False,
                    user_heavy=u_hv, item_heavy=i_hv,
                    cg_iters=polish_cg or als._CG_ITERS,
                    warmstart=als._CG_WARMSTART)
            np.asarray(st.user_factors[0:1, 0:1])
            np.asarray(st.item_factors[0:1, 0:1])
            return st

        run()
        t0 = time.perf_counter()
        st = run()
        warm = time.perf_counter() - t0
        fit = als.rmse(st, users, items, vals)
        ho = als.rmse(st, ho_u, ho_i, ho_r)
        print(f"{name:26s} warm={warm:5.2f}s fit={fit:.4f} "
              f"heldout={ho:.4f}", flush=True)

    P = jax.lax.Precision
    timed("f32 HIGHEST x10", 0, P.HIGHEST)
    timed("bf16 x10", 10, P.HIGHEST)
    timed("mixed 9+1 cg16", 9, P.HIGHEST)
    timed("mixed 9+1 cg8", 9, P.HIGHEST, polish_cg=8)
    timed("mixed 8+2 cg16", 8, P.HIGHEST)


if __name__ == "__main__":
    main()
