"""Split the warm-process compile cost into trace/lower vs cache-hit
compile (dev tool for the persistent-cache numbers in BENCH/BASELINE).

Phase 1 (fresh cache dir): lower + compile cold, writing the cache entry.
Phase 2 (jax.clear_caches): lower again (pure Python/trace cost), then
compile — which should be a persistent-cache HIT (deserialize only).
Run on the real TPU: python scripts/compile_cache_profile.py [nnz]
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NNZ = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000_000
N_USERS, N_ITEMS, RANK, SWEEPS = 138_493, 26_744, 128, 10


def main():
    from incubator_predictionio_tpu.utils.lease import install_sigterm_exit

    import jax

    # dial as a killable waiter, then make SIGTERM a clean exit so a
    # timeout-kill mid-run cannot wedge the lease we now hold
    jax.devices()
    install_sigterm_exit()
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import als
    from incubator_predictionio_tpu.ops.sparse import (
        build_padded_rows,
        split_heavy,
    )
    from incubator_predictionio_tpu.utils import compile_cache

    cache_dir = tempfile.mkdtemp(prefix="pio_ccprof_")
    compile_cache.enable(cache_dir)

    rng = np.random.default_rng(7)
    iw = (np.arange(N_ITEMS) + 1.0) ** -0.55
    items = rng.choice(N_ITEMS, NNZ, p=iw / iw.sum()).astype(np.int32)
    uw = (np.arange(N_USERS) + 1.0) ** -0.3
    users = rng.choice(N_USERS, NNZ, p=uw / uw.sum()).astype(np.int32)
    vals = rng.normal(3.5, 1.0, NNZ).astype(np.float32)
    u_light, u_heavy = split_heavy(
        build_padded_rows(users, items, vals, N_USERS))
    i_light, i_heavy = split_heavy(
        build_padded_rows(items, users, vals, N_ITEMS))
    u_tree, i_tree = als._buckets_tree(u_light), als._buckets_tree(i_light)
    u_hv, i_hv = als._heavy_tree(u_heavy), als._heavy_tree(i_heavy)
    state = als.als_init(jax.random.key(0), N_USERS, N_ITEMS, RANK)

    kwargs = dict(l2=0.1, alpha=0.0, iterations=SWEEPS, reg_nnz=True,
                  compute_dtype=jnp.bfloat16,
                  precision=jax.lax.Precision.DEFAULT, implicit=False,
                  user_heavy=u_hv, item_heavy=i_hv, cg_iters=6)

    def lower():
        return als._als_run_fused.lower(state, u_tree, i_tree, **kwargs)

    for phase in ("cold", "warm-cache"):
        if phase == "warm-cache":
            jax.clear_caches()
        t0 = time.perf_counter()
        lowered = lower()
        t_lower = time.perf_counter() - t0
        t0 = time.perf_counter()
        lowered.compile()
        t_compile = time.perf_counter() - t0
        print(f"{phase:11s} trace+lower={t_lower:5.1f}s "
              f"compile={t_compile:5.1f}s", flush=True)
    import os
    sizes = sum(
        os.path.getsize(os.path.join(cache_dir, f))
        for f in os.listdir(cache_dir))
    print(f"cache dir: {len(os.listdir(cache_dir))} entries, "
          f"{sizes / 1e6:.1f} MB", flush=True)


if __name__ == "__main__":
    main()
