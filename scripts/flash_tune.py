"""Flash-kernel block-shape sweep vs the XLA blockwise scan.

Run on the real chip (the CPU interpret path measures nothing useful):

    python scripts/flash_tune.py            # default sweep
    PIO_TUNE_SEQS=8192,32768 python scripts/flash_tune.py

Prints one JSON line per (S, q_block, kv_block) config plus the XLA
blockwise number per S, dispatch-amortized (20-rep loops, dependent-fetch
sync — block_until_ready returns early on the tunneled platform). Use the
result to update the flash_attention block defaults
(ops/pallas_kernels.py) and transformer.FLASH_MIN_SEQ.

Round-4 state this sweeps against: 1024x1024 blocks lose to the scan at
S=8k (18.13 vs 12.33 ms) and win 5.76x at 32k — the hypothesis space is
(a) smaller q blocks raise grid parallelism for short S, (b) larger kv
blocks amortize the online-softmax epilogue, (c) the crossover simply
moves.
"""

import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    from incubator_predictionio_tpu.utils.lease import install_sigterm_exit

    import jax

    # honor an explicit platform pin: the accelerator plugin re-selects
    # itself at interpreter start, so the env var alone is not enough
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # dial as a killable waiter (no handler: a blocked dial needs the
    # default OS kill), THEN make SIGTERM a clean interpreter exit so a
    # timeout-kill mid-run cannot wedge the chip lease we now hold
    jax.devices()
    install_sigterm_exit()
    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops.attention import blockwise_attention
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        flash_attention,
        flash_available,
    )

    if not flash_available():
        print(json.dumps({"error": "flash kernel unavailable on this "
                                   "backend"}))
        return 1

    seqs = [int(v) for v in os.environ.get(
        "PIO_TUNE_SEQS", "8192,16384,32768").split(",") if v]
    blocks = [int(v) for v in os.environ.get(
        "PIO_TUNE_BLOCKS", "256,512,1024,2048").split(",") if v]
    reps = int(os.environ.get("PIO_TUNE_REPS", "20"))
    h, d = 8, 64

    import functools

    def timed(fn, *args):
        # jit BOTH sides so the comparison measures compiled dispatch —
        # production calls attention inside jit, where eager per-call
        # re-trace/custom-vjp overhead does not exist; timing flash
        # eagerly against a jitted scan would bias the crossover high
        jfn = jax.jit(fn)
        r = jfn(*args)
        np.asarray(r[0:1, 0:1, 0:1, 0:1])
        t0 = time.perf_counter()
        for _ in range(reps):
            r = jfn(*args)
        np.asarray(r[0:1, 0:1, 0:1, 0:1])
        return (time.perf_counter() - t0) / reps * 1e3

    for s in seqs:
        key = jax.random.key(0)
        q, k, v = (jax.random.normal(kk, (1, s, h, d), jnp.bfloat16)
                   for kk in jax.random.split(key, 3))
        xla_ms = timed(
            functools.partial(blockwise_attention, causal=True), q, k, v)
        print(json.dumps({"s": s, "impl": "xla_blockwise",
                          "ms": round(xla_ms, 2)}), flush=True)
        best = None
        for qb, kb in itertools.product(blocks, blocks):
            if qb > s or kb > s:
                continue
            try:
                ms = timed(
                    functools.partial(flash_attention, causal=True,
                                      q_block=qb, kv_block=kb), q, k, v)
            except Exception as e:
                print(json.dumps({"s": s, "q_block": qb, "kv_block": kb,
                                  "error": str(e)[:120]}), flush=True)
                continue
            rec = {"s": s, "impl": "flash", "q_block": qb, "kv_block": kb,
                   "ms": round(ms, 2), "vs_xla": round(xla_ms / ms, 2)}
            print(json.dumps(rec), flush=True)
            if best is None or ms < best["ms"]:
                best = rec
        if best:
            print(json.dumps({"s": s, "best": best}), flush=True)


if __name__ == "__main__":
    sys.exit(main())
