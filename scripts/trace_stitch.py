#!/usr/bin/env python3
"""trace_stitch — reconstruct a request's cross-server timeline from
``pio.trace`` span logs.

Every server emits one JSON span line per request (obs/trace.py), and
every in-repo HTTP client hop forwards ``X-PIO-Trace-Id`` plus its own
span ID as ``X-PIO-Parent-Span`` — so the span lines from a prediction
server, the storage server it calls, and the event server a feedback
POST lands on all carry one trace ID and parent-span links. This tool
joins them back into one tree:

    # all spans of one request, across every process's log
    cat prediction.log storage.log | python scripts/trace_stitch.py \
        --trace e2e-trace-0042

    # summarize every trace seen in the logs
    python scripts/trace_stitch.py logs/*.log --list

``--decisions`` is the freshness controller's audit view
(obs/controller.py): one tree per ``controller.decision`` root span,
stitched to the cross-process retrain/reload subtree its trace ID
reached — "burn spike → decision → retrain → rolling swap" as one
timeline. Actuation spans (``controller.retrain`` /
``controller.reload``) whose trace carries NO decision root are
**orphans** — an actuation nothing audited — and surface loudly on
stderr with exit code 1.

Lines that are not JSON span objects (ordinary log output) are skipped,
so the tool can eat raw mixed stderr streams. Ordering inside a trace
uses the per-line wall stamp (``ts``); cross-process skew at request
granularity is NTP-bounded and only affects sibling order, never the
parent/child structure (that comes from the span IDs).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, Iterable, List, Optional, TextIO, Tuple


def parse_span_lines(lines: Iterable[str]) -> List[dict]:
    """Extract the JSON span records from a mixed log stream: any line
    whose JSON object carries a ``traceId`` counts; everything else —
    non-JSON, JSON without a trace — is silently skipped."""
    spans: List[dict] = []
    for line in lines:
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and obj.get("traceId"):
            spans.append(obj)
    return spans


def group_by_trace(spans: List[dict]) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for s in spans:
        out.setdefault(s["traceId"], []).append(s)
    return out


def build_tree(spans: List[dict]) -> List[dict]:
    """Link one trace's spans into a forest on spanId/parentSpanId.
    Returns the roots; every span gains a ``children`` list. A span
    whose parent never logged (sampled out, foreign process, crashed
    mid-request) becomes a root — an orphan is still evidence."""
    by_id: Dict[str, dict] = {}
    for s in spans:
        s.setdefault("children", [])
        sid = s.get("spanId")
        if sid:
            by_id[sid] = s
    roots: List[dict] = []
    for s in spans:
        parent = by_id.get(s.get("parentSpanId") or "")
        if parent is not None and parent is not s:
            parent["children"].append(s)
        else:
            roots.append(s)
    def ts(s: dict) -> float:
        return float(s.get("ts") or 0.0)
    for s in spans:
        s["children"].sort(key=ts)
    roots.sort(key=ts)
    return roots


def _span_label(s: dict) -> str:
    if s.get("span") == "http.request":
        core = (f"{s.get('server', '?')} {s.get('method', '?')} "
                f"{s.get('route', '?')} -> {s.get('status', '?')}")
    else:
        core = str(s.get("span", "?"))
    dur = s.get("durationMs")
    dur_s = f" {dur:.3f}ms" if isinstance(dur, (int, float)) else ""
    sid = s.get("spanId")
    sid_s = f" [{sid}]" if sid else ""
    return core + dur_s + sid_s


def render_trace(trace_id: str, spans: List[dict],
                 out: Optional[TextIO] = None) -> str:
    """Indented cross-server timeline of one trace; offsets are
    relative to the trace's earliest stamped span."""
    lines: List[str] = [f"trace {trace_id} ({len(spans)} spans)"]
    stamped = [float(s["ts"]) for s in spans if s.get("ts")]
    t0 = min(stamped) if stamped else 0.0

    def emit(span: dict, depth: int) -> None:
        ts = span.get("ts")
        off = f"+{(float(ts) - t0) * 1e3:9.1f}ms" if ts else " " * 12
        lines.append(f"  {off} {'  ' * depth}{_span_label(span)}")
        for child in span["children"]:
            emit(child, depth + 1)

    for root in build_tree(spans):
        emit(root, 0)
    text = "\n".join(lines)
    if out is not None:
        print(text, file=out)
    return text


#: span names the control plane emits around an actuation — an
#: actuation-family span (``controller.*`` from the freshness
#: controller, ``knob.*`` from the knob controller) must never appear
#: in a trace without that family's decision root (the decision-record
#: contract, obs/controller.py / obs/knobs.py)
DECISION_SPANS = ("controller.decision", "knob.decision")
ACTUATION_SPAN_PREFIXES = ("controller.", "knob.")


def _decision_root_for(span_name: str) -> Optional[str]:
    """The decision-root span name that sanctions ``span_name``, or
    None when it is not an actuation-family span at all."""
    for prefix, root in zip(ACTUATION_SPAN_PREFIXES, DECISION_SPANS):
        if span_name.startswith(prefix):
            return root
    return None


def find_decisions(traces: Dict[str, List[dict]]
                   ) -> List[Tuple[str, dict]]:
    """(trace_id, decision span) for every controller.decision /
    knob.decision span, oldest first."""
    out: List[Tuple[str, dict]] = []
    for tid, spans in traces.items():
        for s in spans:
            if s.get("span") in DECISION_SPANS:
                out.append((tid, s))
    out.sort(key=lambda p: float(p[1].get("ts") or 0.0))
    return out


def find_orphan_actuations(traces: Dict[str, List[dict]]) -> List[dict]:
    """Actuation spans (controller.retrain / controller.reload /
    knob.apply / any controller.* or knob.* that is not the decision
    itself) in traces with NO decision root OF THEIR OWN FAMILY: an
    actuation record nothing audited. A knob.apply span is only
    sanctioned by a knob.decision root — a controller.decision in the
    same trace does not cover it."""
    orphans: List[dict] = []
    for _tid, spans in traces.items():
        roots = {s.get("span") for s in spans} & set(DECISION_SPANS)
        for s in spans:
            name = str(s.get("span", ""))
            root = _decision_root_for(name)
            if root is None or name in DECISION_SPANS:
                continue
            if root not in roots:
                orphans.append(s)
    orphans.sort(key=lambda s: float(s.get("ts") or 0.0))
    return orphans


def render_decisions(traces: Dict[str, List[dict]],
                     out: Optional[TextIO] = None,
                     err: Optional[TextIO] = None) -> int:
    """The --decisions view: one stitched tree per decision root (the
    whole trace — the decision span plus every retrain/reload/HTTP hop
    its trace ID reached), then the orphan report. Returns the exit
    code: 0 clean, 1 when orphan actuations exist."""
    out = out if out is not None else sys.stdout
    err = err if err is not None else sys.stderr
    decisions = find_decisions(traces)
    if not decisions:
        print("no controller decisions in the input", file=out)
    first = True
    for tid, d in decisions:
        if not first:
            print(file=out)
        first = False
        head = (f"decision #{d.get('decisionId', '?')} "
                f"action={d.get('action', '?')} "
                f"reason={d.get('reason', '?')}")
        if d.get("knob"):
            head += f" knob={d['knob']}"
        print(head, file=out)
        render_trace(tid, traces[tid], out=out)
    orphans = find_orphan_actuations(traces)
    if orphans:
        print(f"\n!! {len(orphans)} ORPHAN ACTUATION SPAN(S) — "
              "controller.*/knob.* spans whose trace has NO decision "
              "root of their family; an actuation happened that "
              "nothing audited:", file=err)
        for s in orphans:
            print(f"!!   trace={s.get('traceId')} span={s.get('span')} "
                  f"ts={s.get('ts')} "
                  f"decisionId={s.get('decisionId', '?')}", file=err)
        return 1
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="stitch pio.trace span logs into per-trace "
                    "cross-server timelines")
    ap.add_argument("files", nargs="*",
                    help="log files to read (default: stdin)")
    ap.add_argument("--trace", help="only this trace ID")
    ap.add_argument("--list", action="store_true",
                    help="one summary line per trace instead of trees")
    ap.add_argument("--decisions", action="store_true",
                    help="control-plane audit view: one stitched tree "
                         "per controller.decision / knob.decision "
                         "root; orphan actuation spans (controller.* "
                         "or knob.* with no decision of their family "
                         "in their trace) surface on stderr with exit "
                         "code 1")
    args = ap.parse_args(argv)

    lines: List[str] = []
    if args.files:
        for path in args.files:
            with open(path, encoding="utf-8", errors="replace") as f:
                lines.extend(f)
    else:
        lines.extend(sys.stdin)

    traces = group_by_trace(parse_span_lines(lines))
    if args.trace:
        traces = {k: v for k, v in traces.items() if k == args.trace}
        if not traces:
            print(f"no spans for trace {args.trace!r}", file=sys.stderr)
            return 1
    if args.decisions:
        return render_decisions(traces)
    if args.list:
        for tid, spans in sorted(traces.items()):
            servers = sorted({s.get("server", s.get("span", "?"))
                              for s in spans})
            print(f"{tid}  {len(spans)} spans  {','.join(servers)}")
        return 0
    first = True
    for tid, spans in sorted(traces.items()):
        if not first:
            print()
        render_trace(tid, spans, out=sys.stdout)
        first = False
    return 0


if __name__ == "__main__":
    sys.exit(main())
