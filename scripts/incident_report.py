#!/usr/bin/env python3
"""incident_report — render a flight-recorder incident bundle for humans.

An incident bundle (obs/recorder.py IncidentCapture, captured under
``PIO_INCIDENT_DIR`` on an SLO fast-burn breach or ``POST /incident``)
is one self-contained JSON artifact: the fleet-merged pre-breach metric
window, the breaching histogram's exemplar trace IDs, each worker's
scheduler state and the in-window controller decisions. This tool turns
it into the post-incident narrative:

    # the human summary: breach header, per-instance timeline of the
    # breaching series around T0, scheduler state, decisions in-window
    python scripts/incident_report.py incidents/inc-...-serve_p99.json

    # plus the exemplar TRACE TREES, stitched from span logs through
    # the trace_stitch machinery (the bundle names WHICH traces to pull)
    python scripts/incident_report.py bundle.json --spans worker0.log \
        --spans worker1.log

    # CI / runbook gate: exit 1 when the bundle is malformed
    python scripts/incident_report.py bundle.json --check
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

# trace_stitch lives beside this script (scripts/ is not a package);
# its parse/group/render machinery is the one copy of span stitching
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import trace_stitch  # noqa: E402


class MalformedBundle(Exception):
    """The bundle violates the pio-incident-v1 schema."""


def check_bundle(bundle: Any) -> List[str]:
    """Schema validation → list of problems (empty = well-formed).
    Collected, not fail-fast: a --check failure should name everything
    wrong with the artifact at once."""
    problems: List[str] = []
    if not isinstance(bundle, dict):
        return ["bundle is not a JSON object"]
    if bundle.get("schema") != "pio-incident-v1":
        problems.append(
            f"unknown schema {bundle.get('schema')!r} "
            "(expected pio-incident-v1)")
    for field, typ in (("id", str), ("trigger", str), ("scope", str),
                      ("ts", (int, float)), ("windowS", (int, float))):
        if not isinstance(bundle.get(field), typ):
            problems.append(f"missing/mistyped field {field!r}")
    rec = bundle.get("recorder")
    instances = (rec or {}).get("instances")
    if not isinstance(instances, dict) or not instances:
        problems.append("recorder.instances missing or empty")
        instances = {}
    ok_instances = 0
    for name, dump in instances.items():
        if not isinstance(dump, dict):
            problems.append(f"instance {name!r}: dump is not an object")
            continue
        if "error" in dump:
            continue  # a degraded pull is recorded, not malformed
        if not isinstance(dump.get("series"), dict):
            problems.append(f"instance {name!r}: no series block")
            continue
        ok_instances += 1
    if instances and ok_instances == 0:
        problems.append("every instance pull failed — the bundle holds "
                        "no metric window at all")
    ex = bundle.get("exemplars")
    if not isinstance(ex, dict) or not isinstance(
            ex.get("traceIds"), list):
        problems.append("exemplars block missing/mistyped")
    if not isinstance(bundle.get("decisions"), list):
        problems.append("decisions block missing/mistyped")
    slo = bundle.get("slo")
    if bundle.get("trigger") not in (None, "manual") and slo is not None \
            and not isinstance(slo, dict):
        problems.append("slo block mistyped")
    return problems


def _fmt_ts(ts: Optional[float], t0: Optional[float]) -> str:
    if not isinstance(ts, (int, float)) or not isinstance(
            t0, (int, float)):
        return "        ?"
    return f"{ts - t0:+8.1f}s"


def _series_children(dump: Dict[str, Any],
                     name: str) -> List[Dict[str, Any]]:
    fam = (dump.get("series") or {}).get(name)
    return list(fam.get("children", [])) if isinstance(fam, dict) else []


def render_timeline(bundle: Dict[str, Any], metric: Optional[str],
                    tail_points: int = 20) -> List[str]:
    """Per-instance tail of the breaching series around T0 (histogram
    points carry per-interval p50/p99 — the recorder's "what did p99
    look like" answer), plus the queue-depth/shed context series when
    recorded."""
    t0 = bundle.get("ts")
    lines: List[str] = []
    instances = (bundle.get("recorder") or {}).get("instances", {})
    context = ("pio_serve_queue_depth", "pio_serve_shed_total")
    for inst in sorted(instances):
        dump = instances[inst]
        if not isinstance(dump, dict):
            continue
        if "error" in dump:
            lines.append(f"  [{inst}] PULL FAILED: {dump['error']}")
            continue
        lines.append(f"  [{inst}]")
        names = [metric] if metric else []
        names += [c for c in context if c in (dump.get("series") or {})]
        for name in names:
            for child in _series_children(dump, name):
                pts = child.get("points", [])[-tail_points:]
                if not pts:
                    continue
                label = json.dumps(child.get("labels", {}),
                                   sort_keys=True)
                lines.append(f"    {name} {label}")
                for p in pts:
                    off = _fmt_ts(p[0] if p else None, t0)
                    if len(p) >= 6:  # histogram point
                        p99 = ("-" if p[5] is None
                               else f"{p[5] * 1e3:9.2f}ms")
                        p50 = ("-" if p[4] is None
                               else f"{p[4] * 1e3:9.2f}ms")
                        lines.append(
                            f"      {off}  n={p[3]:<6} p50={p50} "
                            f"p99={p99}")
                    else:
                        lines.append(f"      {off}  {p[1]}")
        state = dump.get("state") or {}
        if state:
            lines.append(f"    state: {json.dumps(state, sort_keys=True)}")
    return lines


def render_decisions(bundle: Dict[str, Any]) -> List[str]:
    t0 = bundle.get("ts")
    out: List[str] = []
    for d in bundle.get("decisions", []):
        off = _fmt_ts(d.get("ts"), t0)
        out.append(
            f"  {off}  #{d.get('id', '?')} {d.get('kind', '?')} "
            f"mode={d.get('mode', '?')} action={d.get('action', '-')} "
            f"reason={d.get('reason', '-')} "
            f"trace={d.get('traceId', '-')}")
    if not out:
        out.append("  (no controller decisions in the window)")
    return out


def render_exemplar_trees(bundle: Dict[str, Any],
                          span_files: List[str]) -> List[str]:
    """The exemplar trace trees: every span log line whose trace ID the
    bundle names, stitched through trace_stitch.build_tree — the
    cross-process "this WAS the p99 query" reconstruction."""
    trace_ids = set((bundle.get("exemplars") or {}).get("traceIds", []))
    out: List[str] = []
    if not trace_ids:
        out.append("  (bundle names no exemplar trace IDs — check the "
                   "sampling floor, see the runbook)")
        return out
    lines: List[str] = []
    for path in span_files:
        with open(path, encoding="utf-8", errors="replace") as f:
            lines.extend(f)
    traces = trace_stitch.group_by_trace(
        trace_stitch.parse_span_lines(lines))
    for tid in sorted(trace_ids):
        spans = traces.get(tid)
        if not spans:
            out.append(f"  trace {tid}: no spans in the supplied logs")
            continue
        out.append(trace_stitch.render_trace(tid, spans))
    return out


def render(bundle: Dict[str, Any],
           span_files: Optional[List[str]] = None) -> str:
    slo = bundle.get("slo") or {}
    metric = (slo.get("objective") or {}).get("metric")
    header = [
        f"incident {bundle.get('id', '?')}",
        f"  trigger: {bundle.get('trigger', '?')}   "
        f"scope: {bundle.get('scope', '?')}   "
        f"T0: {bundle.get('ts', '?')} (epoch s)",
    ]
    if slo:
        fast = ((slo.get("windows") or {}).get("fast") or {})
        header.append(
            f"  slo: {slo.get('name', '?')} fast burn "
            f"{fast.get('burnRate', '?')} over "
            f"{fast.get('observations', '?')} obs; budget remaining "
            f"{slo.get('errorBudgetRemaining', '?')}")
    ex_ids = (bundle.get("exemplars") or {}).get("traceIds", [])
    header.append(f"  exemplar traces: {', '.join(ex_ids) or '(none)'}")
    parts = header
    parts.append("")
    parts.append("timeline (pre-breach window tail):")
    parts.extend(render_timeline(bundle, metric))
    parts.append("")
    parts.append("controller decisions in-window:")
    parts.extend(render_decisions(bundle))
    if span_files:
        parts.append("")
        parts.append("exemplar trace trees:")
        parts.extend(render_exemplar_trees(bundle, span_files))
    return "\n".join(parts)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="render a flight-recorder incident bundle "
                    "(obs/recorder.py) to a human summary")
    ap.add_argument("bundle", help="incident bundle JSON path")
    ap.add_argument("--spans", action="append", default=[],
                    metavar="LOG",
                    help="span log file(s) to stitch the exemplar "
                         "trace trees from (repeatable)")
    ap.add_argument("--check", action="store_true",
                    help="validate only: exit 1 on a malformed bundle")
    args = ap.parse_args(argv)

    try:
        with open(args.bundle, encoding="utf-8") as f:
            bundle = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"MALFORMED: cannot read bundle: {e}", file=sys.stderr)
        return 1
    problems = check_bundle(bundle)
    if problems:
        for p in problems:
            print(f"MALFORMED: {p}", file=sys.stderr)
        return 1
    if args.check:
        print(f"ok: {bundle['id']} (trigger={bundle['trigger']}, "
              f"scope={bundle['scope']}, "
              f"{len((bundle['recorder'] or {}).get('instances', {}))} "
              "instance(s))")
        return 0
    print(render(bundle, span_files=args.spans))
    return 0


if __name__ == "__main__":
    sys.exit(main())
