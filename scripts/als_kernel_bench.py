"""ALS fused-kernel (Pallas) vs XLA bucket path at the bench shape.

Run on the real chip:

    python scripts/als_kernel_bench.py                  # full ML-20M shape
    PIO_TUNE_NNZ=2000000 python scripts/als_kernel_bench.py   # smoke

Prints one JSON line per configuration: warm train wall, derived MFU
(both peak conventions), and fit RMSE — kernel off vs on, plus the
planted heldout so numerics regressions show up next to the speed. Use
the result to confirm `PIO_ALS_KERNEL=auto` helps before the driver
bench, and to quantify the Gram-stream removal (expected: bf16-peak MFU
0.079 → 0.15+ per the round-4 verdict target).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> int:
    from incubator_predictionio_tpu.utils.lease import install_sigterm_exit

    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # dial as a killable waiter, then make SIGTERM a clean exit so a
    # timeout-kill mid-run cannot wedge the lease we now hold
    jax.devices()
    install_sigterm_exit()

    n_users = int(os.environ.get("PIO_TUNE_USERS", 138_493))
    n_items = int(os.environ.get("PIO_TUNE_ITEMS", 26_744))
    nnz = int(os.environ.get("PIO_TUNE_NNZ", 20_000_000))
    rank = int(os.environ.get("PIO_TUNE_RANK", 128))
    sweeps = int(os.environ.get("PIO_TUNE_SWEEPS", 10))
    l2 = float(os.environ.get("PIO_BENCH_L2", "0.03"))
    peak_f32 = float(os.environ.get("PIO_BENCH_PEAK_FLOPS", 98.5e12))
    peak_bf16 = float(os.environ.get("PIO_BENCH_PEAK_FLOPS_BF16", 197e12))

    rng = np.random.default_rng(7)
    iw = (np.arange(n_items) + 1.0) ** -0.55
    uw = (np.arange(n_users) + 1.0) ** -0.3

    def pairs(n):
        return (rng.choice(n_users, n, p=uw / uw.sum()).astype(np.int32),
                rng.choice(n_items, n, p=iw / iw.sum()).astype(np.int32))

    plant, noise = 16, 0.35
    u_true = rng.normal(0, 1 / np.sqrt(plant),
                        (n_users, plant)).astype(np.float32)
    v_true = rng.normal(0, 1.0, (n_items, plant)).astype(np.float32)

    def rate(u, i):
        return (3.5 + np.einsum("nk,nk->n", u_true[u], v_true[i])
                + rng.normal(0, noise, len(u))).astype(np.float32)

    users, items = pairs(nnz)
    ratings = rate(users, items)
    hu, hi = pairs(200_000)
    hr = rate(hu, hi)

    import jax.numpy as jnp

    from incubator_predictionio_tpu.ops import als
    from incubator_predictionio_tpu.ops.sparse import build_both_sides

    (ul, uh), (il, ih) = build_both_sides(users, items, ratings,
                                          n_users, n_items)
    u_tree, i_tree = als._buckets_tree(ul), als._buckets_tree(il)
    u_hv, i_hv = als._heavy_tree(uh), als._heavy_tree(ih)

    # analytic FLOPs (bench.py convention, bf16 CG budget)
    k = float(rank)
    iters_cg = min(als._CG_ITERS_BF16, als._CG_ITERS)
    per_sweep = (2 * (2.0 * nnz * k * k * 2.0) + 2 * (2.0 * nnz * k)
                 + (n_users + n_items) * iters_cg * 2.0 * k * k)
    flops = per_sweep * sweeps

    # measure what PIO_ALS_KERNEL=auto would actually select: gate the
    # kernel leg on the real Mosaic probe (forcing past a failed probe
    # would either crash mid-run or silently time interpret mode). The
    # legs run _mixed_run under the production warm-start default, so
    # probe that exact variant (warm adds the x0 operand — a different
    # kernel)
    kernel_ok = als._kernel_enabled(False, warm=als._CG_WARMSTART)
    # the fused gather+Gram+CG generation probes its own variant, and
    # only the VMEM-fitting side routes through it (als._fused_sides:
    # at ML-20M shape the user half-sweep, whose gather table is the
    # small item side)
    fused_sides = (als._fused_sides(n_users, n_items, False,
                                    als._CG_WARMSTART, jnp.bfloat16,
                                    rank)
                   if kernel_ok else (False, False))
    # each leg: (use_kernel, min-D routing cut, rows per program,
    # use_fused). PIO_TUNE_MIN_DS × PIO_TUNE_ROWS sweep both knobs so
    # one chip window yields the whole layout picture; the fused-gather
    # leg rides along when its probe passes and a side fits the budget
    legs = [(False, 0, 1, (False, False))]
    if kernel_ok:
        min_ds = [int(v) for v in os.environ.get(
            "PIO_TUNE_MIN_DS", "0,64").split(",") if v.strip()]
        rows_l = [int(v) for v in os.environ.get(
            "PIO_TUNE_ROWS", "1,8").split(",") if v.strip()]
        if not min_ds or not rows_l:
            print(json.dumps({"kernel": True,
                              "skipped": "PIO_TUNE_MIN_DS or "
                                         "PIO_TUNE_ROWS is empty"}),
                  flush=True)
        legs += [(True, d, r, (False, False))
                 for r in rows_l for d in min_ds]
        if any(fused_sides):
            legs += [(True, d, 1, fused_sides) for d in min_ds]
        else:
            print(json.dumps({"fused": True,
                              "skipped": "fused-gather probe failed or "
                                         "no side fits "
                                         "PIO_ALS_FUSED_VMEM_MB"}),
                  flush=True)
    else:
        print(json.dumps({"kernel": True,
                          "skipped": "als_kernel_available() is False on "
                                     "this backend (or PIO_ALS_KERNEL=off)"
                          }), flush=True)
    for use_kernel, min_d, rows, fused in legs:
        def train():
            out = als._mixed_run(
                als.als_init(jax.random.key(0), n_users, n_items, rank),
                u_tree, i_tree, l2, sweeps, sweeps, True,
                jnp.float32, jax.lax.Precision.HIGHEST,
                user_heavy=u_hv, item_heavy=i_hv,
                use_kernel=use_kernel, kernel_min_d=min_d,
                kernel_rows=rows, use_fused=fused)
            np.asarray(out.user_factors[0:1, 0:1])
            np.asarray(out.item_factors[0:1, 0:1])
            return out

        t0 = time.perf_counter()
        state = train()
        first = time.perf_counter() - t0
        t0 = time.perf_counter()
        state = train()
        warm = time.perf_counter() - t0
        rec = {
            "kernel": use_kernel,
            "kernel_min_d": min_d,
            "kernel_rows": rows,
            "fused_user_sweep": fused[0],
            "fused_item_sweep": fused[1],
            "warm_s": round(warm, 3),
            "compile_s": round(max(first - warm, 0.0), 1),
            "mfu_f32_peak": round(flops / warm / peak_f32, 4),
            "mfu_bf16_peak": round(flops / warm / peak_bf16, 4),
            "fit_rmse": round(float(als.rmse(state, users, items,
                                             ratings)), 4),
            "heldout_rmse": round(float(als.rmse(state, hu, hi, hr)), 4),
        }
        print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
