"""Workflow runtime: train/evaluate drivers + model checkpointing.

Parity: core/src/main/scala/.../workflow/ (CreateWorkflow, CoreWorkflow,
EvaluationWorkflow, WorkflowContext). The reference's spark-submit process
hop disappears: `pio train` runs the workflow in-process on the TPU host.
"""

from incubator_predictionio_tpu.workflow.workflow import CoreWorkflow
from incubator_predictionio_tpu.workflow import checkpoint
from incubator_predictionio_tpu.workflow.fake import FakeRun

__all__ = ["CoreWorkflow", "checkpoint", "FakeRun"]
