"""Model checkpointing — pytrees of device arrays → durable blobs.

Replaces the reference's Kryo serialization of trained models into the
MODELDATA repository (CoreWorkflow.scala:76-81, CreateServer.scala:73-87
KryoInstantiator). Device arrays are converted to host numpy on save and
restored as numpy on load; they migrate back to the TPU (with the serving
sharding) the first time a jitted predict touches them, or explicitly via
:func:`device_restore`.

The reference's three model classes (SURVEY.md §5 checkpoint/resume):
serializable models → stored as-is; RDD models → stored as Unit + silently
retrained at deploy; PersistentModel → custom save/load. Here: pytrees are
always storable, :class:`~...core.persistent_model.RetrainMarker` makes the
retrain path explicit, and PersistentModel keeps its contract.
"""

from __future__ import annotations

import io
import logging
import pickle
from typing import Any, List, Optional

from incubator_predictionio_tpu.core.persistent_model import (
    PersistentModel,
    PersistentModelManifest,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext

logger = logging.getLogger(__name__)

_FORMAT_VERSION = 1


def _np(obj: Any):
    import numpy as np

    return np.asarray(obj)


def _restore_array(arr: Any) -> Any:
    return arr  # numpy; device transfer happens lazily at first jit use


class _ModelPickler(pickle.Pickler):
    """Pickler that converts jax Arrays to host numpy on the way out."""

    def reducer_override(self, obj: Any):
        try:
            import jax
        except Exception:  # pragma: no cover - jax always present
            return NotImplemented
        if isinstance(obj, jax.Array):
            return (_restore_array, (_np(obj),))
        return NotImplemented


def dumps(obj: Any) -> bytes:
    buf = io.BytesIO()
    _ModelPickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(
        (_FORMAT_VERSION, obj)
    )
    return buf.getvalue()


def loads(data: bytes) -> Any:
    version, obj = pickle.loads(data)
    if version != _FORMAT_VERSION:
        raise ValueError(f"Unsupported model blob version {version}")
    return obj


def serialize_models(
    models: List[Any],
    instance_id: str,
    ctx: RuntimeContext,
    algo_params: Optional[List[Any]] = None,
) -> bytes:
    """Make the model list durable (Engine.makeSerializableModels:286 +
    CoreWorkflow kryo step). PersistentModels run their own ``save`` and are
    replaced by manifests."""
    out: List[Any] = []
    algo_params = algo_params or [None] * len(models)
    for model, params in zip(models, algo_params):
        if isinstance(model, PersistentModel):
            cls = type(model)
            if model.save(instance_id, params, ctx):
                out.append(
                    PersistentModelManifest(
                        class_path=f"{cls.__module__}.{cls.__qualname__}",
                        instance_id=instance_id,
                    )
                )
                continue
            logger.info(
                "%s.save returned False; falling back to default "
                "checkpointing", cls.__name__,
            )
        out.append(model)
    return dumps(out)


def deserialize_models(data: bytes) -> List[Any]:
    models = loads(data)
    if not isinstance(models, list):
        raise ValueError("Model blob does not contain a model list")
    return models


def device_restore(tree: Any, sharding: Optional[Any] = None) -> Any:
    """Push every array leaf of a restored model back onto device, optionally
    with a serving sharding (donated device-resident serving state)."""
    import jax
    import numpy as np

    def put(leaf: Any) -> Any:
        if isinstance(leaf, (np.ndarray, jax.Array)):
            return jax.device_put(leaf, sharding) if sharding else jax.device_put(leaf)
        return leaf

    return jax.tree_util.tree_map(put, tree)
