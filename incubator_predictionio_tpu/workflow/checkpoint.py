"""Model checkpointing — pytrees of device arrays → durable blobs.

Replaces the reference's Kryo serialization of trained models into the
MODELDATA repository (CoreWorkflow.scala:76-81, CreateServer.scala:73-87
KryoInstantiator). Device arrays are converted to host numpy on save and
restored as numpy on load; they migrate back to the TPU (with the serving
sharding) the first time a jitted predict touches them, or explicitly via
:func:`device_restore` / ``Algorithm.prepare_model``.

Format (version 2): a magic header + **msgpack of a structural encoding** —
plain JSON-ish values pass through, numpy/jax arrays become
(dtype, shape, raw bytes) tags, and model objects are encoded as
dataclass-field maps reconstructed through their constructors. Loading
never executes embedded code: the decoder resolves model classes only from
modules that are ALREADY imported (no import side effects; see
``_resolve_dataclass``) and refuses anything that is not a dataclass — the
arbitrary-callable gadget surface of pickle does not exist here. (The
reference inherits a worse version of this risk through Kryo's
class-name-driven instantiation.)

Version-1 blobs (pickle) still load for backward compatibility, with a
loud warning; set ``PIO_ALLOW_PICKLE_CHECKPOINTS=0`` to refuse them.

The reference's three model classes (SURVEY.md §5 checkpoint/resume):
serializable models → stored as-is; RDD models → stored as Unit + silently
retrained at deploy; PersistentModel → custom save/load. Here: dataclass /
pytree models are storable, :class:`~...core.persistent_model.RetrainMarker`
makes the retrain path explicit, and PersistentModel keeps its contract.
"""

from __future__ import annotations

import dataclasses
import importlib
import logging
import os
import pickle
from typing import Any, Dict, List, Optional

from incubator_predictionio_tpu.core.persistent_model import (
    PersistentModel,
    PersistentModelManifest,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.utils.structcodec import StructCodec

logger = logging.getLogger(__name__)

_MAGIC_V2 = b"PIOCKPT2"
_FORMAT_VERSION = 2

#: structural tag key — a reserved dict key marking an encoded object
_TAG = "~pio~"


class CheckpointError(ValueError):
    """A model (or blob) outside the safe checkpoint format."""


# ---------------------------------------------------------------------------
# structural encode / decode — the shared codec (utils/structcodec.py, same
# core the remote-storage wire protocol uses) plus the dataclass tag
# ---------------------------------------------------------------------------

def _encode_ext(obj: Any, codec: Any) -> Any:
    # dataclass instances (the model pytree nodes) — checked here so the
    # checkpoint error message stays domain-specific for everything else
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        cls = type(obj)
        fields = {
            f.name: codec.encode(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
        return {_TAG: "dc",
                "c": f"{cls.__module__}:{cls.__qualname__}", "f": fields}
    return NotImplemented


def _encode(obj: Any) -> Any:
    try:
        return _CODEC.encode(obj)
    except CheckpointError as e:
        raise CheckpointError(
            f"{e}: models must be dataclasses / pytrees of arrays and "
            "plain values (or implement PersistentModel for custom "
            "persistence)"
        ) from None


def _resolve_dataclass(path: str) -> type:
    """Resolve a model class from an ALREADY-IMPORTED module.

    The decoder never imports new modules: importing runs the module's
    top-level code, which would let a tampered blob execute an arbitrary
    installed module as a side effect. Engine model classes are always
    imported before models load (deploy resolves the engine factory first),
    so a sys.modules miss means a truly foreign blob — refuse it unless the
    operator opts in via ``PIO_CHECKPOINT_ALLOW_IMPORT=1``."""
    import sys

    mod_name, _, qual = path.partition(":")
    mod = sys.modules.get(mod_name)
    if mod is None:
        if os.environ.get("PIO_CHECKPOINT_ALLOW_IMPORT") == "1":
            try:
                mod = importlib.import_module(mod_name)
            except Exception as e:
                raise CheckpointError(
                    f"cannot resolve model class {path!r}: {e}")
        else:
            raise CheckpointError(
                f"model class {path!r} lives in a module that is not "
                "imported; import your engine module before loading the "
                "checkpoint (or set PIO_CHECKPOINT_ALLOW_IMPORT=1 to let "
                "the loader import it)")
    try:
        cls: Any = mod
        for part in qual.split("."):
            cls = getattr(cls, part)
    except AttributeError as e:
        raise CheckpointError(f"cannot resolve model class {path!r}: {e}")
    if not (isinstance(cls, type) and dataclasses.is_dataclass(cls)):
        # the decoder only ever constructs dataclasses — anything else in
        # the class slot is a malformed (or malicious) blob
        raise CheckpointError(f"{path!r} is not a dataclass")
    return cls


def _decode_ext(tag: str, obj: dict, codec: Any) -> Any:
    if tag == "dc":
        cls = _resolve_dataclass(obj["c"])
        fields = {k: codec.decode(v) for k, v in obj["f"].items()}
        return cls(**fields)
    return NotImplemented


_CODEC = StructCodec(_TAG, CheckpointError, _encode_ext, _decode_ext)


def _decode(obj: Any) -> Any:
    return _CODEC.decode(obj)


# ---------------------------------------------------------------------------
# blob API
# ---------------------------------------------------------------------------

def dumps(obj: Any) -> bytes:
    """Encode a model pytree into a version-2 checkpoint blob."""
    import msgpack

    payload = msgpack.packb(
        {"version": _FORMAT_VERSION, "root": _encode(obj)},
        use_bin_type=True,
    )
    return _MAGIC_V2 + payload


def loads(data: bytes) -> Any:
    """Decode a checkpoint blob (v2 msgpack; v1 pickle with opt-out)."""
    import msgpack

    if data[: len(_MAGIC_V2)] == _MAGIC_V2:
        doc = msgpack.unpackb(
            data[len(_MAGIC_V2):], raw=False, strict_map_key=False)
        if doc.get("version") != _FORMAT_VERSION:
            raise CheckpointError(
                f"Unsupported model blob version {doc.get('version')}")
        return _decode(doc["root"])
    # ---- legacy v1: pickle ----
    if os.environ.get("PIO_ALLOW_PICKLE_CHECKPOINTS", "1") == "0":
        raise CheckpointError(
            "legacy pickle checkpoint refused "
            "(PIO_ALLOW_PICKLE_CHECKPOINTS=0); retrain to re-checkpoint "
            "in the safe format")
    logger.warning(
        "loading a legacy v1 (pickle) model checkpoint — retrain to "
        "upgrade it to the safe msgpack format")
    version, obj = pickle.loads(data)
    if version != 1:
        raise CheckpointError(f"Unsupported model blob version {version}")
    return obj


def serialize_models(
    models: List[Any],
    instance_id: str,
    ctx: RuntimeContext,
    algo_params: Optional[List[Any]] = None,
) -> bytes:
    """Make the model list durable (Engine.makeSerializableModels:286 +
    CoreWorkflow kryo step). PersistentModels run their own ``save`` and are
    replaced by manifests."""
    out: List[Any] = []
    algo_params = algo_params or [None] * len(models)
    for model, params in zip(models, algo_params):
        if isinstance(model, PersistentModel):
            cls = type(model)
            if model.save(instance_id, params, ctx):
                out.append(
                    PersistentModelManifest(
                        class_path=f"{cls.__module__}.{cls.__qualname__}",
                        instance_id=instance_id,
                    )
                )
                continue
            logger.info(
                "%s.save returned False; falling back to default "
                "checkpointing", cls.__name__,
            )
        out.append(model)
    return dumps(out)


def deserialize_models(data: bytes) -> List[Any]:
    models = loads(data)
    if not isinstance(models, list):
        raise CheckpointError("Model blob does not contain a model list")
    return models


def host_materialize(obj: Any) -> Any:
    """Fetch every array found anywhere in a model structure to host
    numpy, COLLECTIVELY when an array is sharded across pod processes.

    Called by the workflow on EVERY pod process before the non-zero
    workers exit: a model holding a jax.Array with non-addressable shards
    cannot be fetched by process 0 alone (and a lone allgather would
    deadlock once the workers are gone), so the gather happens while all
    participants are still alive. Single-process runs reduce to a plain
    host fetch.

    Traversal mirrors the checkpoint encoder (``_encode_ext``): engine
    models are plain dataclasses, NOT registered pytrees, so
    ``tree_map`` would treat them as opaque leaves and skip exactly the
    arrays this function exists to gather — the walk recurses into
    dataclass fields, dicts, lists, and tuples by hand. The field walk
    must be deterministic and identical on every process (dataclass
    field order is), because each non-addressable fetch is a collective."""
    import jax
    import numpy as np

    if isinstance(obj, jax.Array):
        if not obj.is_fully_addressable:
            from jax.experimental import multihost_utils

            return np.asarray(
                multihost_utils.process_allgather(obj, tiled=True))
        return np.asarray(obj)
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        # copy + setattr instead of dataclasses.replace: replace() refuses
        # init=False fields and re-runs __init__ (breaking on InitVars),
        # and object.__setattr__ also covers frozen dataclasses
        import copy

        new = copy.copy(obj)
        for f in dataclasses.fields(obj):
            object.__setattr__(
                new, f.name, host_materialize(getattr(obj, f.name)))
        return new
    if isinstance(obj, dict):
        return {k: host_materialize(v) for k, v in obj.items()}
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        # namedtuple: the constructor takes N positional args, not one
        # iterable (a plain tuple(<generator>) call would TypeError here)
        return type(obj)(*(host_materialize(v) for v in obj))
    if isinstance(obj, (list, tuple)):
        return type(obj)(host_materialize(v) for v in obj)
    return obj


def device_restore(tree: Any, sharding: Optional[Any] = None) -> Any:
    """Push every array leaf of a restored model back onto device, optionally
    with a serving sharding (donated device-resident serving state)."""
    import jax
    import numpy as np

    def put(leaf: Any) -> Any:
        if isinstance(leaf, (np.ndarray, jax.Array)):
            return jax.device_put(leaf, sharding) if sharding else jax.device_put(leaf)
        return leaf

    return jax.tree_util.tree_map(put, tree)
