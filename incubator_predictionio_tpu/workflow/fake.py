"""FakeWorkflow — run arbitrary user code through the workflow machinery.

Parity: core/.../workflow/FakeWorkflow.scala:17-109 (``FakeRun``): a user
singleton assigns ``func`` (there ``SparkContext => Unit``, here
``RuntimeContext -> None``) and runs it with ``pio eval module:Obj`` —
useful for experimenting inside the exact runtime environment (storage
configured, mesh context built) without writing a real engine.

Example::

    class HelloWorld(FakeRun):
        def __init__(self):
            super().__init__()
            self.func = lambda ctx: print("HelloWorld", ctx.mesh)

    hello_world = HelloWorld()   # then: pio eval my_module:hello_world
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence, Tuple

from incubator_predictionio_tpu.core.base import Evaluator
from incubator_predictionio_tpu.core.evaluation import Evaluation
from incubator_predictionio_tpu.core.params import EngineParams
from incubator_predictionio_tpu.parallel.context import RuntimeContext


class FakeEngine:
    """FakeWorkflow.scala:32-51 — an engine that produces no eval data."""

    def batch_eval(
        self,
        ctx: RuntimeContext,
        engine_params_list: Sequence[EngineParams],
        params: Any = None,
    ) -> list:
        return []

    def train(self, *args: Any, **kwargs: Any) -> list:
        raise RuntimeError("FakeEngine cannot train; use `pio eval`.")


@dataclasses.dataclass
class FakeEvalResult:
    """FakeWorkflow.scala:69-72 — noSave result; nothing is persisted."""

    no_save: bool = True

    def to_one_liner(self) -> str:
        return "FakeEvalResult"

    def to_jsonable(self) -> dict:
        return {"result": "FakeEvalResult"}

    def to_html(self) -> str:
        return "<p>FakeEvalResult</p>"


class FakeRunner(Evaluator):
    """FakeWorkflow.scala:53-67 — evaluator that just calls the function."""

    def __init__(self, f: Callable[[RuntimeContext], Any]):
        super().__init__()
        self.f = f

    def evaluate(
        self,
        ctx: RuntimeContext,
        evaluation: Any,
        engine_eval_data_set: Sequence[Tuple[EngineParams, Any]],
        params: Any = None,
    ) -> FakeEvalResult:
        self.f(ctx)
        return FakeEvalResult()


class FakeRun(Evaluation):
    """FakeWorkflow.scala:75-109 — assign ``func`` and run via `pio eval`."""

    def __init__(self) -> None:
        super().__init__()
        self.engine_params_list: list[EngineParams] = []

    @property
    def func(self) -> Callable[[RuntimeContext], Any]:
        raise NotImplementedError("write-only (FakeWorkflow.scala:104)")

    @func.setter
    def func(self, f: Callable[[RuntimeContext], Any]) -> None:
        self.engine_evaluator = (FakeEngine(), FakeRunner(f))
        self.engine_params_list = [EngineParams()]
