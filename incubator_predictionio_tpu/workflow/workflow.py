"""CoreWorkflow — training and evaluation drivers.

Parity: workflow/CoreWorkflow.scala:45-160 and EvaluationWorkflow.scala:30-43.
``run_train``: register an INIT EngineInstance → build the RuntimeContext
(the WorkflowContext/SparkContext step) → ``engine.train`` → checkpoint the
models into MODELDATA → mark COMPLETED. ``run_evaluation``: register an
EVALUATING EvaluationInstance → ``engine.batch_eval`` → evaluator → store
one-liner/HTML/JSON results → EVALCOMPLETED.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import traceback
from typing import Any, List, Optional, Sequence

from incubator_predictionio_tpu.core.engine import Engine
from incubator_predictionio_tpu.core.params import EngineParams, WorkflowParams
from incubator_predictionio_tpu.data.storage import (
    EngineInstance,
    EvaluationInstance,
    Model,
    Storage,
)
from incubator_predictionio_tpu.parallel.context import RuntimeContext
from incubator_predictionio_tpu.utils import json_codec, tracing
from incubator_predictionio_tpu.utils.times import now_utc
from incubator_predictionio_tpu.workflow import checkpoint

logger = logging.getLogger(__name__)

#: sentinel for "pod leg did not run" — None is a legal return value for a
#: custom evaluator (and conceivably a train), and confusing the two would
#: re-run a collective after the workers exited: a permanent hang
_UNSET = object()


def _continuation_models(
    engine_params: EngineParams,
    engine_id: str,
    engine_version: str,
    engine_variant: str,
) -> Optional[List[Any]]:
    """Previous COMPLETED run's models for the continuation retrain, or
    None when continuation is off / inapplicable.

    Auto-disable (the spec-change guard): ANY difference in the stored
    data-source / preparator / algorithm params invalidates the prior
    model — a changed rank or λ makes its factors unusable, and a
    changed data spec rebuilds the id space the prefix mapping relies
    on. Strict JSON equality keeps the check simple; a refused
    continuation only costs a cold train. Model-load failures likewise
    degrade to fresh training — continuation is an optimization, never
    a correctness dependency."""
    from incubator_predictionio_tpu.ops.retrain import continue_enabled

    if not continue_enabled():
        return None
    try:
        prev = Storage.get_meta_data_engine_instances().get_latest_completed(
            engine_id, engine_version, engine_variant)
        if prev is None:
            return None
        current = (
            json_codec.dumps(engine_params.data_source_params),
            json_codec.dumps(engine_params.preparator_params),
            json_codec.dumps(engine_params.algorithm_params_list),
        )
        stored = (prev.data_source_params, prev.preparator_params,
                  prev.algorithms_params)
        if current != stored:
            logger.info(
                "continuation disabled: engine params changed since "
                "instance %s", prev.id)
            return None
        blob = Storage.get_model_data_models().get(prev.id)
        if blob is None:
            return None
        models = checkpoint.deserialize_models(blob.models)
        logger.info("continuation: seeding retrain from instance %s",
                    prev.id)
        return models
    except Exception:
        logger.exception("continuation model load failed; training fresh")
        return None


def make_runtime_context(
    workflow_params: Optional[WorkflowParams] = None,
) -> RuntimeContext:
    """WorkflowContext.scala parity — runtime_conf drives mesh/seed config."""
    conf = dict((workflow_params.runtime_conf if workflow_params else {}) or {})
    return RuntimeContext(
        seed=int(conf.get("seed", 0)),
        model_parallelism=int(conf.get("model_parallelism", 1)),
        conf=conf,
    )


class CoreWorkflow:
    TRAIN_STATUS_INIT = "INIT"
    TRAIN_STATUS_TRAINING = "TRAINING"
    TRAIN_STATUS_COMPLETED = "COMPLETED"
    TRAIN_STATUS_ABORTED = "ABORTED"
    EVAL_STATUS_EVALUATING = "EVALUATING"
    EVAL_STATUS_COMPLETED = "EVALCOMPLETED"
    EVAL_STATUS_ABORTED = "EVALABORTED"

    @staticmethod
    def run_train(
        engine: Engine,
        engine_params: EngineParams,
        engine_id: str = "default",
        engine_version: str = "NOT_VERSIONED",
        engine_variant: str = "default",
        engine_factory: str = "",
        params: Optional[WorkflowParams] = None,
        ctx: Optional[RuntimeContext] = None,
        env: Optional[dict] = None,
        prev_models: Optional[List[Any]] = None,
    ) -> str:
        """Train, checkpoint, register. Returns the engine instance ID.

        ``prev_models`` is the explicit continuation seam: when given,
        those models seed the O(delta) continuation retrain directly —
        for callers that already hold (and vouch for) a compatible
        model, bypassing the instance lookup AND its strict
        params-equality auto-disable; when None — the normal path, and
        what the freshness controller's retrain actuator uses — the
        last COMPLETED instance's models are loaded, guarded by the
        auto-disable (:func:`_continuation_models`).

        In a multi-process pod (`pio train --hosts`, or an
        externally-provisioned jax.distributed runtime) every process
        runs the same SPMD training program and participates in the
        collective host-materialization of the trained models
        (checkpoint.host_materialize — pod-sharded arrays cannot be
        fetched by one process after the others exit), but only process 0
        owns the metadata/model writes — the workers then return an empty
        id, exactly like Spark executors vs the driver."""
        params = params or WorkflowParams()
        ctx = ctx or make_runtime_context(params)
        from incubator_predictionio_tpu.parallel import distributed

        pod = distributed.is_multihost()
        if pod and prev_models is not None:
            # pod models are sharded and the continuation prefix
            # mapping is per-host — the seed cannot apply. Say so
            # loudly: the caller (the freshness controller's retrain
            # actuator) budgeted for an O(delta) wall and is getting a
            # cold full train instead.
            logger.warning(
                "prev_models ignored on the multi-host pod path "
                "(continuation retrain is single-host); training fresh")
            prev_models = None
        pre_trained = _UNSET
        # captured before the (possibly hours-long) pod training leg so the
        # persisted instance's start→end span covers training even though
        # the insert itself is deferred until after the collectives
        train_start = now_utc()
        tracer = tracing.Tracer(
            profile_dir=params.runtime_conf.get("profile_dir") or None
        )

        def make_instance(status: str) -> EngineInstance:
            # single source for the instance record — the pod abort path
            # and the normal INIT path must never drift apart field-wise
            return EngineInstance(
                id="",
                status=status,
                start_time=train_start,
                end_time=now_utc(),
                engine_id=engine_id,
                engine_version=engine_version,
                engine_variant=engine_variant,
                engine_factory=engine_factory,
                batch=params.batch,
                env=dict(env or {}),
                runtime_conf=dict(params.runtime_conf),
                data_source_params=json_codec.dumps(
                    engine_params.data_source_params),
                preparator_params=json_codec.dumps(
                    engine_params.preparator_params),
                algorithms_params=json_codec.dumps(
                    engine_params.algorithm_params_list),
                serving_params=json_codec.dumps(engine_params.serving_params),
            )

        if pod:
            # EVERY pod process runs the collective legs FIRST — before
            # any process touches fallible storage. Otherwise a
            # proc-0-only storage error (its insert/update) would strand
            # the workers inside untimed jax collectives forever.
            try:
                with tracer.activate():
                    models = engine.train(ctx, engine_params, params)
                    models = checkpoint.host_materialize(models)  # collective
                    # completion gate: COMPLETED must mean the WHOLE pod
                    # finished. Without this, a training function with no
                    # real cross-process dependency lets process 0 finish
                    # and persist even though a peer crashed mid-train —
                    # and a FAILED `pio train --hosts` run would leave a
                    # COMPLETED instance for deploy to pick up.
                    distributed.barrier("pio-train-complete")
            except Exception:
                if not distributed.is_pod_worker():
                    # the collective already failed, so storage I/O can no
                    # longer strand the workers — record the abort so the
                    # instance list shows the failure (single-host parity)
                    try:
                        Storage.get_meta_data_engine_instances().insert(
                            make_instance(CoreWorkflow.TRAIN_STATUS_ABORTED))
                    except Exception:
                        logger.exception(
                            "failed to record ABORTED pod train instance")
                raise
            if distributed.is_pod_worker():
                logger.info(
                    "process %d/%d: training shard complete (process 0 "
                    "persists the instance)",
                    distributed.process_index(),
                    distributed.process_count())
                return ""
            pre_trained = models
        instances = Storage.get_meta_data_engine_instances()
        instance = make_instance(CoreWorkflow.TRAIN_STATUS_INIT)
        instance_id = instances.insert(instance)
        instance = dataclasses.replace(instance, id=instance_id)
        logger.info("Training engine instance %s", instance_id)
        try:
            instances.update(
                dataclasses.replace(instance,
                                    status=CoreWorkflow.TRAIN_STATUS_TRAINING)
            )
            # on the pod path training already ran (and profiled) inside
            # the first tracer.activate(); don't start the profiler again
            # over the cached models — it would emit an empty extra trace
            with tracer.activate(profile=pre_trained is _UNSET):
                if pre_trained is _UNSET and prev_models is None:
                    # continuation seed (single-host only — pod models are
                    # sharded and the prefix mapping is per-host): timed as
                    # its own phase so /metrics shows the seed-load leg
                    with tracing.phase("continue_seed"):
                        prev_models = _continuation_models(
                            engine_params, engine_id, engine_version,
                            engine_variant)
                models = (pre_trained if pre_trained is not _UNSET
                          else engine.train(ctx, engine_params, params,
                                            prev_models=prev_models))
                algo_params = [
                    p for _n, p in engine_params.algorithm_params_list
                ]
                with tracing.phase("checkpoint"):
                    blob = checkpoint.serialize_models(
                        models, instance_id, ctx, algo_params=algo_params
                    )
                    Storage.get_model_data_models().insert(
                        Model(instance_id, blob)
                    )
            instances.update(
                dataclasses.replace(
                    instance,
                    status=CoreWorkflow.TRAIN_STATUS_COMPLETED,
                    end_time=now_utc(),
                    runtime_conf={**instance.runtime_conf, **tracer.to_conf()},
                )
            )
            # phase walls → registry gauges: one /metrics scrape shows
            # this run's read/prepare/train/checkpoint breakdown next to
            # the serving metrics (docs/observability.md). Telemetry
            # export must never demote a COMPLETED train to ABORTED
            try:
                tracer.export_metrics()
            except Exception:
                logger.exception("phase-metrics export failed")
            logger.info(
                "Training completed; engine instance %s saved (%d bytes of "
                "models); %s", instance_id, len(blob), tracer.summary(),
            )
        except Exception:
            instances.update(
                dataclasses.replace(
                    instance,
                    status=CoreWorkflow.TRAIN_STATUS_ABORTED,
                    end_time=now_utc(),
                )
            )
            raise
        return instance_id

    @staticmethod
    def load_models(
        instance_id: str,
        engine: Optional[Engine] = None,
        engine_params: Optional[EngineParams] = None,
        ctx: Optional[RuntimeContext] = None,
        params: Optional[WorkflowParams] = None,
    ) -> List[Any]:
        """Restore checkpointed models (CreateServer.scala:216-220 kryo invert
        + Engine.prepareDeploy).

        The decoder resolves model dataclasses from ALREADY-IMPORTED modules
        only (checkpoint._resolve_dataclass — no import side effects on
        decode). Deploy/eval satisfy this by construction: the engine
        factory is resolved (hence its module imported) before any blob is
        read. Programmatic callers passing just ``instance_id`` must import
        the engine module first, or set ``PIO_CHECKPOINT_ALLOW_IMPORT=1``
        to restore the pre-r3 importlib behavior for trusted stores."""
        blob = Storage.get_model_data_models().get(instance_id)
        if blob is None:
            raise ValueError(f"No models stored for engine instance {instance_id}")
        models = checkpoint.deserialize_models(blob.models)
        if engine is not None and engine_params is not None:
            ctx = ctx or make_runtime_context(params)
            models = engine.prepare_deploy(
                ctx, engine_params, instance_id, models, params
            )
        return models

    @staticmethod
    def run_evaluation(
        evaluation: Any,
        engine_params_list: Sequence[EngineParams],
        evaluation_class: str = "",
        engine_params_generator_class: str = "",
        params: Optional[WorkflowParams] = None,
        ctx: Optional[RuntimeContext] = None,
        env: Optional[dict] = None,
    ) -> tuple[str, Any]:
        """Evaluate all candidates. Returns (evaluation instance id, result).

        Pod semantics mirror run_train: non-zero processes compute their
        SPMD shard of every candidate but never touch storage; process 0
        persists the instance and returns the result."""
        params = params or WorkflowParams()
        ctx = ctx or make_runtime_context(params)
        from incubator_predictionio_tpu.parallel import distributed

        pod_result = _UNSET
        eval_start = now_utc()

        def _eval():
            eval_data = evaluation.engine.batch_eval(
                ctx, engine_params_list, params)
            return evaluation.evaluator.evaluate(
                ctx, evaluation, eval_data, params)

        def make_instance(status: str) -> EvaluationInstance:
            # single source for the record — pod abort vs EVALUATING paths
            # must never drift apart field-wise
            return EvaluationInstance(
                id="",
                status=status,
                start_time=eval_start,
                end_time=now_utc(),
                evaluation_class=evaluation_class,
                engine_params_generator_class=engine_params_generator_class,
                batch=params.batch,
                env=dict(env or {}),
                runtime_conf=dict(params.runtime_conf),
            )

        if distributed.is_multihost():
            # collective legs first on EVERY process (same rationale as
            # run_train: no proc-0 storage I/O while workers sit in
            # untimed collectives)
            evaluator = evaluation.evaluator
            if distributed.is_pod_worker():
                # process 0 owns best.json too (same-content races on a
                # shared filesystem are still races)
                saved_path = getattr(evaluator, "output_path", None)
                if saved_path is not None:
                    evaluator.output_path = None
                try:
                    result = _eval()
                    distributed.barrier("pio-eval-complete")
                finally:
                    if saved_path is not None:
                        evaluator.output_path = saved_path
                return "", result
            try:
                pod_result = _eval()
                # completion gate, same rationale as run_train: an
                # EVALCOMPLETED instance must mean the WHOLE pod finished
                # — without this a crashed peer still lets process 0
                # persist when the evaluation has no true cross-process
                # dependency
                distributed.barrier("pio-eval-complete")
            except Exception:
                # collective already failed; record the abort (the
                # single-host path below does this inside its try block)
                try:
                    Storage.get_meta_data_evaluation_instances().insert(
                        make_instance(CoreWorkflow.EVAL_STATUS_ABORTED))
                except Exception:
                    logger.exception(
                        "failed to record ABORTED pod evaluation instance")
                raise
        instances = Storage.get_meta_data_evaluation_instances()
        instance = make_instance(CoreWorkflow.EVAL_STATUS_EVALUATING)
        instance_id = instances.insert(instance)
        instance = dataclasses.replace(instance, id=instance_id)
        try:
            result = pod_result if pod_result is not _UNSET else _eval()
            if getattr(result, "no_save", False):
                # FakeWorkflow results are not persisted
                # (CoreWorkflow.scala:138-142 noSave branch).
                instances.update(
                    dataclasses.replace(
                        instance,
                        status=CoreWorkflow.EVAL_STATUS_COMPLETED,
                        end_time=now_utc(),
                    )
                )
                return instance_id, result
            instances.update(
                dataclasses.replace(
                    instance,
                    status=CoreWorkflow.EVAL_STATUS_COMPLETED,
                    end_time=now_utc(),
                    evaluator_results=result.to_one_liner(),
                    evaluator_results_html=result.to_html(),
                    evaluator_results_json=json.dumps(result.to_jsonable()),
                )
            )
            logger.info("Evaluation %s completed: %s", instance_id,
                        result.to_one_liner())
            return instance_id, result
        except Exception:
            logger.error("Evaluation %s aborted:\n%s", instance_id,
                         traceback.format_exc())
            instances.update(
                dataclasses.replace(
                    instance,
                    status=CoreWorkflow.EVAL_STATUS_ABORTED,
                    end_time=now_utc(),
                )
            )
            raise
