"""Speed layer — device-side fold-in serving between retrains.

PredictionIO is explicitly a Lambda Architecture; this package is the
missing speed leg next to the batch leg (train + O(delta) continuation
retrain) and the serving leg. It keeps deployed models fresh WITHOUT
retraining:

- :mod:`.foldin` — batched regularized least-squares row solves against
  the frozen other-side factors, reusing the training CG machinery
  (ops/als.py) and padded to a fixed bucket ladder so the compile cache
  stays warm (no per-query recompiles). This is the same row solve ALX
  (arxiv 2112.02194) runs at scale on TPUs.
- :mod:`.overlay` — the real-time overlay: a log-tail cursor subscriber
  (base.Events.tail_cursor / read_interactions_since) maintains a
  per-key dirty set, folds dirty/unknown keys in batches, and caches the
  solved vectors with a TTL, keyed (key, cursor). Invalidated wholesale
  on hot model swap and per-key on newer events.
- :mod:`.cache` — the bounded TTL micro-cache the serving hot paths use
  in front of synchronous EventStore reads (the `serve-blocking-io`
  pio-lint rule points here).

Serving integration: the prediction server builds one overlay per
algorithm that offers a fold-in config (core/base.py
``Algorithm.make_speed_overlay``) and the engines consult it before the
base model — fresh sessions and brand-new users get exact model-quality
scores seconds after their first events, not after the next retrain.
"""

__all__ = [
    "FoldInSolver",
    "SpeedOverlay",
    "SpeedOverlayConfig",
    "TTLCache",
    "foldin_compile_cache_size",
]

#: lazy re-exports (PEP 562): importing ``speed.cache`` from a serving
#: algorithm's __init__ must NOT drag jax in through ``foldin`` — the
#: storage-only CLI verbs pin their platform before any jax import
_EXPORTS = {
    "TTLCache": ("incubator_predictionio_tpu.speed.cache", "TTLCache"),
    "FoldInSolver": (
        "incubator_predictionio_tpu.speed.foldin", "FoldInSolver"),
    "foldin_compile_cache_size": (
        "incubator_predictionio_tpu.speed.foldin",
        "foldin_compile_cache_size"),
    "SpeedOverlay": (
        "incubator_predictionio_tpu.speed.overlay", "SpeedOverlay"),
    "SpeedOverlayConfig": (
        "incubator_predictionio_tpu.speed.overlay", "SpeedOverlayConfig"),
}


def __getattr__(name: str):
    try:
        module, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    return getattr(importlib.import_module(module), attr)
