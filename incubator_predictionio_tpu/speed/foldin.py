"""Batched device fold-in: solve factor rows against frozen factors.

THE speed-layer compute kernel. For a user (or item) with events newer
than the deployed instance, the exact "what would training have given
this row" answer is one regularized least-squares solve of that row
against the OTHER side's frozen factor table — the same per-row normal
equation ALS solves every sweep, so this module reuses the training
assembly + CG machinery verbatim (ops/als.py ``_gram_rhs_nnz`` /
``_reg_solve``): fold-in numerics cannot drift from training numerics.

Shape discipline: serving traffic produces arbitrary (batch, degree)
pairs, and a naive jit would compile per query. Pending rows are instead
padded onto a small fixed ladder of bucket widths × power-of-two batch
sizes, so the number of compiled variants is bounded by the ladder
(len(widths) × log2(max_batch) + 1) regardless of traffic — steady state
serves entirely from the jit cache (``foldin_compile_cache_size`` is the
counter the tests assert on). Histories longer than the widest bucket
keep their most recent entries (the solve stays O(ladder) per row; a
power user's full history re-enters at the next retrain anyway).
"""

from __future__ import annotations

import functools
import os
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from incubator_predictionio_tpu.obs import profile as _profile
from incubator_predictionio_tpu.ops import als as _als


def foldin_flops(degrees: Sequence[int], rank: int,
                 cg_iters: int) -> float:
    """Analytic useful FLOPs of one fold-in bucket dispatch: per row of
    degree d the Gram assembly is 4·d·K² + rhs 2·d·K, plus the CG solve
    ~iters·2·K² per row — the same counting convention as
    ``ops.als.train_flops`` (padding waste lowers MFU, it never counts
    as work)."""
    k = float(rank)
    d = float(sum(int(x) for x in degrees))
    return 4.0 * d * k * k + 2.0 * d * k \
        + len(degrees) * cg_iters * 2.0 * k * k


def _width_ladder() -> Tuple[int, ...]:
    """Fixed bucket widths (ascending). Read per call so tests/operators
    can override at runtime; the jit cache keys on the resulting shapes
    either way."""
    raw = os.environ.get("PIO_SPEED_WIDTHS", "8,32,128,512")
    widths = sorted({max(int(w), 1) for w in raw.split(",") if w.strip()})
    return tuple(widths) or (8, 32, 128, 512)


def max_batch() -> int:
    """Largest rows-per-dispatch bucket (power of two). Public: the
    overlay's queue-depth-adaptive fold-in budget (speed/overlay.py)
    sizes its per-poll rungs in multiples of this, so every full
    dispatch it requests is a full ladder bucket with zero padding
    waste."""
    try:
        n = int(os.environ.get("PIO_SPEED_MAX_BATCH", "64"))
    except ValueError:
        n = 64
    return 1 << max(n - 1, 0).bit_length()


#: original private name, kept for callers/tests that grew against it
_max_batch = max_batch


@functools.partial(jax.jit, static_argnames=("reg_nnz", "implicit",
                                             "cg_iters"))
def _solve_rows(
    other_factors: jax.Array,   # [M, K] f32 — frozen other-side table
    yty: Optional[jax.Array],   # [K, K] shared Gram (implicit) or None
    cols: jax.Array,            # [B, D] int32, padding cols = 0
    vals: jax.Array,            # [B, D] f32
    mask: jax.Array,            # [B, D] f32 in {0, 1}
    l2: jax.Array,              # scalar f32 (operand — no recompiles)
    alpha: jax.Array,           # scalar f32
    reg_nnz: bool,
    implicit: bool,
    cg_iters: int,
) -> jax.Array:
    """One ladder bucket's fold-in solve → [B, K] f32 (0 for empty rows).

    Exactly the training bucket solve: explicit mode is the MLlib ALS-WR
    λ(·nnz) ridge, implicit mode the Hu-Koren-Volinsky system with the
    batch-shared YᵗY kept out of the matrix (ops/als.py)."""
    gram, rhs, nnz = _als._gram_rhs_nnz(
        other_factors, cols, vals, mask, jnp.float32,
        jax.lax.Precision.HIGHEST, implicit=implicit, alpha=alpha)
    return _als._reg_solve(gram, rhs, nnz, l2, reg_nnz, implicit=implicit,
                           yty=yty, cg_iters=cg_iters)


@functools.partial(jax.jit, static_argnames=("reg_nnz", "implicit",
                                             "cg_iters"))
def _solve_rows_kernel(
    other_factors: jax.Array,   # [M, K] f32 — frozen other-side table
    yty: Optional[jax.Array],   # [K, K] shared Gram (implicit) or None
    cols: jax.Array,
    vals: jax.Array,
    mask: jax.Array,
    l2: jax.Array,
    alpha: jax.Array,
    reg_nnz: bool,
    implicit: bool,
    cg_iters: int,
) -> jax.Array:
    """Kernel-path twin of :func:`_solve_rows`: one ladder bucket through
    the fused gather+Gram+CG Pallas kernel (ops/pallas_kernels
    ``als_fused_solve_cg_pallas``) — the SAME kernel the training sweeps
    dispatch, so fold-in and training share one fused code path end to
    end. Implicit rides the precomputed YᵗY and the training path's
    doubled CG budget. Same jit-cache discipline: one compiled variant
    per ladder bucket, counted by :func:`foldin_compile_cache_size`."""
    from incubator_predictionio_tpu.ops.pallas_kernels import (
        als_fused_solve_cg_pallas,
    )

    return als_fused_solve_cg_pallas(
        other_factors, cols, vals, mask, l2, reg_nnz=reg_nnz,
        iters=cg_iters * (2 if implicit else 1), implicit=implicit,
        alpha=alpha, yty=yty)


def foldin_compile_cache_size() -> int:
    """Number of compiled fold-in variants in this process — the
    no-per-query-recompilation contract's counter. Bounded by the bucket
    ladder (widths × batch sizes × param-flag combinations actually
    used, across BOTH the XLA and the fused-kernel solve paths); tests
    assert it stops growing once the ladder is warm."""
    return int(_solve_rows._cache_size()) \
        + int(_solve_rows_kernel._cache_size())


class FoldInSolver:
    """Batched fold-in against one frozen factor table.

    ``rows`` are (cols, vals) int32/float32 pairs — the key's observed
    interactions indexed into the other side's factor table. ``solve``
    groups them onto the bucket ladder, dispatches one jitted solve per
    occupied (width, batch) bucket, and returns the solved vectors in
    input order.
    """

    def __init__(
        self,
        other_factors: Any,          # [M, K] (host or device)
        l2: float,
        reg_nnz: bool = True,
        implicit: bool = False,
        alpha: float = 1.0,
        cg_iters: Optional[int] = None,
        use_kernel: Optional[bool] = None,
    ) -> None:
        from incubator_predictionio_tpu.parallel.placement import (
            is_distributed,
        )

        # a mesh-sharded frozen table (a placed model's factors) is
        # served AS-IS: jnp.asarray keeps the sharding, the ladder
        # solves run under plain jit and GSPMD routes each history's
        # gathers to the owning shard — no host round trip, no
        # full-table replication on the serving host
        self.other_factors = jnp.asarray(other_factors, jnp.float32)
        self.sharded = is_distributed(self.other_factors)
        self.rank = int(self.other_factors.shape[1])
        self.l2 = float(l2)
        self.reg_nnz = bool(reg_nnz)
        self.implicit = bool(implicit)
        self.alpha = float(alpha)
        self.cg_iters = int(cg_iters if cg_iters is not None
                            else _als._CG_ITERS)
        # fused-kernel routing, resolved ONCE per deploy (the Mosaic
        # probe compiles a real kernel — never per fold-in): the ladder
        # buckets dispatch the SAME fused gather+Gram+CG kernel training
        # uses, when the frozen table fits its VMEM budget. None = auto
        # (PIO_ALS_FUSED_GRAM + per-variant probe); tests force True,
        # which serves via interpret on Mosaic-less backends.
        from incubator_predictionio_tpu.ops.pallas_kernels import (
            als_fused_fits,
        )

        fits = als_fused_fits(self.other_factors.shape[0], self.rank,
                              jnp.float32)
        if use_kernel is None:
            use_kernel = fits and _als._fused_enabled(self.implicit,
                                                      warm=False)
        # pallas_call does not auto-partition under GSPMD: a sharded
        # frozen table always serves through the XLA assembly
        self.use_kernel = bool(use_kernel) and fits and not self.sharded
        # the batch-shared YᵗY of implicit ALS: computed ONCE per deploy
        # (it only depends on the frozen table), not once per fold-in
        self._yty = (_als._gram_all(self.other_factors,
                                    jax.lax.Precision.HIGHEST)
                     if self.implicit else None)

    # -- ladder packing -----------------------------------------------------
    @staticmethod
    def _bucket_width(degree: int, widths: Sequence[int]) -> int:
        for w in widths:
            if degree <= w:
                return w
        return widths[-1]

    def solve(
        self, rows: Sequence[Tuple[np.ndarray, np.ndarray]]
    ) -> np.ndarray:
        """Fold in a batch of keys → [len(rows), K] f32 (in input order).

        Empty histories solve to the zero vector (the cold-start fixed
        point); histories wider than the ladder keep their most RECENT
        ``widths[-1]`` interactions (callers pass history oldest-first).
        """
        n = len(rows)
        out = np.zeros((n, self.rank), np.float32)
        if n == 0:
            return out
        widths = _width_ladder()
        max_b = _max_batch()
        by_width: dict = {}
        for slot, (cols, vals) in enumerate(rows):
            cols = np.asarray(cols, np.int32).reshape(-1)
            vals = np.asarray(vals, np.float32).reshape(-1)
            d = int(cols.shape[0])
            if d == 0:
                continue
            cap = widths[-1]
            if d > cap:  # keep the newest interactions
                cols, vals, d = cols[-cap:], vals[-cap:], cap
            by_width.setdefault(self._bucket_width(d, widths), []).append(
                (slot, cols, vals))
        for width, members in sorted(by_width.items()):
            for s in range(0, len(members), max_b):
                chunk = members[s:s + max_b]
                b = len(chunk)
                b_pad = min(1 << max(b - 1, 0).bit_length(), max_b)
                cols = np.zeros((b_pad, width), np.int32)
                vals = np.zeros((b_pad, width), np.float32)
                mask = np.zeros((b_pad, width), np.float32)
                for r, (_slot, c, v) in enumerate(chunk):
                    cols[r, :len(c)] = c
                    vals[r, :len(v)] = v
                    mask[r, :len(c)] = 1.0
                _pt0 = _profile.t0()
                solve_fn = (_solve_rows_kernel if self.use_kernel
                            else _solve_rows)
                sol = np.asarray(solve_fn(
                    self.other_factors, self._yty,
                    jnp.asarray(cols), jnp.asarray(vals), jnp.asarray(mask),
                    jnp.float32(self.l2), jnp.float32(self.alpha),
                    reg_nnz=self.reg_nnz, implicit=self.implicit,
                    cg_iters=self.cg_iters))
                # np.asarray already synced the dispatch: result=None
                _profile.record(
                    _pt0, "foldin", "foldin_solve",
                    foldin_flops([len(c) for _s, c, _v in chunk],
                                 self.rank, self.cg_iters)
                    if _pt0 is not None else 0.0)
                for r, (slot, _c, _v) in enumerate(chunk):
                    out[slot] = sol[r]
        return out

    def warmup(self) -> None:
        """Pre-compile every ladder width at batch size 1 (the common
        trickle shape) so the first live fold-in never pays an XLA
        compile. Larger batch shapes compile on first use — bounded by
        the ladder either way."""
        for width in _width_ladder():
            # degree == width so each solve lands in ITS bucket (a
            # shorter row would all fall into the smallest bucket)
            self.solve([(np.zeros(width, np.int32),
                         np.ones(width, np.float32))])


def dense_reference_solve(
    other_factors: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    l2: float,
    reg_nnz: bool = True,
    implicit: bool = False,
    alpha: float = 1.0,
) -> np.ndarray:
    """Dense numpy least-squares reference for ONE row — the differential
    oracle the fold-in tests compare every ladder bucket against.

    Explicit: (XᵀX + λ·nnz·I) w = Xᵀy. Implicit (Hu-Koren-Volinsky with
    binary preference): (YᵗY + Yᵤᵗ(Cᵤ−I)Yᵤ + λI) w = Yᵤᵗcᵤ, c = 1+αr.
    """
    other = np.asarray(other_factors, np.float64)
    x = other[np.asarray(cols, np.int64)]
    y = np.asarray(vals, np.float64)
    k = other.shape[1]
    if implicit:
        conf = 1.0 + alpha * y
        a = other.T @ other + x.T @ np.diag(conf - 1.0) @ x \
            + l2 * np.eye(k)
        b = x.T @ conf
    else:
        lam = l2 * (max(len(y), 1) if reg_nnz else 1.0)
        a = x.T @ x + lam * np.eye(k)
        b = x.T @ y
    return np.linalg.solve(a, b).astype(np.float32)
