"""The real-time overlay: log-tail subscriber + fold-in cache.

One :class:`SpeedOverlay` serves one deployed algorithm. A poll cycle:

1. ``read_interactions_since(cursor)`` — the O(delta) tail read — yields
   every interaction written since the last poll.
2. Every key (user for recommendation/ecommerce, item for
   similarproduct) seen in the tail is marked DIRTY with the new cursor,
   its overlay entry dropped (per-key invalidation on newer events) and
   its version bumped (the serving micro-caches key on this).
3. Dirty keys are folded in as ONE batched device solve
   (:class:`~.foldin.FoldInSolver`): the key's full event history is
   read from the store (hash-pushdown ``find`` on the entity side) and
   solved against the frozen other-side factors. Solved vectors land in
   the overlay keyed ``(key, cursor)`` with a TTL.

Serving threads call :meth:`lookup` — a dict probe under a lock, no
storage or device work ever happens on the query path. The prediction
server invalidates the whole overlay on hot model swap (/reload) and
rebuilds it against the new model's factors.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from incubator_predictionio_tpu.obs import metrics as obs_metrics
from incubator_predictionio_tpu.obs.freshness import FreshnessTracker
from incubator_predictionio_tpu.speed.foldin import FoldInSolver
from incubator_predictionio_tpu.utils import times

logger = logging.getLogger(__name__)

#: process-wide speed-layer telemetry (docs/observability.md). Shared by
#: every overlay in the process — the scrape wants totals, and multiple
#: deployed algorithms booking into one family keeps cardinality flat.
_HITS = obs_metrics.REGISTRY.counter(
    "pio_speed_hits_total", "overlay lookups served a folded-in vector")
_MISSES = obs_metrics.REGISTRY.counter(
    "pio_speed_misses_total",
    "overlay lookups that fell through to the base model")
_FOLDIN_SECONDS = obs_metrics.REGISTRY.histogram(
    "pio_speed_foldin_seconds",
    "wall of one batched fold-in solve (history read + device solve)")
_FOLDIN_ROWS = obs_metrics.REGISTRY.counter(
    "pio_speed_foldin_rows_total", "keys folded in by the speed layer")
_OVERLAY_SIZE = obs_metrics.REGISTRY.gauge(
    "pio_speed_overlay_size", "folded-in vectors currently cached "
    "(all overlays in this process; summed at scrape time)")
#: live overlays, for the scrape-time size collector (weak: a dropped
#: overlay must never be pinned by telemetry)
_LIVE_OVERLAYS: "weakref.WeakSet" = weakref.WeakSet()


def _collect_overlay_size() -> None:
    _OVERLAY_SIZE.set(sum(len(ov._vectors) for ov in list(_LIVE_OVERLAYS)))


obs_metrics.REGISTRY.register_collector("speed_overlay_size",
                                        _collect_overlay_size)
_CURSOR_LAG = obs_metrics.REGISTRY.gauge(
    "pio_speed_cursor_lag_events",
    "events written but not yet seen by the overlay poll (last poll)")


@dataclasses.dataclass
class SpeedOverlayConfig:
    """Everything one overlay needs: where the events are, which side is
    being folded in, and the training hyperparameters the solve must
    match."""

    app_name: str
    channel_name: Optional[str] = None
    #: engine name for the per-engine freshness series (BOUNDED label
    #: set: one value per deployed engine template, never a key/id)
    engine: str = "default"
    entity_type: str = "user"
    target_entity_type: str = "item"
    event_names: Tuple[str, ...] = ("rate",)
    value_prop: Optional[str] = None
    event_values: Optional[Dict[str, float]] = None
    default_value: float = 1.0
    #: which side of the interaction stream is folded in: "entity"
    #: (users — recommendation/ecommerce) or "target" (items —
    #: similarproduct's new-item fold-in)
    key_side: str = "entity"
    #: fold-in hyperparameters — MUST match the deployed model's training
    l2: float = 0.1
    reg_nnz: bool = True
    implicit: bool = False
    alpha: float = 1.0
    #: post-solve transform (similarproduct normalizes to unit vectors)
    transform: Optional[Callable[[np.ndarray], np.ndarray]] = None
    #: history cap per key (newest kept) and per-poll fold-in budget.
    #: ``max_keys_per_poll`` is the budget LADDER BASE, not a fixed
    #: cap: under dirty backlog the overlay doubles its per-poll budget
    #: up to ``max_keys_per_poll × max_keys_growth`` and collapses back
    #: when the backlog drains — the fold-in plane's twin of the
    #: serving scheduler's queue-depth-adaptive batching
    #: (serving/scheduler.py; docs/production.md "Serving fleet")
    max_history: int = 512
    max_keys_per_poll: int = 256
    #: backlog growth headroom: the adaptive budget's cap as a multiple
    #: of the base (16 → a 256 base may reach 4096 keys/poll)
    max_keys_growth: int = 16
    ttl_s: float = 300.0


class SpeedOverlay:
    """TTL'd overlay of fold-in vectors over one frozen factor table."""

    def __init__(
        self,
        config: SpeedOverlayConfig,
        other_factors: Any,            # frozen [M, K] factors (other side)
        other_index,                   # id -> column index (BiMap/dict)
        key_index=None,                # id -> row index of the KEY side
        clock: Optional[Callable[[], float]] = None,
        index_sink: Optional[
            Callable[[List[str], List[np.ndarray]], None]] = None,
    ) -> None:
        self.config = config
        #: publish hook for KEY-side serving indexes (the two-stage
        #: MIPS seam, ops/mips.publish_rows): called with every batch
        #: of (keys, solved vectors) the moment they publish, so a
        #: fold-in row is findable as a RESULT — exactly scored and
        #: merged — before the index's next rebuild. Telemetry-grade:
        #: a sink failure never fails the fold-in.
        self.index_sink = index_sink
        # the frozen table may be a MESH-SHARDED placed table
        # (parallel/placement.py): the solver serves it as-is — ladder
        # solves run under plain jit with GSPMD routing each history's
        # gathers to the owning shard, and only the tiny [K] fold-in
        # vectors ever reach this host (`solver.sharded` surfaces the
        # layout in /status). No full-table replication on the serving
        # box — the property that lets the speed layer ride a catalog
        # no single chip could hold.
        self.solver = FoldInSolver(
            other_factors, l2=config.l2, reg_nnz=config.reg_nnz,
            implicit=config.implicit, alpha=config.alpha)
        self.other_index = other_index
        #: the base model's key-side index: keys IN it have pre-deploy
        #: history the tail never saw (their fold-in reads the store);
        #: keys NOT in it are new since training and their accumulated
        #: tail history is complete — no storage read per cold key, the
        #: property that keeps a cold-start flood O(delta)
        self.key_index = key_index if key_index is not None else {}
        self._clock = clock if clock is not None else times.monotonic
        self._lock = threading.Lock()
        from collections import OrderedDict

        #: key id -> (vector, cursor_at_solve, expires_at). LRU-bounded
        #: (publish order ≈ expiry order at a constant TTL) and swept of
        #: expired entries every poll — lookups alone must not be the
        #: only reclaim path, or never-again-queried keys leak forever.
        self._vectors: "OrderedDict[str, Tuple[np.ndarray, int, float]]" \
            = OrderedDict()
        self._max_vectors = 1 << 17
        #: key id -> cursor of the newest event seen for it
        self._dirty: Dict[str, int] = {}
        #: key id -> monotonically increasing event-batch version (the
        #: serving micro-caches validate against this). LRU-bounded: an
        #: evicted key restarting at version 1 still MISSES any cached
        #: entry (validation is equality, not ordering), so eviction is
        #: always safe, never stale.
        self._versions: "OrderedDict[str, int]" = OrderedDict()
        self._max_versions = 1 << 18
        #: model-unknown keys' accumulated (cols, vals) history from the
        #: tail — LRU-bounded; per-key length capped at max_history
        self._tail_hist: "OrderedDict[str, Tuple[list, list]]" = \
            OrderedDict()
        self._tail_hist_max_keys = 65536
        #: end-to-end freshness trace (obs/freshness.py): append stamps
        #: ride the tail read in, fold-in publishes hand them over, and
        #: the first serving HIT closes the pio_freshness_seconds loop
        self.freshness = FreshnessTracker(engine=config.engine)
        #: queue-depth-adaptive per-poll fold-in budget: doubles from
        #: the configured base while dirty keys outpace it, collapses
        #: when the backlog drains (see SpeedOverlayConfig)
        self._budget_rung = max(int(config.max_keys_per_poll), 1)
        self.cursor = self._initial_cursor()
        _LIVE_OVERLAYS.add(self)
        self.hits = 0
        self.misses = 0
        self.foldins = 0
        self.last_lag = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _initial_cursor(self) -> int:
        from incubator_predictionio_tpu.data.store import EventStore

        try:
            return EventStore.tail_cursor(
                self.config.app_name, self.config.channel_name)
        except Exception:
            logger.exception("speed overlay: tail cursor unavailable")
            return -1

    @property
    def enabled(self) -> bool:
        with self._lock:
            return self.cursor >= 0

    # -- serving-side API (hot path: dict probes only) ----------------------
    def lookup(self, key_id: str) -> Optional[np.ndarray]:
        """Folded-in vector for ``key_id``, or None (miss). A key dirtied
        by events newer than its solve, or past its TTL, misses — the
        base model (or its fallback) serves until the next poll re-folds.
        """
        now = self._clock()
        with self._lock:
            entry = self._vectors.get(key_id)
            if entry is not None:
                vec, at_cursor, expires = entry
                if now < expires and self._dirty.get(key_id, -1) <= at_cursor:
                    self.hits += 1
                    _HITS.inc()
                else:
                    del self._vectors[key_id]
                    vec = None
            else:
                vec = None
            if vec is None:
                self.misses += 1
                _MISSES.inc()
        if vec is not None:
            # outside the overlay lock: first hit after a fold closes
            # the end-to-end freshness loop (dict pop + one observe;
            # later hits are a single probe)
            self.freshness.on_serve_hit(key_id)
        return vec

    def covers(self, key_id: str) -> bool:
        """True when :meth:`lookup` would hit — batched serving fast
        paths use this to route overlay keys through the per-query path
        WITHOUT booking a hit/miss."""
        now = self._clock()
        with self._lock:
            entry = self._vectors.get(key_id)
            return (entry is not None and now < entry[2]
                    and self._dirty.get(key_id, -1) <= entry[1])

    def key_version(self, key_id: str) -> int:
        """Monotonic per-key event version — bumps every time a poll sees
        new events for the key. The serving micro-caches (speed/cache.py)
        pass this as their entry version so a key's cached storage reads
        invalidate the moment the speed layer sees newer events."""
        with self._lock:
            return self._versions.get(key_id, 0)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "size": len(self._vectors),
                "dirty": len(self._dirty),
                "hits": self.hits,
                "misses": self.misses,
                "foldins": self.foldins,
                "cursor": self.cursor,
                "cursorLagEvents": self.last_lag,
                "shardedTable": self.solver.sharded,
                "foldinBudget": self._budget_rung,
            }

    # -- lifecycle ----------------------------------------------------------
    def invalidate_all(self) -> None:
        """Wholesale invalidation — hot model swap. The dirty set stays:
        those keys still have events newer than ANY model. In-flight
        freshness journeys die with their vectors (the successor overlay
        re-solves and restarts the trace)."""
        with self._lock:
            self._vectors.clear()
        self.freshness.invalidate()

    def known_keys(self) -> List[str]:
        """Every key this overlay has state for (solved, dirty, or
        tail-tracked) — what a successor overlay adopts on hot swap."""
        with self._lock:
            return list({*self._vectors, *self._dirty, *self._tail_hist})

    def adopt_keys(self, keys: Sequence[str]) -> int:
        """Hot-swap continuity: mark the predecessor overlay's keys
        dirty so the next polls RE-SOLVE them against the NEW factors
        (their events predate this overlay's cursor, so the tail alone
        would never surface them). Keys the new model trained on are
        skipped — the batch leg already covers them. Returns the number
        adopted."""
        n = 0
        with self._lock:
            for key in keys:
                if key in self.key_index:
                    continue
                self._dirty.setdefault(key, self.cursor)
                n += 1
        return n

    def start(self, interval_s: Optional[float] = None) -> None:
        """Spawn the background poller (daemon). No-op when the backend
        has no tail support."""
        if not self.enabled or self._thread is not None:
            return
        if interval_s is None:
            try:
                interval_s = float(os.environ.get("PIO_SPEED_POLL_S", "1.0"))
            except ValueError:
                interval_s = 1.0

        def run() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.poll()
                except Exception:
                    logger.exception("speed overlay poll failed")

        self._thread = threading.Thread(
            target=run, daemon=True, name="pio-speed-overlay")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    # -- the poll cycle -----------------------------------------------------
    def poll(self, max_keys: Optional[int] = None) -> Dict[str, Any]:
        """One subscriber cycle: tail read → dirty marking → batched
        fold-in. Returns a stats dict (tests and the bench read it)."""
        from incubator_predictionio_tpu.data.store import EventStore

        cfg = self.config
        # snapshot the cursor once: it is written under the lock by the
        # reset branch below and by _fold_chunks, and read by stats()
        # scrapes on other threads
        with self._lock:
            cursor = self.cursor
        if cursor < 0:
            return {"enabled": False}
        inter, _times, append_ms, new_cursor, reset = \
            EventStore.read_interactions_since(
                cursor, cfg.app_name, cfg.channel_name,
                entity_type=cfg.entity_type,
                target_entity_type=cfg.target_entity_type,
                event_names=cfg.event_names,
                value_prop=cfg.value_prop,
                event_values=cfg.event_values,
                default_value=cfg.default_value,
            )
        if reset or new_cursor < cursor:
            # log rewrite (compaction/drop): every derived fact is
            # suspect — invalidate and resynchronize
            logger.warning(
                "speed overlay: cursor reset (%d -> %d); invalidating",
                cursor, new_cursor)
            with self._lock:
                self._vectors.clear()
                self._dirty.clear()
                self._tail_hist.clear()
                self.cursor = new_cursor
            self.freshness.invalidate()
            return {"reset": True, "cursor": new_cursor}
        if cfg.key_side == "entity":
            tail_keys = inter.user_ids
            key_idx, other_ids, other_idx = (
                inter.user_idx, inter.item_ids, inter.item_idx)
        else:
            tail_keys = inter.item_ids
            key_idx, other_ids, other_idx = (
                inter.item_idx, inter.user_ids, inter.user_idx)
        # resolve ids/columns OUTSIDE the lock — a bulk import can put
        # millions of rows in one delta, and the overlay lock is on the
        # serving hot path (lookup); only the dict writes hold it, in
        # bounded chunks so lookups interleave
        keys = list(tail_keys)
        rows: List[Tuple[str, Optional[int], float]] = []
        #: key -> oldest append wall (ms) in this delta — the freshness
        #: trace's stage-0 anchor (all dirtied keys, model-known too)
        append_by_key: Dict[str, int] = {}
        for row in range(len(inter)):
            key = keys[int(key_idx[row])]
            if len(append_ms):
                a = int(append_ms[row])
                if a > 0:
                    prev = append_by_key.get(key)
                    append_by_key[key] = a if prev is None else min(prev, a)
            if key in self.key_index:
                continue
            col = self.other_index.get(other_ids[int(other_idx[row])])
            if col is None:
                continue
            rows.append((key, int(col), float(inter.values[row])))
        self.freshness.on_poll_batch(append_by_key)
        chunk = 8192
        for s in range(0, max(len(keys), 1), chunk):
            with self._lock:
                for key in keys[s:s + chunk]:
                    self._dirty[key] = new_cursor
                    self._versions[key] = self._versions.pop(key, 0) + 1
                    self._vectors.pop(key, None)  # newer events: drop
                while len(self._versions) > self._max_versions:
                    self._versions.popitem(last=False)
        # accumulate model-UNKNOWN keys' history from the tail itself:
        # complete for keys born after the overlay started, so their
        # fold-in never pays a per-key storage read
        for s in range(0, len(rows), chunk):
            with self._lock:
                for key, col, val in rows[s:s + chunk]:
                    hist = self._tail_hist.get(key)
                    if hist is None:
                        hist = ([], [])
                        self._tail_hist[key] = hist
                        while (len(self._tail_hist)
                               > self._tail_hist_max_keys):
                            self._tail_hist.popitem(last=False)
                    else:
                        self._tail_hist.move_to_end(key)
                    hist[0].append(col)
                    hist[1].append(val)
                    if len(hist[0]) > cfg.max_history:
                        del hist[0][0]
                        del hist[1][0]
        now = self._clock()
        with self._lock:
            self.cursor = new_cursor
            # sweep expired vectors (lookups only reclaim keys that get
            # queried again; idle keys must not pin their vectors)
            expired = [k for k, (_v, _c, exp) in self._vectors.items()
                       if now >= exp]
            for k in expired:
                del self._vectors[k]
            budget = (self._budget_rung if max_keys is None
                      else int(max_keys))
            backlog = len(self._dirty)
            pending = list(self._dirty.items())[:budget]
        solved = self._fold_in(pending, new_cursor) if pending else 0
        # adapt the per-poll budget to the observed backlog: grow one
        # rung while dirty keys outpace it (a cold-start flood folds in
        # O(log) polls instead of O(backlog/base)), collapse one rung
        # when the backlog sits at half the budget or less — the same
        # grow/collapse hysteresis as the serving scheduler's rung.
        # GROWN rungs round up to full fold-in dispatch buckets
        # (foldin.max_batch) so a grown budget never ends on a padded
        # partial batch; the configured base (the idle/collapse floor)
        # and the cap are never exceeded by the rounding. Explicit
        # max_keys overrides (tests, operators) bypassed the rung, so
        # they must not train it either.
        if max_keys is None:
            from incubator_predictionio_tpu.speed import foldin as _foldin

            bucket = max(_foldin.max_batch(), 1)
            base = max(int(cfg.max_keys_per_poll), 1)
            cap = base * max(int(cfg.max_keys_growth), 1)
            # the rung is read by stats() scrapes and the budget slice
            # above, both under the lock
            with self._lock:
                if backlog > self._budget_rung:
                    grown = min(self._budget_rung * 2, cap)
                    if grown > base:
                        grown = min(-(-grown // bucket) * bucket, cap)
                    self._budget_rung = grown
                elif 2 * backlog <= self._budget_rung:
                    self._budget_rung = max(self._budget_rung // 2, base)
        with self._lock:
            size = len(self._vectors)
            still_dirty = len(self._dirty)
        try:
            end_cursor = EventStore.tail_cursor(cfg.app_name,
                                                cfg.channel_name)
        except Exception:
            end_cursor = new_cursor
        lag = int(end_cursor) - int(new_cursor)
        if not 0 <= lag < (1 << 40):
            lag = 0  # log generation changed mid-poll; next poll resets
        with self._lock:
            self.last_lag = lag
        _CURSOR_LAG.set(lag)
        return {"tail_rows": int(len(inter)), "solved": solved,
                "size": size, "dirty": still_dirty,
                "cursor": new_cursor, "lag": lag}

    # -- history + solve ----------------------------------------------------
    def _history(self, key_id: str) -> Tuple[np.ndarray, np.ndarray]:
        """Full interaction history of one key → (cols, vals), oldest
        first, indexed into the other side's factor table. Runs on the
        POLLER thread — never on a serving thread.

        Model-unknown keys solve from their tail-accumulated history
        (no storage read — the cold-start flood path); model-known keys
        have pre-deploy interactions the tail never saw, so they pay one
        hash-pushdown store read per fold-in."""
        if key_id not in self.key_index:
            with self._lock:
                hist = self._tail_hist.get(key_id)
                if hist is not None:
                    return (np.asarray(hist[0], np.int32),
                            np.asarray(hist[1], np.float32))
        from incubator_predictionio_tpu.data.store import EventStore

        cfg = self.config
        kwargs: Dict[str, Any] = dict(
            app_name=cfg.app_name, channel_name=cfg.channel_name,
            entity_type=cfg.entity_type,
            target_entity_type=cfg.target_entity_type,
            event_names=list(cfg.event_names),
            limit=cfg.max_history, reversed=True)
        if cfg.key_side == "entity":
            kwargs["entity_id"] = key_id
        else:
            kwargs["target_entity_id"] = key_id
        fixed = cfg.event_values or {}
        cols: List[int] = []
        vals: List[float] = []
        for e in EventStore.find(**kwargs):
            other_id = (e.target_entity_id if cfg.key_side == "entity"
                        else e.entity_id)
            if other_id is None:
                continue
            col = self.other_index.get(other_id)
            if col is None:
                continue  # the other entity is unknown to the model
            if e.event in fixed:
                v = fixed[e.event]
            elif cfg.value_prop is not None:
                raw = e.properties.to_jsonable().get(cfg.value_prop)
                if not isinstance(raw, (int, float)) or isinstance(raw, bool):
                    continue
                v = float(raw)
            else:
                v = cfg.default_value
            cols.append(int(col))
            vals.append(float(v))
        # the find was newest-first (limit keeps the newest); restore
        # oldest-first so the solver's history-cap keeps the newest
        cols.reverse()
        vals.reverse()
        return np.asarray(cols, np.int32), np.asarray(vals, np.float32)

    def _fold_in(self, pending: Sequence[Tuple[str, int]],
                 cursor: int) -> int:
        """Batched fold-in of the pending dirty keys; returns the number
        of vectors published."""
        import time as _time

        cfg = self.config
        t0 = _time.perf_counter()
        keys = [k for k, _c in pending]
        rows = []
        for key in keys:
            try:
                rows.append(self._history(key))
            except Exception:
                logger.exception(
                    "speed overlay: history read failed for %r", key)
                rows.append((np.empty(0, np.int32), np.empty(0, np.float32)))
        vectors = self.solver.solve(rows)
        expires = self._clock() + cfg.ttl_s
        solved = 0
        published: List[str] = []
        published_vecs: List[np.ndarray] = []
        unpublished: List[str] = []
        with self._lock:
            for key, (cols, _vals), vec in zip(keys, rows, vectors):
                # only retire the dirty mark if no NEWER event arrived
                # while we solved (its cursor would exceed ours)
                if self._dirty.get(key, -1) <= cursor:
                    self._dirty.pop(key, None)
                if len(cols) == 0:
                    # nothing the model knows about: no vector
                    unpublished.append(key)
                    continue
                if cfg.transform is not None:
                    vec = cfg.transform(vec)
                vec32 = np.asarray(vec, np.float32)
                self._vectors[key] = (vec32, cursor, expires)
                self._vectors.move_to_end(key)
                published.append(key)
                published_vecs.append(vec32)
                solved += 1
            while len(self._vectors) > self._max_vectors:
                self._vectors.popitem(last=False)
            self.foldins += solved
        dt = _time.perf_counter() - t0
        if self.index_sink is not None and published:
            # outside the lock: the sink re-quantizes serving-index
            # rows / extends the exact tail (ops/mips.publish_rows)
            try:
                self.index_sink(published, published_vecs)
            except Exception:
                logger.exception("speed overlay: index sink failed")
            else:
                # fold-in → tail → daemon handoff: the publish may have
                # pushed the virtual-id tail past its rebuild trigger;
                # nudge the rebuild daemon instead of waiting out its
                # poll tick (no-op when the daemon isn't hosted here)
                try:
                    from incubator_predictionio_tpu.ops import (
                        mips_daemon,
                    )

                    mips_daemon.notify_publish()
                except Exception:
                    logger.exception(
                        "speed overlay: rebuild daemon nudge failed")
        # freshness stage 2: published keys now await their first serve;
        # keys with nothing foldable stop being traced (no vector can
        # ever serve their events until the next retrain)
        self.freshness.on_folded(published, dt)
        self.freshness.discard(unpublished)
        _FOLDIN_SECONDS.observe(dt)
        _FOLDIN_ROWS.inc(len(keys))
        return solved
