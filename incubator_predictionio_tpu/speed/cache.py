"""Bounded TTL micro-cache for serving-hot-path storage reads.

A ``predict()`` that does a synchronous EventStore round trip per query
(the ecommerce recent-events / constraint reads) pays the storage layer
on the serving hot path — the `serve-blocking-io` pio-lint hazard. This
cache bounds that cost: reads are served from a (maxsize, TTL)-bounded
LRU map, and entries carry an optional VERSION (the speed layer's
per-key event cursor) so a key whose entity received newer events misses
immediately instead of waiting out the TTL.

Clock discipline: all expiry decisions read the injectable clock
(``utils/times.monotonic`` by default) so tests advance a FakeClock
instead of sleeping.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Hashable, Optional, Tuple

from incubator_predictionio_tpu.utils import times


def serve_cache_ttl(default: float = 5.0) -> float:
    """THE micro-cache TTL knob (``PIO_SERVE_CACHE_TTL_S``,
    docs/production.md) — every serve-time micro-cache resolves its TTL
    through here so one knob tunes them all."""
    import os

    try:
        return float(os.environ.get("PIO_SERVE_CACHE_TTL_S", str(default)))
    except ValueError:
        return default


def store_version(app_name, channel_name=None):
    """Cache-invalidation version for serve-time micro-caches: the
    store's monotonic write cursor (the speed layer's anchor). ANY write
    bumps it, so e.g. a ``$set`` constraint flip still lands on the very
    next query, while queries between writes stop paying the storage
    scan. None (no app / backend without tail support / storage error)
    degrades to pure TTL."""
    from incubator_predictionio_tpu.data.store import EventStore

    if app_name is None:
        return None
    try:
        cur = EventStore.tail_cursor(app_name, channel_name)
    except Exception:
        return None
    return cur if cur >= 0 else None


class TTLCache:
    """Thread-safe bounded TTL+version cache.

    ``get_or_load(key, loader, version=...)`` is the serving-path entry
    point: one loader call per (key, version, TTL window), concurrent
    misses may race the loader (benign — last writer wins, both get a
    correct value). ``version=None`` means pure-TTL semantics.
    """

    def __init__(self, maxsize: int = 1024, ttl_s: float = 5.0,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.maxsize = max(int(maxsize), 1)
        self.ttl_s = float(ttl_s)
        self._clock = clock if clock is not None else times.monotonic
        self._lock = threading.Lock()
        #: key -> (value, expires_at, version)
        self._data: "OrderedDict[Hashable, Tuple[Any, float, Any]]" = \
            OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: Hashable, version: Any = None) -> Any:
        """→ ``(True, value)`` on hit, ``(False, None)`` on miss.

        The hit flag exists because cached values may legitimately be
        None/empty (an empty recent-events list is a valid cached read).
        A stored version differing from ``version`` is a miss — the
        speed-layer cursor invalidation."""
        now = self._clock()
        with self._lock:
            entry = self._data.get(key)
            if entry is not None:
                value, expires, ver = entry
                if now < expires and ver == version:
                    self._data.move_to_end(key)
                    self.hits += 1
                    return True, value
                del self._data[key]
            self.misses += 1
            return False, None

    def put(self, key: Hashable, value: Any, version: Any = None) -> None:
        now = self._clock()
        with self._lock:
            self._data[key] = (value, now + self.ttl_s, version)
            self._data.move_to_end(key)
            while len(self._data) > self.maxsize:
                self._data.popitem(last=False)

    def get_or_load(self, key: Hashable, loader: Callable[[], Any],
                    version: Any = None) -> Any:
        hit, value = self.get(key, version=version)
        if hit:
            return value
        value = loader()
        self.put(key, value, version=version)
        return value

    def invalidate(self, key: Hashable) -> None:
        with self._lock:
            self._data.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)
