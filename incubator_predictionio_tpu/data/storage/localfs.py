"""Local-filesystem model blob store.

Parity: data/.../storage/localfs/LocalFSModels.scala (and the HDFS twin,
hdfs/HDFSModels.scala — a GCS/remote-fs driver would slot in the same way).
Only the ``Models`` interface is provided, exactly like the reference.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

from incubator_predictionio_tpu.data.storage import base


class StorageClient(base.BaseStorageClient):
    def __init__(self, config: base.StorageClientConfig):
        super().__init__(config)
        path = config.properties.get("PATH", "~/.pio_tpu/models")
        self.base_path = Path(path).expanduser()
        self.base_path.mkdir(parents=True, exist_ok=True)

    def close(self) -> None:
        pass


class LocalFSModels(base.Models):
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.path = client.base_path
        self.prefix = prefix

    def _file(self, model_id: str) -> Path:
        return self.path / f"{self.prefix}{model_id}"

    def insert(self, model: base.Model) -> None:
        self._file(model.id).write_bytes(model.models)

    def get(self, model_id: str) -> Optional[base.Model]:
        f = self._file(model_id)
        if not f.exists():
            return None
        return base.Model(model_id, f.read_bytes())

    def delete(self, model_id: str) -> None:
        f = self._file(model_id)
        if f.exists():
            f.unlink()


DATA_OBJECTS = {"Models": LocalFSModels}
