"""Wire codec for the remote storage protocol — msgpack + typed tags.

The reference's deployment story runs every server against shared network
services (PostgreSQL/HBase/Elasticsearch — data/.../storage/jdbc/
StorageClient.scala:35-60); the drivers speak those services' own wire
protocols. This framework's network backend speaks its own compact
protocol instead: msgpack framing with explicit tags for the storage
record types. The decoder constructs ONLY the fixed record types in
``_RECORDS`` plus a handful of structural tags — there is no class-name
resolution and no code execution on decode.

Numpy arrays (and the columnar :class:`Interactions` / :class:`IdTable`
forms) travel as raw dtype+shape+bytes, so a training-scale scan crosses
the network as a few contiguous buffers, not millions of objects.
"""

from __future__ import annotations

import dataclasses
from datetime import datetime
from typing import Any, Dict

from incubator_predictionio_tpu.data.datamap import DataMap, PropertyMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import base
from incubator_predictionio_tpu.data.storage.base import UNSET

_TAG = "~t~"

#: record dataclasses allowed on the wire (name → class). Decoding builds
#: these through their constructors; nothing else is ever instantiated.
_RECORDS: Dict[str, type] = {
    "App": base.App,
    "AccessKey": base.AccessKey,
    "Channel": base.Channel,
    "EngineInstance": base.EngineInstance,
    "EvaluationInstance": base.EvaluationInstance,
    "EngineManifest": base.EngineManifest,
    "Model": base.Model,
}
_RECORD_NAMES = {cls: name for name, cls in _RECORDS.items()}


class WireError(ValueError):
    """Malformed wire payload."""


def encode(obj: Any) -> Any:
    import numpy as np

    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if obj is UNSET:
        return {_TAG: "unset"}
    if isinstance(obj, datetime):
        return {_TAG: "dt", "v": obj.isoformat()}
    if isinstance(obj, Event):
        return {_TAG: "event", "v": obj.to_jsonable()}
    if isinstance(obj, PropertyMap):
        return {_TAG: "pmap", "v": obj.to_jsonable(),
                "a": obj.first_updated.isoformat(),
                "z": obj.last_updated.isoformat()}
    if isinstance(obj, DataMap):
        return {_TAG: "dmap", "v": obj.to_jsonable()}
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        return {_TAG: "nd", "d": a.dtype.str, "s": list(a.shape),
                "b": a.tobytes()}
    if isinstance(obj, base.IdTable):
        return {_TAG: "idt", "b": obj.blob, "o": encode(obj.offsets)}
    if isinstance(obj, base.Interactions):
        return {_TAG: "inter", "u": encode(obj.user_idx),
                "i": encode(obj.item_idx), "v": encode(obj.values),
                "uids": encode(obj.user_ids), "iids": encode(obj.item_ids)}
    cls_name = _RECORD_NAMES.get(type(obj))
    if cls_name is not None:
        fields = {
            f.name: encode(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
        return {_TAG: "rec", "c": cls_name, "f": fields}
    if isinstance(obj, (list, tuple)):
        return {_TAG: "tu", "v": [encode(x) for x in obj]} \
            if isinstance(obj, tuple) else [encode(x) for x in obj]
    if isinstance(obj, dict):
        if all(isinstance(k, str) for k in obj) and _TAG not in obj:
            return {k: encode(v) for k, v in obj.items()}
        return {_TAG: "map",
                "v": [[encode(k), encode(v)] for k, v in obj.items()]}
    raise WireError(f"cannot encode {type(obj).__qualname__} on the wire")


def decode(obj: Any) -> Any:
    import numpy as np

    if isinstance(obj, list):
        return [decode(x) for x in obj]
    if not isinstance(obj, dict):
        return obj
    tag = obj.get(_TAG)
    if tag is None:
        return {k: decode(v) for k, v in obj.items()}
    if tag == "unset":
        return UNSET
    if tag == "dt":
        return datetime.fromisoformat(obj["v"])
    if tag == "event":
        return Event.from_jsonable(obj["v"])
    if tag == "pmap":
        return PropertyMap(
            obj["v"],
            first_updated=datetime.fromisoformat(obj["a"]),
            last_updated=datetime.fromisoformat(obj["z"]))
    if tag == "dmap":
        return DataMap(obj["v"])
    if tag == "nd":
        arr = np.frombuffer(obj["b"], dtype=np.dtype(obj["d"]))
        return arr.reshape(obj["s"]).copy()
    if tag == "idt":
        return base.IdTable(obj["b"], decode(obj["o"]))
    if tag == "inter":
        return base.Interactions(
            user_idx=decode(obj["u"]), item_idx=decode(obj["i"]),
            values=decode(obj["v"]), user_ids=decode(obj["uids"]),
            item_ids=decode(obj["iids"]))
    if tag == "rec":
        cls = _RECORDS.get(obj["c"])
        if cls is None:
            raise WireError(f"unknown record type {obj['c']!r}")
        return cls(**{k: decode(v) for k, v in obj["f"].items()})
    if tag == "tu":
        return tuple(decode(x) for x in obj["v"])
    if tag == "map":
        return {decode(k): decode(v) for k, v in obj["v"]}
    raise WireError(f"unknown wire tag {tag!r}")


def pack(obj: Any) -> bytes:
    import msgpack

    return msgpack.packb(encode(obj), use_bin_type=True)


def unpack(data: bytes) -> Any:
    import msgpack

    return decode(msgpack.unpackb(data, raw=False, strict_map_key=False))
