"""Wire codec for the remote storage protocol — msgpack + typed tags.

The reference's deployment story runs every server against shared network
services (PostgreSQL/HBase/Elasticsearch — data/.../storage/jdbc/
StorageClient.scala:35-60); the drivers speak those services' own wire
protocols. This framework's network backend speaks its own compact
protocol instead: msgpack framing over the shared structural codec
(utils/structcodec.py) with explicit tags for the storage record types.
The decoder constructs ONLY the fixed record types in ``_RECORDS`` plus
the structural tags — there is no class-name resolution and no code
execution on decode.

Numpy arrays (and the columnar :class:`Interactions` / :class:`IdTable`
forms) travel as raw dtype+shape+bytes, so a training-scale scan crosses
the network as a few contiguous buffers, not millions of objects.
"""

from __future__ import annotations

import dataclasses
from datetime import datetime
from typing import Any, Dict

from incubator_predictionio_tpu.data.datamap import DataMap, PropertyMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import base
from incubator_predictionio_tpu.data.storage.base import UNSET
from incubator_predictionio_tpu.utils.structcodec import StructCodec

_TAG = "~t~"

#: record dataclasses allowed on the wire (name → class). Decoding builds
#: these through their constructors; nothing else is ever instantiated.
_RECORDS: Dict[str, type] = {
    "App": base.App,
    "AccessKey": base.AccessKey,
    "Channel": base.Channel,
    "EngineInstance": base.EngineInstance,
    "EvaluationInstance": base.EvaluationInstance,
    "EngineManifest": base.EngineManifest,
    "Model": base.Model,
}
_RECORD_NAMES = {cls: name for name, cls in _RECORDS.items()}


class WireError(ValueError):
    """Malformed wire payload."""


def _encode_ext(obj: Any, codec: StructCodec) -> Any:
    if obj is UNSET:
        return {_TAG: "unset"}
    if isinstance(obj, Event):
        return {_TAG: "event", "v": obj.to_jsonable()}
    if isinstance(obj, PropertyMap):  # before the structural DataMap rule
        return {_TAG: "pmap", "v": obj.to_jsonable(),
                "a": obj.first_updated.isoformat(),
                "z": obj.last_updated.isoformat()}
    if isinstance(obj, base.IdTable):
        return {_TAG: "idt", "b": obj.blob, "o": codec.encode(obj.offsets)}
    if isinstance(obj, base.Interactions):
        return {_TAG: "inter", "u": codec.encode(obj.user_idx),
                "i": codec.encode(obj.item_idx),
                "v": codec.encode(obj.values),
                "uids": codec.encode(obj.user_ids),
                "iids": codec.encode(obj.item_ids)}
    cls_name = _RECORD_NAMES.get(type(obj))
    if cls_name is not None:
        fields = {
            f.name: codec.encode(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
        return {_TAG: "rec", "c": cls_name, "f": fields}
    return NotImplemented


def _decode_ext(tag: str, obj: dict, codec: StructCodec) -> Any:
    if tag == "unset":
        return UNSET
    if tag == "event":
        return Event.from_jsonable(obj["v"])
    if tag == "pmap":
        return PropertyMap(
            obj["v"],
            first_updated=datetime.fromisoformat(obj["a"]),
            last_updated=datetime.fromisoformat(obj["z"]))
    if tag == "idt":
        return base.IdTable(obj["b"], codec.decode(obj["o"]))
    if tag == "inter":
        return base.Interactions(
            user_idx=codec.decode(obj["u"]), item_idx=codec.decode(obj["i"]),
            values=codec.decode(obj["v"]), user_ids=codec.decode(obj["uids"]),
            item_ids=codec.decode(obj["iids"]))
    if tag == "rec":
        cls = _RECORDS.get(obj["c"])
        if cls is None:
            raise WireError(f"unknown record type {obj['c']!r}")
        return cls(**{k: codec.decode(v) for k, v in obj["f"].items()})
    return NotImplemented


_CODEC = StructCodec(_TAG, WireError, _encode_ext, _decode_ext)


def encode(obj: Any) -> Any:
    return _CODEC.encode(obj)


def decode(obj: Any) -> Any:
    return _CODEC.decode(obj)


def pack(obj: Any) -> bytes:
    import msgpack

    return msgpack.packb(encode(obj), use_bin_type=True)


def unpack(data: bytes) -> Any:
    import msgpack

    return decode(msgpack.unpackb(data, raw=False, strict_map_key=False))
