"""GCS object-store model blob backend (``TYPE=gcs``).

Parity: the reference stores model blobs on a distributed filesystem
(data/src/main/scala/org/apache/predictionio/data/storage/hdfs/
HDFSModels.scala); on a TPU pod deployment the shared store is a GCS
bucket, not a POSIX directory. This driver speaks the **GCS JSON API
directly over HTTPS** — no SDK dependency (none is baked into the image),
and nothing the runtime needs beyond stdlib ``http.client``:

- **auth**: OAuth2 bearer token resolved in order from the ``TOKEN``
  source property, the ``GOOGLE_OAUTH_ACCESS_TOKEN`` env var, or the
  GCE/TPU-VM **metadata server** (the standard ambient identity on TPU
  pods — ``metadata.google.internal``), cached until shortly before
  expiry. No key-file crypto: on the hardware this targets, the metadata
  server is always there.
- **emulator**: the ``EMULATOR_HOST`` source property or the standard
  ``STORAGE_EMULATOR_HOST`` env var points the client at a plain-HTTP
  endpoint with auth disabled. The test suite runs the Models conformance
  suite against :class:`EmulatorServer` (below) so the real wire path —
  media upload, ``alt=media`` download, delete, 404 mapping, retry —
  is exercised end to end in-process.

Storage env shape (registry: ``data/storage/__init__.py``)::

    PIO_STORAGE_SOURCES_GCS_TYPE=gcs
    PIO_STORAGE_SOURCES_GCS_BUCKET=my-models-bucket
    PIO_STORAGE_SOURCES_GCS_BASE_PATH=pio/models        # optional prefix
    PIO_STORAGE_REPOSITORIES_MODELDATA_NAME=pio_model
    PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE=GCS

Only the ``Models`` interface is provided, exactly like the reference's
HDFS driver (metadata/events belong on sqlite/remote/cpplog).
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from typing import Dict, Optional
from urllib.parse import quote

from incubator_predictionio_tpu.data.storage import base


def _storage_error() -> type:
    from incubator_predictionio_tpu.data.storage import StorageError

    return StorageError


#: the GCE/TPU-VM metadata endpoint serving ambient service-account tokens
_METADATA_HOST = os.environ.get(
    "GCE_METADATA_HOST", "metadata.google.internal")
_TOKEN_PATH = ("/computeMetadata/v1/instance/service-accounts/"
               "default/token")


class StorageClient(base.BaseStorageClient):
    """Keep-alive JSON-API channel to one bucket.

    Connections are thread-local (the prediction/event servers call DAOs
    from worker threads); a connection-level failure closes and retries
    once — every operation here is idempotent (full-object PUT semantics,
    GET, DELETE), so the blind retry is safe."""

    def __init__(self, config: base.StorageClientConfig):
        super().__init__(config)
        props = config.properties
        self.bucket = props.get("BUCKET")
        if not self.bucket:
            raise _storage_error()(
                "gcs storage source needs PIO_STORAGE_SOURCES_<NAME>_BUCKET")
        self.base_path = props.get("BASE_PATH", "").strip("/")
        self.timeout = float(props.get("TIMEOUT", "60"))
        emulator = (props.get("EMULATOR_HOST")
                    or os.environ.get("STORAGE_EMULATOR_HOST"))
        if emulator:
            # accept the forms the ecosystem actually sets: bare
            # host:port, http(s)://host:port, optional trailing slash
            # (fake-gcs-server defaults to https://…:4443)
            from urllib.parse import urlsplit

            raw = emulator
            if "//" not in emulator:
                emulator = "http://" + emulator
            parts = urlsplit(emulator)
            try:
                port = parts.port  # lazily parsed; bad ports raise here
                host = parts.hostname
            except ValueError:
                port = host = None
            if not host or parts.scheme not in ("http", "https"):
                raise _storage_error()(
                    "unparseable GCS emulator address "
                    f"{raw!r} (from EMULATOR_HOST / STORAGE_EMULATOR_HOST)"
                    " — expected [http[s]://]host:port")
            self.tls = parts.scheme == "https"
            self.host = host
            self.port = port or (443 if self.tls else 80)
            self._fixed_token: Optional[str] = None
            self._auth = False
        else:
            self.host, self.port, self.tls = "storage.googleapis.com", 443, True
            self._fixed_token = (props.get("TOKEN")
                                 or os.environ.get(
                                     "GOOGLE_OAUTH_ACCESS_TOKEN"))
            self._auth = True
        from incubator_predictionio_tpu.utils.http import (
            ClientConnectionPool,
        )

        self._pool = ClientConnectionPool(self.host, self.port,
                                          self.timeout, tls=self.tls)
        self._token: Optional[str] = None
        self._token_exp = 0.0
        self._token_lock = threading.Lock()

    # -- connection management ---------------------------------------------
    def _conn(self) -> http.client.HTTPConnection:
        return self._pool.get()

    def _drop_conn(self) -> None:
        self._pool.drop()

    def close(self) -> None:
        self._pool.close_all()

    # -- auth ---------------------------------------------------------------
    def _bearer(self) -> Optional[str]:
        if not self._auth:
            return None
        if self._fixed_token:
            return self._fixed_token
        with self._token_lock:
            if self._token and time.time() < self._token_exp:
                return self._token
            conn = http.client.HTTPConnection(_METADATA_HOST, timeout=10)
            try:
                conn.request("GET", _TOKEN_PATH,
                             headers={"Metadata-Flavor": "Google"})
                resp = conn.getresponse()
                payload = resp.read()
                if resp.status != 200:
                    raise _storage_error()(
                        f"metadata token fetch failed: HTTP {resp.status}")
                doc = json.loads(payload)
                self._token = doc["access_token"]
                # refresh a minute early so in-flight requests never carry
                # a token that expires mid-transfer
                self._token_exp = time.time() + float(
                    doc.get("expires_in", 300)) - 60.0
                return self._token
            except OSError as e:
                raise _storage_error()(
                    "no GCS credentials: set PIO_STORAGE_SOURCES_<N>_TOKEN "
                    "or GOOGLE_OAUTH_ACCESS_TOKEN, or run where the GCE "
                    f"metadata server is reachable ({e})") from e
            finally:
                conn.close()

    #: transient statuses Google's client guidance mandates retrying with
    #: exponential backoff — every operation this driver issues is
    #: idempotent (full-object upload, GET, DELETE), so blind re-send is
    #: safe
    _RETRY_STATUSES = (429, 500, 502, 503, 504)
    _MAX_ATTEMPTS = int(os.environ.get("PIO_GCS_RETRIES", "4"))

    def request(self, method: str, path: str,
                body: Optional[bytes] = None,
                content_type: str = "application/octet-stream"):
        headers: Dict[str, str] = {}
        if body is not None:
            headers["Content-Type"] = content_type
        token = self._bearer()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        last = "no attempt made"
        for attempt in range(self._MAX_ATTEMPTS):
            if attempt:
                # 0.5, 1, 2, … seconds; the emulator never hits this
                time.sleep(0.5 * (1 << (attempt - 1)))
            conn = self._conn()
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
            except (http.client.HTTPException, ConnectionError,
                    OSError) as e:
                last = repr(e)
                self._drop_conn()
                continue
            if resp.status in self._RETRY_STATUSES:
                last = f"HTTP {resp.status} {payload[:200]!r}"
                continue
            return resp.status, payload
        raise _storage_error()(
            f"gcs request {method} {path} failed after "
            f"{self._MAX_ATTEMPTS} attempts: {last}")

    # -- object operations ---------------------------------------------------
    def _object_name(self, name: str) -> str:
        return f"{self.base_path}/{name}" if self.base_path else name

    def put_object(self, name: str, data: bytes) -> None:
        obj = quote(self._object_name(name), safe="")
        status, payload = self.request(
            "POST",
            f"/upload/storage/v1/b/{self.bucket}/o"
            f"?uploadType=media&name={obj}",
            body=data)
        if status not in (200, 201):
            raise _storage_error()(
                f"gcs upload of {name!r} failed: HTTP {status} "
                f"{payload[:200]!r}")

    def get_object(self, name: str) -> Optional[bytes]:
        obj = quote(self._object_name(name), safe="")
        status, payload = self.request(
            "GET", f"/storage/v1/b/{self.bucket}/o/{obj}?alt=media")
        if status == 404:
            # GCS reports a missing/inaccessible BUCKET as 404 too — a
            # typo'd bucket would otherwise read as "every model absent"
            # and deploys would silently fall back instead of surfacing
            # the config error. Probe the bucket once per process.
            self._check_bucket_once()
            return None
        if status != 200:
            raise _storage_error()(
                f"gcs download of {name!r} failed: HTTP {status} "
                f"{payload[:200]!r}")
        return payload

    _bucket_ok: Optional[bool] = None

    def _check_bucket_once(self) -> None:
        if self._bucket_ok:
            return
        status, payload = self.request(
            "GET", f"/storage/v1/b/{self.bucket}")
        if status == 404 and self._auth:
            # the bucket itself does not exist — a typo'd BUCKET, the one
            # misconfig that reads as "every model absent". Gate on
            # _auth (real GCS), not TLS: an https emulator
            # (fake-gcs-server's default) may lack bucket metadata or
            # auto-create buckets lazily, so its 404s are inconclusive.
            raise _storage_error()(
                f"gcs bucket {self.bucket!r} does not exist (HTTP 404 on "
                f"bucket metadata; {payload[:200]!r}) — check "
                "PIO_STORAGE_SOURCES_<N>_BUCKET; object reads were "
                "returning 404 for every id")
        # 200 = bucket readable. 403 is NOT a misconfig signal: a
        # least-privilege service account (roles/storage.objectAdmin —
        # objects only, no storage.buckets.get) legitimately cannot read
        # bucket metadata, and failing here would make Models.get() → None
        # unreachable on correctly-scoped credentials. Anything
        # inconclusive: accept and never re-probe.
        self._bucket_ok = True

    def delete_object(self, name: str) -> bool:
        obj = quote(self._object_name(name), safe="")
        status, payload = self.request(
            "DELETE", f"/storage/v1/b/{self.bucket}/o/{obj}")
        if status in (204, 200):
            return True
        if status == 404:
            return False
        raise _storage_error()(
            f"gcs delete of {name!r} failed: HTTP {status} "
            f"{payload[:200]!r}")


class GCSModels(base.Models):
    """Models DAO on a bucket (HDFSModels.scala role: one blob per
    engine-instance id)."""

    def __init__(self, client: StorageClient,
                 config: base.StorageClientConfig, prefix: str = ""):
        self.client = client
        self.prefix = prefix

    def _name(self, model_id: str) -> str:
        return f"{self.prefix}{model_id}"

    def insert(self, model: base.Model) -> None:
        self.client.put_object(self._name(model.id), model.models)

    def get(self, model_id: str) -> Optional[base.Model]:
        data = self.client.get_object(self._name(model_id))
        if data is None:
            return None
        return base.Model(model_id, data)

    def delete(self, model_id: str) -> None:
        self.client.delete_object(self._name(model_id))


DATA_OBJECTS = {"Models": GCSModels}


# ---------------------------------------------------------------------------
# In-process emulator (tests / local development)
# ---------------------------------------------------------------------------

class EmulatorServer:
    """Minimal GCS JSON-API emulator covering the subset this driver
    speaks: media upload, ``alt=media`` download, delete, 404 mapping.
    Auth-free plain HTTP, like the official emulators — point the client
    at it via ``EMULATOR_HOST`` / ``STORAGE_EMULATOR_HOST``.

    Test/dev utility only; the conformance suite drives the real driver
    through it so the wire path is what gets tested, not a file fake."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from incubator_predictionio_tpu.utils.http import (
            HttpServer,
            Response,
            Router,
        )

        self.objects: Dict[str, Dict[str, bytes]] = {}
        self._lock = threading.Lock()
        r = Router()

        @r.post("/upload/storage/v1/b/{bucket}/o")
        def upload(request):
            name = request.query.get("name", "")
            if not name or request.query.get("uploadType") != "media":
                return Response(400, {"error": "media upload only"})
            with self._lock:
                self.objects.setdefault(
                    request.path_params["bucket"], {})[name] = request.body
            return Response(200, {"name": name,
                                  "size": str(len(request.body))})

        @r.get("/storage/v1/b/{bucket}")
        def bucket_meta(request):
            # emulators auto-create buckets on first write; report every
            # bucket readable so the driver's misconfig probe passes
            return Response(200, {"name": request.path_params["bucket"]})

        @r.get("/storage/v1/b/{bucket}/o/{obj}")
        def download(request):
            bucket = request.path_params["bucket"]
            name = request.path_params["obj"]  # router unquotes
            with self._lock:
                data = self.objects.get(bucket, {}).get(name)
            if data is None:
                return Response(404, {"error": "notFound"})
            if request.query.get("alt") == "media":
                return Response(200, body=data,
                                content_type="application/octet-stream")
            return Response(200, {"name": name, "size": str(len(data))})

        @r.delete("/storage/v1/b/{bucket}/o/{obj}")
        def delete(request):
            bucket = request.path_params["bucket"]
            name = request.path_params["obj"]
            with self._lock:
                existed = self.objects.get(bucket, {}).pop(name, None)
            if existed is None:
                return Response(404, {"error": "notFound"})
            return Response(204, body=b"")

        self.http = HttpServer(r, host, port)

    def start_background(self) -> int:
        return self.http.start_background()

    def stop(self) -> None:
        self.http.stop()
