"""Training-projection cache — a materialized columnar view of one event log.

The reference re-scans HBase region-by-region on every `pio train`
(data/.../storage/hbase/HBPEvents.scala:63-88); on a single host that
re-scan is the dominant cost of the user-visible train wall (measured:
~18 s of a ~25 s `pio train` at ML-20M scale). This module is the
TPU-first answer: the columnar arrays the training read produces —
(user_idx, item_idx, value, time) COO plus the two interned id tables —
are persisted next to the log the moment they exist (at bulk-import time,
or after a full scan), so the next training read is a sequential load
instead of a 20M-record parse.

It is strictly a *cache* with LSM-style invalidation:

- validity is keyed on the log's raw entry count and dead-entry count
  (eventlog.cc pio_evlog_entry_count / pio_evlog_dead_count): any
  tombstone since the write invalidates it (conservative — deletes are
  rare); new appends leave it valid and become the *tail*,
- a scan served from the cache re-scans only the tail (the native scan's
  ``min_entry_idx``), remaps the tail's ids into the cached tables, and
  folds the merged result back into the cache,
- any shape the fold cannot prove equivalent to a fresh full scan
  (non-monotone event times, different filter spec, fixed-value queries)
  falls back to the full native scan — correctness never depends on the
  cache.

The cache serves only "stored-value" queries (single event name, the same
``value_prop`` it was built with): const-/default-valued scans include
records *lacking* the property, which the cache cannot enumerate.

File format: one JSON header line, then raw little-endian sections
(uidx i32[n] | iidx i32[n] | vals f32[n] | times i64[n] | user blob |
user offsets i64[U+1] | item blob | item offsets i64[I+1]), written to a
temp file and atomically renamed; a size mismatch or torn header simply
reads as "no cache".
"""

from __future__ import annotations

import dataclasses
import json
import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from incubator_predictionio_tpu.data.storage.base import IdTable

_MAGIC = "pio-traincache"
_VERSION = 1

#: below this row count a full scan is cheap and the cache write is pure
#: overhead (every unit-test log would grow a sidecar file) — only logs at
#: training scale get the projection
MIN_NNZ = int(os.environ.get("PIO_TRAINCACHE_MIN_NNZ", str(1_000_000)))


@dataclasses.dataclass
class Spec:
    entity_type: str
    target_entity_type: str
    event_name: str
    value_prop: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_json(d: dict) -> "Spec":
        return Spec(**d)


@dataclasses.dataclass
class TrainCache:
    spec: Spec
    uidx: np.ndarray      # [n] int32 into user table
    iidx: np.ndarray      # [n] int32 into item table
    vals: np.ndarray      # [n] float32 (spec.value_prop values)
    times: np.ndarray     # [n] int64 ms, non-decreasing
    user_tab: IdTable
    item_tab: IdTable
    raw_count: int        # log entries covered (tail starts here)
    dead_count: int       # log dead entries at write time

    def __len__(self) -> int:
        return len(self.uidx)


def path_for(log_path: str | Path) -> Path:
    return Path(str(log_path) + ".traincache")


def load(path: Path) -> Optional[TrainCache]:
    """Parse + validate the cache file; None on any mismatch (never raises
    for a corrupt/torn file — that just means 'no cache')."""
    try:
        with open(path, "rb") as f:
            header_line = f.readline(1 << 16)
            hdr = json.loads(header_line)
            if hdr.get("magic") != _MAGIC or hdr.get("version") != _VERSION:
                return None
            n = int(hdr["n"])
            nu, ni = int(hdr["n_users"]), int(hdr["n_items"])
            ub, ib = int(hdr["ubytes"]), int(hdr["ibytes"])
            expect = (len(header_line) + n * (4 + 4 + 4 + 8)
                      + ub + (nu + 1) * 8 + ib + (ni + 1) * 8)
            if os.fstat(f.fileno()).st_size != expect:
                return None
            uidx = np.fromfile(f, np.int32, n)
            iidx = np.fromfile(f, np.int32, n)
            vals = np.fromfile(f, np.float32, n)
            times = np.fromfile(f, np.int64, n)
            ublob = f.read(ub)
            uoffs = np.fromfile(f, np.int64, nu + 1)
            iblob = f.read(ib)
            ioffs = np.fromfile(f, np.int64, ni + 1)
        return TrainCache(
            spec=Spec.from_json(hdr["spec"]),
            uidx=uidx, iidx=iidx, vals=vals, times=times,
            user_tab=IdTable(ublob, uoffs),
            item_tab=IdTable(iblob, ioffs),
            raw_count=int(hdr["raw_count"]),
            dead_count=int(hdr["dead_count"]),
        )
    except (OSError, ValueError, KeyError, TypeError):
        return None


class StagedWrite:
    """A cache file serialized to its temp name but not yet published.

    Splits :func:`write` so the O(cache) disk serialization can run
    WITHOUT the storage lock (at training scale the file is hundreds of
    MB — streaming it under the lock would stall every concurrent event
    write, the exact class the sharded-scan lock-narrowing removed),
    while the atomic rename — the only part that needs to serialize with
    other cache writers — runs under the lock after the caller has
    revalidated its snapshot. Exactly one of commit()/abort() must be
    called; abort() after commit() is a no-op."""

    __slots__ = ("_tmp", "_path")

    def __init__(self, tmp: Path, path: Path):
        self._tmp = tmp
        self._path = path

    def commit(self) -> None:
        os.replace(self._tmp, self._path)

    def abort(self) -> None:
        self._tmp.unlink(missing_ok=True)


#: staging temp names must be unique per CALL, not just per process:
#: serialization runs outside the storage lock, so two concurrent scans
#: seeding the same cache would otherwise truncate/interleave one
#: shared temp file (itertools.count() is atomic under the GIL)
_stage_seq = __import__("itertools").count()


def stage(path: Path, cache: TrainCache) -> StagedWrite:
    """Serialize ``cache`` to a call-unique temp file next to ``path``
    → :class:`StagedWrite` (publish with commit(), discard with
    abort())."""
    hdr = json.dumps({
        "magic": _MAGIC, "version": _VERSION,
        "spec": cache.spec.to_json(),
        "n": len(cache.uidx),
        "n_users": len(cache.user_tab), "n_items": len(cache.item_tab),
        "ubytes": len(cache.user_tab.blob),
        "ibytes": len(cache.item_tab.blob),
        "raw_count": cache.raw_count, "dead_count": cache.dead_count,
    }).encode() + b"\n"
    tmp = path.with_suffix(
        path.suffix + f".tmp{os.getpid()}.{next(_stage_seq)}")
    try:
        with open(tmp, "wb") as f:
            f.write(hdr)
            np.ascontiguousarray(cache.uidx, np.int32).tofile(f)
            np.ascontiguousarray(cache.iidx, np.int32).tofile(f)
            np.ascontiguousarray(cache.vals, np.float32).tofile(f)
            np.ascontiguousarray(cache.times, np.int64).tofile(f)
            f.write(cache.user_tab.blob)
            np.ascontiguousarray(cache.user_tab.offsets, np.int64).tofile(f)
            f.write(cache.item_tab.blob)
            np.ascontiguousarray(cache.item_tab.offsets, np.int64).tofile(f)
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise
    return StagedWrite(tmp, path)


def write(path: Path, cache: TrainCache) -> None:
    stage(path, cache).commit()


def invalidate(log_path: str | Path) -> None:
    path_for(log_path).unlink(missing_ok=True)
    plan_path_for(log_path).unlink(missing_ok=True)


# ---------------------------------------------------------------------------
# prep-plan sidecar: the per-side degree histograms, persisted alongside
# the projection and keyed on the SAME (raw_count, dead_count, spec)
# snapshot. A matching plan lets the next training prep skip the native
# degree/plan pass entirely (ops/sparse.build_padded_rows ``degrees``) and
# is maintained O(delta) at fold time (add the tail's bincount); any key
# mismatch just means "no plan" — prep recomputes, correctness never
# depends on it.
# ---------------------------------------------------------------------------

_PLAN_MAGIC = "pio-prepplan"
_PLAN_VERSION = 1


def plan_path_for(log_path: str | Path) -> Path:
    return Path(str(log_path) + ".prepplan")


def save_plan(path: Path, spec: Spec, raw_count: int, dead_count: int,
              user_degrees: np.ndarray, item_degrees: np.ndarray) -> None:
    """Atomically publish the degree histograms for one cache snapshot."""
    hdr = json.dumps({
        "magic": _PLAN_MAGIC, "version": _PLAN_VERSION,
        "spec": spec.to_json(),
        "raw_count": int(raw_count), "dead_count": int(dead_count),
        "n_users": int(len(user_degrees)),
        "n_items": int(len(item_degrees)),
    }).encode() + b"\n"
    tmp = path.with_suffix(
        path.suffix + f".tmp{os.getpid()}.{next(_stage_seq)}")
    try:
        with open(tmp, "wb") as f:
            f.write(hdr)
            np.ascontiguousarray(user_degrees, np.int64).tofile(f)
            np.ascontiguousarray(item_degrees, np.int64).tofile(f)
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise
    os.replace(tmp, path)


def load_plan(path: Path, spec: Spec, raw_count: int,
              dead_count: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """→ (user_degrees, item_degrees) when the plan matches the exact
    (spec, raw_count, dead_count) snapshot; None on any mismatch or a
    torn/corrupt file (which just reads as 'no plan')."""
    try:
        with open(path, "rb") as f:
            hdr = json.loads(f.readline(1 << 16))
            if (hdr.get("magic") != _PLAN_MAGIC
                    or hdr.get("version") != _PLAN_VERSION
                    or Spec.from_json(hdr["spec"]) != spec
                    or int(hdr["raw_count"]) != raw_count
                    or int(hdr["dead_count"]) != dead_count):
                return None
            nu, ni = int(hdr["n_users"]), int(hdr["n_items"])
            ud = np.fromfile(f, np.int64, nu)
            id_ = np.fromfile(f, np.int64, ni)
            if len(ud) != nu or len(id_) != ni:
                return None
        return ud, id_
    except (OSError, ValueError, KeyError, TypeError):
        return None


# ---------------------------------------------------------------------------
# id-table algebra (host-side numpy, off the device path)
# ---------------------------------------------------------------------------

def table_bytes(tab: IdTable) -> list[bytes]:
    blob, offs = tab.blob, tab.offsets
    return [bytes(blob[offs[i]:offs[i + 1]]) for i in range(len(tab))]


def _build_table(ids: list[bytes]) -> IdTable:
    offs = np.zeros(len(ids) + 1, np.int64)
    if ids:
        np.cumsum([len(b) for b in ids], out=offs[1:])
    return IdTable(b"".join(ids), offs)


def merge_tables(base: IdTable, new: IdTable) -> Tuple[IdTable, np.ndarray]:
    """Append ``new``'s unseen ids to ``base`` → (merged, remap) where
    ``remap[j]`` is the merged index of ``new``'s id j."""
    base_ids = table_bytes(base)
    index = {b: i for i, b in enumerate(base_ids)}
    remap = np.empty(len(new), np.int32)
    added: list[bytes] = []
    for j, b in enumerate(table_bytes(new)):
        k = index.get(b)
        if k is None:
            k = len(base_ids) + len(added)
            index[b] = k
            added.append(b)
        remap[j] = k
    if not added:
        return base, remap
    offs = np.empty(len(base) + len(added) + 1, np.int64)
    offs[:len(base) + 1] = base.offsets
    np.cumsum([len(b) for b in added], out=offs[len(base) + 1:])
    offs[len(base) + 1:] += base.offsets[-1]
    return IdTable(bytes(base.blob) + b"".join(added), offs), remap


class TableMerger:
    """Incrementally merge per-shard id tables into one global table.

    The sharded scan (cpplog.py) interns ids per shard; merging the shard
    tables in shard order — appending each shard's unseen ids in its own
    first-seen order — reproduces exactly the table a sequential scan of
    the concatenated row sequence would intern. Unlike repeated
    :func:`merge_tables` calls, the lookup dict persists across shards,
    so an S-shard merge is O(total ids), not O(S × total ids)."""

    __slots__ = ("_index", "_ids")

    def __init__(self) -> None:
        self._index: dict = {}
        self._ids: list = []

    def add(self, tab: IdTable) -> np.ndarray:
        """Merge one shard table; returns ``remap`` with ``remap[j]`` the
        global index of the shard's id j."""
        remap = np.empty(len(tab), np.int32)
        index, ids = self._index, self._ids
        for j, b in enumerate(table_bytes(tab)):
            k = index.get(b)
            if k is None:
                k = len(ids)
                index[b] = k
                ids.append(b)
            remap[j] = k
        return remap

    def __len__(self) -> int:
        return len(self._ids)

    def table(self) -> IdTable:
        return _build_table(self._ids)


def first_seen_reindex(
    idx: np.ndarray, tab: IdTable
) -> Tuple[np.ndarray, IdTable]:
    """Re-intern ``idx`` in first-occurrence order, dropping unreferenced
    table entries — reproduces exactly the id table a fresh native scan
    of the same row sequence would build."""
    if len(idx) == 0:
        return idx.astype(np.int32), _build_table([])
    uniq, first = np.unique(idx, return_index=True)
    order = np.argsort(first, kind="stable")
    ids_in_order = uniq[order]
    remap = np.full(len(tab), -1, np.int32)
    remap[ids_in_order] = np.arange(len(ids_in_order), dtype=np.int32)
    all_ids = table_bytes(tab)
    return remap[idx], _build_table([all_ids[i] for i in ids_in_order])
