"""Storage SPI: metadata records and DAO contracts.

Parity with the reference's storage traits:

- ``Events``            ⇄ ``LEvents`` (data/.../storage/LEvents.scala:40-492).
  The reference also has ``PEvents`` returning Spark RDDs
  (PEvents.scala:38-189); on TPU there is no executor fan-out to feed, so the
  parallel path is the same DAO streamed into device-sharded arrays by
  ``parallel.ingest`` — the L/P split collapses by design.
- ``Apps`` / ``AccessKeys`` / ``Channels`` / ``EngineInstances`` /
  ``EvaluationInstances`` / ``Models`` ⇄ the metadata DAO traits of the same
  names (data/.../storage/{Apps,AccessKeys,Channels,EngineInstances,
  EvaluationInstances,Models}.scala).

All DAOs are synchronous; the servers wrap them in thread executors (the
reference's ``future*`` methods serve the same purpose over JVM futures).
"""

from __future__ import annotations

import abc
import dataclasses
import logging
import re
import secrets
import threading
from datetime import datetime
from typing import Any, Dict, Iterator, Optional, Sequence

from incubator_predictionio_tpu.data.datamap import DataMap, PropertyMap
from incubator_predictionio_tpu.data.event import Event

logger = logging.getLogger(__name__)

#: Sentinel distinguishing "no filter" from "filter for absent" on target
#: entity queries (the reference encodes this as Option[Option[String]],
#: LEvents.scala:167-182).
UNSET: Any = type("_Unset", (), {"__repr__": lambda s: "UNSET"})()


class StorageError(Exception):
    """Storage.scala:55 StorageException. Lives here (not the package
    ``__init__``) so backend modules that import ``base`` can raise it —
    the package re-exports it for external callers."""


# ---------------------------------------------------------------------------
# Metadata records
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class App:
    """Apps.scala:32 — an app has a unique integer ID and unique name."""
    id: int
    name: str
    description: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class AccessKey:
    """AccessKeys.scala:35 — ``events`` is the allowlist; empty = all."""
    key: str
    appid: int
    events: tuple[str, ...] = ()


CHANNEL_NAME_RE = re.compile(r"^[a-zA-Z0-9-]{1,16}$")
CHANNEL_NAME_CONSTRAINT = (
    "Only alphanumeric and - characters are allowed and max length is 16."
)


def is_valid_channel_name(name: str) -> bool:
    """Channels.scala:54-57."""
    return bool(CHANNEL_NAME_RE.match(name))


@dataclasses.dataclass(frozen=True)
class Channel:
    """Channels.scala:32 — name unique within an app."""
    id: int
    name: str
    appid: int

    def __post_init__(self) -> None:
        if not is_valid_channel_name(self.name):
            raise ValueError(
                f"Invalid channel name: {self.name}. {CHANNEL_NAME_CONSTRAINT}"
            )


@dataclasses.dataclass(frozen=True)
class EngineInstance:
    """EngineInstances.scala:46 — one training run of an engine.

    ``env``/``runtime_conf`` replace the reference's ``env``/``sparkConf``
    (there is no Spark; runtime_conf carries mesh/XLA settings instead).
    """
    id: str
    status: str
    start_time: datetime
    end_time: datetime
    engine_id: str
    engine_version: str
    engine_variant: str
    engine_factory: str
    batch: str = ""
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    runtime_conf: Dict[str, str] = dataclasses.field(default_factory=dict)
    data_source_params: str = ""
    preparator_params: str = ""
    algorithms_params: str = ""
    serving_params: str = ""


@dataclasses.dataclass(frozen=True)
class EvaluationInstance:
    """EvaluationInstances.scala:42 — one evaluation (tuning) run."""
    id: str
    status: str
    start_time: datetime
    end_time: datetime
    evaluation_class: str = ""
    engine_params_generator_class: str = ""
    batch: str = ""
    env: Dict[str, str] = dataclasses.field(default_factory=dict)
    runtime_conf: Dict[str, str] = dataclasses.field(default_factory=dict)
    evaluator_results: str = ""
    evaluator_results_html: str = ""
    evaluator_results_json: str = ""


@dataclasses.dataclass(frozen=True)
class EngineManifest:
    """EngineManifests.scala:36-42 — discover engines by ID and version.

    The reference's ``files`` lists built JAR paths; here they are the
    engine's variant/module files (there is no build artifact to register,
    the factory path is importable directly).
    """
    id: str
    version: str
    name: str
    engine_factory: str
    description: Optional[str] = None
    files: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Model:
    """Models.scala:33 — a serialized model blob keyed by engine instance."""
    id: str
    models: bytes


# ---------------------------------------------------------------------------
# Event DAO
# ---------------------------------------------------------------------------

class IdTable:
    """Arrow-style string table: one utf-8 byte blob + int64 offsets.

    The zero-copy form of a distinct-id list: entry ``i`` is
    ``blob[offsets[i]:offsets[i+1]]`` decoded as utf-8. The native scan
    (eventlog.cc pio_scan_copy_ids) returns exactly this layout, and keeping
    it avoids materializing one Python string per entity on the training
    path — at the native log's ambitions (hundreds of millions of entities)
    per-id ``str`` objects would become the bottleneck. Strings materialize
    lazily at serving-translation time (indexing / iteration).

    Behaves as a read-only sequence of ``str`` so code written against the
    plain-``list`` form of :class:`Interactions` works unchanged.
    """

    __slots__ = ("blob", "offsets", "_lookup")

    def __init__(self, blob: bytes, offsets: "Any"):
        import numpy as np

        self.blob = blob
        self.offsets = np.asarray(offsets, np.int64)
        self._lookup: Optional[Dict[str, int]] = None

    def __len__(self) -> int:
        return max(len(self.offsets) - 1, 0)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self.blob[self.offsets[i]:self.offsets[i + 1]].decode("utf-8")

    def __iter__(self):
        offs = self.offsets
        blob = self.blob
        for i in range(len(self)):
            yield blob[offs[i]:offs[i + 1]].decode("utf-8")

    def __eq__(self, other: Any) -> bool:
        if isinstance(other, (list, tuple, IdTable)):
            return len(self) == len(other) and all(
                a == b for a, b in zip(self, other))
        return NotImplemented

    def __repr__(self) -> str:
        return f"IdTable(n={len(self)}, bytes={len(self.blob)})"

    def index(self, value: str) -> int:
        """Id → dense index (builds a hash lookup on first use)."""
        if self._lookup is None:
            self._lookup = {s: i for i, s in enumerate(self)}
        return self._lookup[value]

    def __contains__(self, value: str) -> bool:
        if self._lookup is None:
            self._lookup = {s: i for i, s in enumerate(self)}
        return value in self._lookup

    def tolist(self) -> list:
        return list(self)

    @classmethod
    def from_list(cls, ids: Sequence[str]) -> "IdTable":
        import numpy as np

        parts = [s.encode("utf-8") for s in ids]
        offs = np.zeros(len(parts) + 1, np.int64)
        if parts:
            np.cumsum([len(p) for p in parts], out=offs[1:])
        return cls(b"".join(parts), offs)


@dataclasses.dataclass
class Interactions:
    """Columnar, pre-indexed (entity, target, value) triples — the training
    ingest format.

    This is the TPU-native replacement for the reference's parallel event
    read (``PEvents.find`` → ``RDD[Event]`` via ``newAPIHadoopRDD``,
    hbase/HBPEvents.scala:63-88): instead of materializing per-event
    objects, backends stream straight into dense int32 COO arrays plus the
    distinct-id tables, ready for ``jax.device_put`` after bucketing.
    ``user_ids[user_idx[k]]`` recovers the original entity id of triple k.

    The id tables are sequences of ``str`` in first-seen (event-time) order —
    either plain lists or zero-copy :class:`IdTable` views (the native
    backend returns the latter; both support len/indexing/iteration).
    """

    user_idx: "Any"     # np.ndarray int32 [nnz] — index into user_ids
    item_idx: "Any"     # np.ndarray int32 [nnz] — index into item_ids
    values: "Any"       # np.ndarray float32 [nnz]
    user_ids: "Any"     # distinct entity ids (list | IdTable), first-seen order
    item_ids: "Any"     # distinct target entity ids (list | IdTable)

    def __len__(self) -> int:
        return int(self.user_idx.shape[0])


def uniform_interactions(events: Sequence[Event]):
    """Events → ``(Interactions, etype, tetype, name, vprop, times_ms)``
    when the whole batch can take the columnar import with observable
    equivalence to per-event inserts, else ``None``.

    THE single fast-path gate — both the CLI bulk import
    (cli/commands.py) and the cpplog REST batch route call this, so the
    equivalence conditions can never drift apart again (a missing UTC
    screen in one copy once silently dropped timezones on read-back).

    Equivalence requires: no explicit event ids (both paths would
    generate them), no tags/prId, a target on every event, one shared
    numeric property key whose values are float32-exact (the columnar
    store is f32; 4.1 would read back 4.0999999), UTC event times
    (compact records store epoch millis and re-render as UTC strings),
    identical event/entity/target types throughout, and a non-reserved
    event name. Callers owe their own screens for anything invisible on
    a parsed Event (the CLI screens raw docs for explicit creationTime).

    Accepted batches are FULLY VALID per ``validate_event`` without the
    caller re-validating each event (the REST hot path depends on this —
    per-event re-validation was a third of insert_batch's cost): the
    uniformity requirement makes every name/type/property-key rule a
    batch-level check against ``first`` (validated once, below), and the
    per-event rules that remain — non-empty entity ids, a target on
    every event — are enforced inside the loop. Batches that fail any
    screen return None and take the generic per-event path, which
    validates in full."""
    import datetime as _dt

    import numpy as np

    from incubator_predictionio_tpu.data.event import (
        BUILTIN_ENTITY_TYPES,
        BUILTIN_PROPERTIES,
        is_reserved_prefix,
    )
    from incubator_predictionio_tpu.utils.times import to_millis

    if not events:
        return None
    first = events[0]
    name, etype, tetype = first.event, first.entity_type, \
        first.target_entity_type
    if not name or name.startswith("$") or not tetype or not etype:
        return None
    # batch-level validity (identical on every event by the uniformity
    # screen): reserved-prefix rules from validate_event — including
    # the event NAME ('pio_rate' is invalid, not merely non-special)
    if (is_reserved_prefix(name)
            or (is_reserved_prefix(etype)
                and etype not in BUILTIN_ENTITY_TYPES)
            or (is_reserved_prefix(tetype)
                and tetype not in BUILTIN_ENTITY_TYPES)):
        return None
    keys = list(first.properties)
    if len(keys) != 1:
        return None
    vprop = keys[0]
    if is_reserved_prefix(vprop) and vprop not in BUILTIN_PROPERTIES:
        return None
    n = len(events)
    users: list = []
    items: list = []
    uidx = np.empty(n, np.int32)
    iidx = np.empty(n, np.int32)
    vals = np.empty(n, np.float32)
    times = np.empty(n, np.int64)
    u_intern: dict = {}
    i_intern: dict = {}
    for k, e in enumerate(events):
        if (e.event != name or e.entity_type != etype
                or e.target_entity_type != tetype
                or not e.entity_id
                or not e.target_entity_id or e.event_id or e.tags
                or e.pr_id or list(e.properties) != keys):
            return None
        v = e.properties.opt(vprop)  # .get raises on an explicit null
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        if float(np.float32(v)) != float(v):
            return None  # not f32-exact: the columnar store would alter it
        if e.event_time.utcoffset() != _dt.timedelta(0):
            return None  # non-UTC offset: re-rendered strings would differ
        u = u_intern.setdefault(e.entity_id, len(u_intern))
        if u == len(users):
            users.append(e.entity_id)
        it = i_intern.setdefault(e.target_entity_id, len(i_intern))
        if it == len(items):
            items.append(e.target_entity_id)
        uidx[k], iidx[k], vals[k] = u, it, v
        times[k] = to_millis(e.event_time)
    inter = Interactions(
        user_idx=uidx, item_idx=iidx, values=vals,
        user_ids=IdTable.from_list(users),
        item_ids=IdTable.from_list(items))
    return inter, etype, tetype, name, vprop, times


#: per-thread scratch buffers for the native body parser
_BODY_PARSE_TLS = threading.local()


def uniform_interactions_from_body(body: bytes, max_n: int):
    """RAW request bytes → the ``(Interactions, etype, tetype, name,
    vprop, times_ms)`` bundle via the NATIVE strict-subset parser
    (native/src/jsonparse.cc), or None when the body is not eligible
    (escapes, eventTime, reserved prefixes, oversized fields, >max_n
    docs…) or the native library is unavailable — callers then fall back
    to ``json.loads`` + :func:`uniform_interactions_from_docs`, which
    owns the full semantics.

    The native acceptance set is a strict subset of the doc gate's with
    identical output (pinned by a randomized differential test in
    tests/test_event_server.py), and the parse runs GIL-released — the
    ingest hot path never materializes per-doc Python objects at all.
    ``times_ms`` is always None here (any explicit eventTime falls
    back)."""
    import ctypes

    import numpy as np

    from incubator_predictionio_tpu import native

    lib = native.load()
    if lib is None or max_n <= 0:
        return None
    cap_field = 200  # jsonparse.cc kMaxField
    # thread-local scratch (the parser runs on pool threads): ~100 KB of
    # buffers per call otherwise dominates the wrapper's own cost
    tl = _BODY_PARSE_TLS
    bufs = getattr(tl, "bufs", None)
    if bufs is None or bufs[0] < max_n:
        bufs = (
            max_n,
            np.empty(max_n, np.int32), np.empty(max_n, np.int32),
            np.empty(max_n, np.float32),
            np.empty(max_n + 1, np.int64), np.empty(max_n + 1, np.int64),
            ctypes.create_string_buffer(max_n * cap_field),
            ctypes.create_string_buffer(max_n * cap_field),
            ctypes.create_string_buffer(4 * cap_field),
            (ctypes.c_int64 * 4)(),
        )
        tl.bufs = bufs
    (_cap, uidx, iidx, vals, uoffs, ioffs, ublob, iblob, scalars,
     scalar_lens) = bufs
    n_users = ctypes.c_int64()
    n_items = ctypes.c_int64()
    i32p = ctypes.POINTER(ctypes.c_int32)
    i64p = ctypes.POINTER(ctypes.c_int64)
    n = lib.pio_parse_uniform_batch(
        body, len(body), max_n,
        uidx.ctypes.data_as(i32p), iidx.ctypes.data_as(i32p),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        ublob, max_n * cap_field, uoffs.ctypes.data_as(i64p),
        ctypes.byref(n_users),
        iblob, max_n * cap_field, ioffs.ctypes.data_as(i64p),
        ctypes.byref(n_items),
        scalars, 4 * cap_field, scalar_lens,
    )
    if n < 1:
        return None
    nu, ni = n_users.value, n_items.value
    # string_at copies only the used prefix (``.raw`` would materialize
    # the whole preallocated buffer per call)
    inter = Interactions(
        user_idx=uidx[:n].copy(), item_idx=iidx[:n].copy(),
        values=vals[:n].copy(),
        user_ids=IdTable(ctypes.string_at(ublob, int(uoffs[nu])),
                         uoffs[:nu + 1].copy()),
        item_ids=IdTable(ctypes.string_at(iblob, int(ioffs[ni])),
                         ioffs[:ni + 1].copy()))
    a, b, c, d = (int(v) for v in scalar_lens)
    s = ctypes.string_at(scalars, a + b + c + d)
    etype = s[:a].decode("utf-8")
    name = s[a:a + b].decode("utf-8")
    tetype = s[a + b:a + b + c].decode("utf-8")
    vprop = s[a + b + c:a + b + c + d].decode("utf-8")
    return inter, etype, tetype, name, vprop, None


def uniform_interactions_from_docs(docs):
    """RAW JSON docs → the same ``(Interactions, etype, tetype, name,
    vprop, times_ms)`` bundle as :func:`uniform_interactions`, or None.

    The REST batch hot path: for the uniform shape, constructing 50
    ``Event`` objects (+ full validation each) costs more than the
    storage write itself — this gate reads the dicts directly and
    guarantees the SAME acceptance set as parsing each doc into an Event
    and running the Event-level gate (pinned by a differential test in
    tests/test_event_server.py). Screens beyond the Event-level gate,
    because a raw doc can carry what a parsed Event cannot show:
    unknown keys reject the batch, and an explicit ``creationTime``
    rejects it (the columnar renderer would rewrite it).

    ``times_ms`` is None when every doc omits ``eventTime`` — the caller
    assigns server-receive time, matching the Event path's parse-time
    default."""
    import datetime as _dt

    import numpy as np

    from incubator_predictionio_tpu.data.event import (
        BUILTIN_ENTITY_TYPES,
        BUILTIN_PROPERTIES,
        is_reserved_prefix,
    )
    from incubator_predictionio_tpu.utils.times import (
        parse_iso8601,
        to_millis,
    )

    if not docs:
        return None
    first = docs[0]
    if not isinstance(first, dict):
        return None
    name = first.get("event")
    etype = first.get("entityType")
    tetype = first.get("targetEntityType")
    if (not name or not isinstance(name, str) or name.startswith("$")
            or not etype or not isinstance(etype, str)
            or not tetype or not isinstance(tetype, str)):
        return None
    if (is_reserved_prefix(name)
            or (is_reserved_prefix(etype)
                and etype not in BUILTIN_ENTITY_TYPES)
            or (is_reserved_prefix(tetype)
                and tetype not in BUILTIN_ENTITY_TYPES)):
        return None
    props = first.get("properties")
    if not isinstance(props, dict) or len(props) != 1:
        return None
    vprop = next(iter(props))
    if is_reserved_prefix(vprop) and vprop not in BUILTIN_PROPERTIES:
        return None
    allowed_keys = {"event", "entityType", "entityId", "targetEntityType",
                    "targetEntityId", "properties", "eventTime"}
    n = len(docs)
    utc = _dt.timezone.utc
    # bulk screens via comprehensions — each pass is ~2× a manual loop in
    # CPython, and the whole gate runs on the GIL-bound ingest hot path.
    # The acceptance set is IDENTICAL to the per-doc loop this replaces
    # (pinned by the differential test in tests/test_event_server.py).
    if not all(isinstance(d, dict) and allowed_keys.issuperset(d)
               and d.get("event") == name and d.get("entityType") == etype
               and d.get("targetEntityType") == tetype for d in docs):
        return None
    try:
        users_l = [d["entityId"] for d in docs]
        items_l = [d["targetEntityId"] for d in docs]
        raw_vals = [d["properties"][vprop] for d in docs]
    except (KeyError, TypeError, IndexError):
        return None
    if not all(isinstance(u, str) and u for u in users_l):
        return None
    if not all(isinstance(t, str) and t for t in items_l):
        return None
    if not all(isinstance(d["properties"], dict) and len(d["properties"]) == 1
               for d in docs):
        return None
    if not all(isinstance(v, (int, float)) and not isinstance(v, bool)
               for v in raw_vals):
        return None
    vals64 = np.asarray(raw_vals, np.float64)
    vals = vals64.astype(np.float32)
    if not np.array_equal(vals.astype(np.float64), vals64):
        return None  # a value is not exactly f32-representable
    times: Optional[Any] = None
    if any(d.get("eventTime") is not None for d in docs):
        # explicit times are the rare wire shape — keep the original
        # per-slot loop (with its backfill semantics) for just this case
        times = np.empty(n, np.int64)
        first_explicit = True
        for k, d in enumerate(docs):
            ts = d.get("eventTime")
            if ts is not None:
                if not isinstance(ts, str):
                    return None
                try:
                    t = parse_iso8601(ts)
                except ValueError:
                    return None
                if t.utcoffset() != _dt.timedelta(0):
                    return None
                if first_explicit:
                    first_explicit = False
                    if k:  # backfill earlier implicit slots
                        now0 = to_millis(_dt.datetime.now(utc))
                        times[:k] = now0 + np.arange(k)
                times[k] = to_millis(t)
            elif not first_explicit:
                times[k] = to_millis(_dt.datetime.now(utc))
    u_intern: dict = {}
    i_intern: dict = {}
    uidx_l = [u_intern.setdefault(u, len(u_intern)) for u in users_l]
    iidx_l = [i_intern.setdefault(t, len(i_intern)) for t in items_l]
    inter = Interactions(
        user_idx=np.array(uidx_l, np.int32),
        item_idx=np.array(iidx_l, np.int32), values=vals,
        user_ids=IdTable.from_list(list(u_intern)),
        item_ids=IdTable.from_list(list(i_intern)))
    return inter, etype, tetype, name, vprop, times


class VectorCursor(tuple):
    """Multi-writer tail cursor: one ``(generation << TAIL_GEN_SHIFT) |
    count`` component per writer shard.

    Speed-layer subscribers (speed/overlay.py, speed/cache.py) treat the
    cursor as an opaque monotonic token, but they DO compare it against
    plain ints (``cursor < 0`` enablement checks, ``-1`` sentinels) and
    format it with ``%d`` — so this tuple subclass answers the scalar
    protocol with the TOTAL entry count (generation bits masked off):
    progress comparisons against ints keep working unchanged, while
    cursor-vs-cursor comparisons are component-wise, which is the only
    ordering that is meaningful across shards:

    - ``a < b`` (both vectors, same length): some shard of ``a`` is
      behind ``b`` — the "went backwards" reset trigger.
    - ``a <= b``: every shard of ``a`` is at or behind ``b`` — the
      "dirty-mark covered by solve cursor" check.
    - different lengths (shard-count change) compare unequal and never
      ``<=``/``>=`` — subscribers fall into their reset path.
    """

    __slots__ = ()

    _COUNT_MASK = (1 << 48) - 1

    def __int__(self) -> int:
        return sum(int(c) & self._COUNT_MASK for c in self)

    __index__ = __int__

    def total(self) -> int:
        return int(self)

    def _cmp(self, other, op, scalar_op):
        if isinstance(other, VectorCursor) or (
                isinstance(other, tuple) and not isinstance(other, str)):
            if len(self) != len(other):
                return False
            return op(self, other)
        if isinstance(other, (int, float)):
            return scalar_op(int(self), other)
        return NotImplemented

    def __lt__(self, other):
        # "some shard is behind" — deliberately NOT a total order: both
        # a < b and b < a hold for cursors that diverged across shards,
        # and either direction means the subscriber must resync
        return self._cmp(other,
                         lambda a, b: any(x < y for x, y in zip(a, b)),
                         lambda a, b: a < b)

    def __le__(self, other):
        return self._cmp(other,
                         lambda a, b: all(x <= y for x, y in zip(a, b)),
                         lambda a, b: a <= b)

    def __gt__(self, other):
        return self._cmp(other,
                         lambda a, b: any(x > y for x, y in zip(a, b)),
                         lambda a, b: a > b)

    def __ge__(self, other):
        return self._cmp(other,
                         lambda a, b: all(x >= y for x, y in zip(a, b)),
                         lambda a, b: a >= b)

    def __eq__(self, other):
        if isinstance(other, tuple):
            return tuple(self) == tuple(other)
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq

    def __hash__(self):
        return tuple.__hash__(self)

    def __repr__(self) -> str:
        return f"VectorCursor({tuple(int(c) for c in self)})"


class Events(abc.ABC):
    """Event CRUD + query DAO (LEvents.scala:40-492)."""

    #: True for in-process backends whose inserts are sub-millisecond
    #: (memory index, native append-only log). The EventServer runs its
    #: ingest hot routes inline on the event loop for these — the
    #: thread-pool round trip costs more than the insert — and keeps the
    #: executor for networked/fsync-bound backends.
    FAST_LOCAL = False

    @abc.abstractmethod
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Initialize the backing table/namespace for an app/channel."""

    @abc.abstractmethod
    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        """Drop all events of an app/channel."""

    @abc.abstractmethod
    def close(self) -> None:
        """Release client connections."""

    @abc.abstractmethod
    def insert(
        self, event: Event, app_id: int, channel_id: Optional[int] = None
    ) -> str:
        """Insert one event, returning its event ID (LEvents.futureInsert)."""

    def insert_batch(
        self, events: Sequence[Event], app_id: int,
        channel_id: Optional[int] = None,
    ) -> list:
        """Bulk insert (PEvents.write:184 / the import tool's path).
        Backends override with a single-write fast path.

        Retry-safe: a mid-batch failure rolls back the AUTO-ID events
        already inserted (best effort), so callers that retry per event
        after a failed bulk write — the EventServer's batch route — can
        never duplicate them. Explicit-id events are NOT rolled back: an
        upsert destroyed the pre-image (deleting would lose data that
        predates the batch), and a per-event retry of the same id is an
        idempotent upsert anyway. The native log is fully atomic instead
        (framed batch + truncate-on-failure)."""
        done: list = []
        try:
            for e in events:
                done.append((self.insert(e, app_id, channel_id),
                             bool(e.event_id)))
        except Exception:
            for eid, explicit in done:
                if explicit:
                    continue  # idempotent under retry; pre-image is gone
                try:
                    self.delete(eid, app_id, channel_id)
                except Exception:  # pragma: no cover - best effort
                    # a failed rollback-delete leaves the auto-id event in
                    # the store, so a caller's per-event retry CAN
                    # duplicate it — log loud enough for an operator to
                    # reconcile (the EventServer batch route documents the
                    # same window)
                    logger.warning(
                        "rollback delete of auto-id event %s failed after "
                        "a mid-batch error; a per-event retry may "
                        "duplicate it", eid, exc_info=True)
            raise
        return [eid for eid, _ in done]

    @abc.abstractmethod
    def get(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> Optional[Event]:
        """Get an event by ID (LEvents.futureGet)."""

    @abc.abstractmethod
    def delete(
        self, event_id: str, app_id: int, channel_id: Optional[int] = None
    ) -> bool:
        """Delete an event by ID (LEvents.futureDelete)."""

    @abc.abstractmethod
    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        """Query events (LEvents.futureFind:167-182).

        Results are ordered by event time ascending (descending when
        ``reversed``); ``limit=None`` or ``-1`` means no limit;
        ``target_entity_type=None`` (explicitly) matches only events *without*
        a target entity, while leaving it ``UNSET`` applies no filter.
        ``start_time`` is inclusive, ``until_time`` exclusive.

        ORDER CONTRACT (cross-backend, pinned by
        tests/test_storage_differential.py): equal event times tie-break
        by insertion order, and an explicit-id upsert MOVES the event to
        the end of its timestamp group (an upsert is a new write — the
        append-only log's natural semantics; memory and sqlite implement
        the same). ``reversed`` returns the exact reverse of the forward
        sequence, ties included. Aggregation replays in this order, so
        same-timestamp ``$set`` conflicts resolve identically on every
        backend.
        """

    def aggregate_properties(
        self,
        app_id: int,
        entity_type: str,
        channel_id: Optional[int] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """Aggregate special events into entity state
        (LEvents.futureAggregateProperties:194-230). ``required`` keeps only
        entities that have ALL the named *properties* defined
        (LEvents.scala:190,211-214)."""
        from incubator_predictionio_tpu.data.aggregator import (
            AGGREGATOR_EVENT_NAMES,
            aggregate_properties,
        )

        events = self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            event_names=AGGREGATOR_EVENT_NAMES,
        )
        result = aggregate_properties(events)
        if required is not None:
            result = {
                k: v for k, v in result.items()
                if all(prop in v for prop in required)
            }
        return result

    def scan_interactions(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_names: Sequence[str] = ("rate",),
        value_prop: Optional[str] = None,
        event_values: Optional[Dict[str, float]] = None,
        default_value: float = 1.0,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
    ) -> Interactions:
        """Columnar training-ingest scan (see :class:`Interactions`).

        Value resolution per event, in order: a fixed per-event-name value
        from ``event_values``; else the numeric property ``value_prop``
        (events *missing* it are skipped — DataSource.scala:66-72 drops
        rate events without a rating); else ``default_value``. Events
        without a target entity are skipped. Backends override this with
        scans that never materialize :class:`Event` objects; this generic
        implementation defines the semantics they must match.
        """
        import numpy as np

        fixed = event_values or {}
        users: Dict[str, int] = {}
        items: Dict[str, int] = {}
        uidx: list = []
        iidx: list = []
        vals: list = []
        for e in self.find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            target_entity_type=target_entity_type,
            event_names=list(event_names),
        ):
            if e.target_entity_id is None:
                continue
            if e.event in fixed:
                v = fixed[e.event]
            elif value_prop is not None:
                raw = e.properties.to_jsonable().get(value_prop)
                if not isinstance(raw, (int, float)) or isinstance(raw, bool):
                    continue   # missing or non-numeric → skipped
                v = float(raw)
            else:
                v = default_value
            u = users.setdefault(e.entity_id, len(users))
            i = items.setdefault(e.target_entity_id, len(items))
            uidx.append(u)
            iidx.append(i)
            vals.append(v)
        return Interactions(
            user_idx=np.asarray(uidx, np.int32),
            item_idx=np.asarray(iidx, np.int32),
            values=np.asarray(vals, np.float32),
            user_ids=list(users),
            item_ids=list(items),
        )

    # -- speed-layer tail cursor -------------------------------------------
    #
    # The Lambda-architecture speed leg (incubator_predictionio_tpu/speed/)
    # polls the write tail of the event log to keep a per-user "dirty" set
    # between retrains. ``tail_cursor`` is a MONOTONIC position in the
    # backend's write order (append-only: entry count; in-memory: insert
    # counter) and ``read_interactions_since`` scans only [cursor, now) —
    # O(delta), never O(log). Backends without a cheap tail return -1 and
    # the speed layer stays disabled on them.

    #: generation shift for tail cursors: the high bits carry a
    #: process-local LOG GENERATION (bumped on compaction/drop — any
    #: rewrite that renumbers entries), the low bits the write position.
    #: A bare count comparison cannot detect "compacted, then appended
    #: past the old count before the next poll"; the generation can.
    TAIL_GEN_SHIFT = 48

    def tail_cursor(self, app_id: int,
                    channel_id: Optional[int] = None) -> int:
        """Current monotonic write cursor (generation ``<<
        TAIL_GEN_SHIFT`` | position), or -1 when the backend has no
        cheap tail-read support. Within one generation a later cursor
        covers every event a previous one did; a generation change means
        everything derived from old cursors is invalid."""
        return -1

    def read_interactions_since(
        self,
        cursor: int,
        app_id: int,
        channel_id: Optional[int] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_names: Sequence[str] = ("rate",),
        value_prop: Optional[str] = None,
        event_values: Optional[Dict[str, float]] = None,
        default_value: float = 1.0,
    ):
        """Columnar scan of ONLY the events written since ``cursor`` →
        ``(Interactions, times_ms, append_ms, new_cursor, reset)``.
        Value-resolution semantics are identical to
        :meth:`scan_interactions`; rows arrive in write order.

        ``append_ms`` (int64 [nnz]) is the wall-clock epoch-millisecond
        stamp of when each row's event was APPENDED to the log — the
        anchor of the end-to-end freshness trace (obs/freshness.py),
        distinct from the event's logical ``eventTime`` (a backfill can
        carry last year's event times but fresh append stamps). Backends
        stamp it as precisely as they can, and always CONSERVATIVELY —
        a stamp may be early (age overstated) but never late (freshness
        is never fabricated): the in-memory backend records exact
        per-slot walls; the native log bounds each batch by its newest
        count observation at/below the cursor (exact when this process
        wrote the events; within one poll interval when another process
        did, since every tail read records what it saw). ``-1`` means
        the backend cannot bound the append wall (e.g. entries written
        before the subscriber's first look at the log) and the row is
        excluded from freshness tracing.

        ``reset=True`` (a cursor from a previous log generation —
        compaction/drop renumbered the entries) carries an EMPTY tail
        and a fresh cursor: the caller must drop everything it derived
        and resynchronize."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support tail reads")

    def import_interactions(
        self,
        inter: Interactions,
        app_id: int,
        channel_id: Optional[int] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_name: str = "rate",
        value_prop: str = "rating",
        times: Optional["Any"] = None,
        base_time: Optional[datetime] = None,
        chunk: int = 20_000,
    ) -> int:
        """Columnar bulk ingest — the inverse of :func:`scan_interactions`.

        Writes one ``event_name`` event per triple with the value stored
        under ``value_prop``; event times come from ``times`` (epoch ms,
        int64 [nnz]) or default to ``base_time + k`` milliseconds so the
        write order is the scan order. This is the bulk-import path the
        reference routes through ``PEvents.write`` (PEvents.scala:184) /
        ``pio import``; backends override it with writers that never
        materialize per-event objects (the native log renders records fully
        in C++).
        """
        from datetime import timedelta

        from incubator_predictionio_tpu.utils.times import now_utc

        n = len(inter)
        t0 = base_time if base_time is not None else now_utc()
        if times is None:
            get_time = lambda k: t0 + timedelta(milliseconds=k)  # noqa: E731
        else:
            from incubator_predictionio_tpu.utils.times import from_millis
            get_time = lambda k: from_millis(int(times[k]))  # noqa: E731
        user_ids = inter.user_ids
        item_ids = inter.item_ids
        for s in range(0, n, chunk):
            batch = [
                Event(
                    event=event_name,
                    entity_type=entity_type,
                    entity_id=user_ids[int(inter.user_idx[k])],
                    target_entity_type=target_entity_type,
                    target_entity_id=item_ids[int(inter.item_idx[k])],
                    properties=DataMap(
                        {value_prop: float(inter.values[k])}),
                    event_time=get_time(k),
                )
                for k in range(s, min(s + chunk, n))
            ]
            self.insert_batch(batch, app_id, channel_id)
        return n


# ---------------------------------------------------------------------------
# Metadata DAOs
# ---------------------------------------------------------------------------

class Apps(abc.ABC):
    """Apps.scala:44-76."""

    @abc.abstractmethod
    def insert(self, app: App) -> Optional[int]:
        """Insert; if ``app.id == 0`` an ID is generated. Returns the ID."""

    @abc.abstractmethod
    def get(self, app_id: int) -> Optional[App]: ...

    @abc.abstractmethod
    def get_by_name(self, name: str) -> Optional[App]: ...

    @abc.abstractmethod
    def get_all(self) -> list[App]: ...

    @abc.abstractmethod
    def update(self, app: App) -> bool: ...

    @abc.abstractmethod
    def delete(self, app_id: int) -> bool: ...


def generate_access_key() -> str:
    """Random URL-safe key (AccessKeys.scala:68 generates base64 of random
    bytes with ``+``/``/``/``=`` stripped; token_urlsafe is the same idea)."""
    return secrets.token_urlsafe(48).replace("-", "").replace("_", "")[:64]


class AccessKeys(abc.ABC):
    """AccessKeys.scala:47-76."""

    @abc.abstractmethod
    def insert(self, k: AccessKey) -> Optional[str]:
        """Insert; generates the key when ``k.key`` is empty. Returns key."""

    @abc.abstractmethod
    def get(self, key: str) -> Optional[AccessKey]: ...

    @abc.abstractmethod
    def get_all(self) -> list[AccessKey]: ...

    @abc.abstractmethod
    def get_by_appid(self, appid: int) -> list[AccessKey]: ...

    @abc.abstractmethod
    def update(self, k: AccessKey) -> bool: ...

    @abc.abstractmethod
    def delete(self, key: str) -> bool: ...


class Channels(abc.ABC):
    """Channels.scala:70-95."""

    @abc.abstractmethod
    def insert(self, channel: Channel) -> Optional[int]:
        """Insert; if ``channel.id == 0`` an ID is generated. Returns the ID."""

    @abc.abstractmethod
    def get(self, channel_id: int) -> Optional[Channel]: ...

    @abc.abstractmethod
    def get_by_appid(self, appid: int) -> list[Channel]: ...

    @abc.abstractmethod
    def delete(self, channel_id: int) -> bool: ...


class EngineInstances(abc.ABC):
    """EngineInstances.scala:75-115."""

    @abc.abstractmethod
    def insert(self, i: EngineInstance) -> str:
        """Insert; generates and returns an ID when ``i.id`` is empty."""

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EngineInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> list[EngineInstance]: ...

    @abc.abstractmethod
    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[EngineInstance]:
        """Latest COMPLETED instance by start time (EngineInstances.scala:82)."""

    @abc.abstractmethod
    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[EngineInstance]: ...

    @abc.abstractmethod
    def update(self, i: EngineInstance) -> bool: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> bool: ...


class EvaluationInstances(abc.ABC):
    """EvaluationInstances.scala:70-100."""

    @abc.abstractmethod
    def insert(self, i: EvaluationInstance) -> str: ...

    @abc.abstractmethod
    def get(self, instance_id: str) -> Optional[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_all(self) -> list[EvaluationInstance]: ...

    @abc.abstractmethod
    def get_completed(self) -> list[EvaluationInstance]:
        """EVALCOMPLETED instances, newest first (EvaluationInstances.scala:85)."""

    @abc.abstractmethod
    def update(self, i: EvaluationInstance) -> bool: ...

    @abc.abstractmethod
    def delete(self, instance_id: str) -> bool: ...


class EngineManifests(abc.ABC):
    """EngineManifests.scala:49-66 — engine registry DAO."""

    @abc.abstractmethod
    def insert(self, m: EngineManifest) -> None: ...

    @abc.abstractmethod
    def get(self, manifest_id: str, version: str) -> Optional[EngineManifest]: ...

    @abc.abstractmethod
    def get_all(self) -> list[EngineManifest]: ...

    @abc.abstractmethod
    def update(self, m: EngineManifest, upsert: bool = False) -> bool: ...

    @abc.abstractmethod
    def delete(self, manifest_id: str, version: str) -> bool: ...


class Models(abc.ABC):
    """Models.scala:40-60 — model blob store."""

    @abc.abstractmethod
    def insert(self, model: Model) -> None: ...

    @abc.abstractmethod
    def get(self, model_id: str) -> Optional[Model]: ...

    @abc.abstractmethod
    def delete(self, model_id: str) -> None: ...


class BaseStorageClient(abc.ABC):
    """A connection to one storage source (Storage.scala:39-53)."""

    prefix: str = ""

    def __init__(self, config: "StorageClientConfig"):
        self.config = config

    @abc.abstractmethod
    def close(self) -> None: ...


@dataclasses.dataclass(frozen=True)
class StorageClientConfig:
    """Storage.scala:62-66 — parsed ``PIO_STORAGE_SOURCES_<NAME>_*`` env."""
    parallel: bool = False
    test: bool = False
    properties: Dict[str, str] = dataclasses.field(default_factory=dict)
