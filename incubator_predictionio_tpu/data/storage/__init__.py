"""Storage registry — env-driven backend selection and DAO factory.

Parity: data/.../storage/Storage.scala:117-407. Configuration comes from the
same env-var scheme as the reference:

- ``PIO_STORAGE_SOURCES_<NAME>_TYPE``  — backend type (memory | sqlite | localfs)
- ``PIO_STORAGE_SOURCES_<NAME>_<KEY>`` — backend properties (e.g. ``PATH``)
- ``PIO_STORAGE_REPOSITORIES_<REPO>_NAME`` / ``_SOURCE`` for
  ``<REPO>`` ∈ {METADATA, EVENTDATA, MODELDATA}

(Storage.scala:127-196 parses the same shapes.) Differences by design: backend
lookup goes through an explicit registry instead of JVM reflection on class
names (Storage.scala:286-303), and unset env falls back to a working
single-box default (SQLite under ``$PIO_HOME``) instead of erroring.
"""

from __future__ import annotations

import importlib
import os
import threading
from typing import Any, Dict, Optional, Type

from incubator_predictionio_tpu.data.storage import base
from incubator_predictionio_tpu.data.storage.base import (  # re-export
    AccessKey,
    AccessKeys,
    App,
    Apps,
    BaseStorageClient,
    Channel,
    Channels,
    EngineInstance,
    EngineInstances,
    EngineManifest,
    EngineManifests,
    EvaluationInstance,
    EvaluationInstances,
    Events,
    Model,
    Models,
    StorageClientConfig,
    StorageError,
    UNSET,
    is_valid_channel_name,
)

__all__ = [
    "AccessKey", "AccessKeys", "App", "Apps", "Channel", "Channels",
    "EngineInstance", "EngineInstances", "EngineManifest", "EngineManifests",
    "EvaluationInstance",
    "EvaluationInstances", "Events", "Model", "Models", "Storage", "is_valid_channel_name",
    "StorageClientConfig", "StorageError", "UNSET", "BaseStorageClient",
]

#: backend type name -> module path providing StorageClient + DATA_OBJECTS
_BACKENDS: Dict[str, str] = {
    "memory": "incubator_predictionio_tpu.data.storage.memory",
    "sqlite": "incubator_predictionio_tpu.data.storage.sqlite",
    "localfs": "incubator_predictionio_tpu.data.storage.localfs",
    # native append-only event log (the HBase-driver role; events only)
    "cpplog": "incubator_predictionio_tpu.data.storage.cpplog",
    # network client for a shared StorageServer (the multi-box topology —
    # the role PostgreSQL/HBase play for the reference)
    "remote": "incubator_predictionio_tpu.data.storage.remote",
    # GCS bucket model-blob store (the HDFSModels role on TPU pods)
    "gcs": "incubator_predictionio_tpu.data.storage.gcs",
}

MetaDataRepository = "METADATA"
EventDataRepository = "EVENTDATA"
ModelDataRepository = "MODELDATA"


class UnsupportedMethodError(StorageError):
    """An optional DAO capability this backend does not implement (e.g.
    columnar ``insert_interactions`` on a backend without a columnar
    write path). Crosses the remote-storage wire typed, so callers can
    cache the capability answer instead of retrying per request."""


class AmbiguousWriteError(StorageError):
    """A non-idempotent remote write whose response was lost AFTER the
    request hit the wire: the write may or may not have been applied.
    Raised instead of retrying (a retry could double-apply); callers must
    surface the ambiguity — falling back to a different write path would
    silently duplicate the data."""


def register_backend(type_name: str, module_path: str) -> None:
    """Register an external backend (replaces classpath reflection)."""
    _BACKENDS[type_name] = module_path


def pio_home() -> str:
    return os.environ.get("PIO_HOME", os.path.expanduser("~/.pio_tpu"))


class Storage:
    """Process-wide storage registry (the reference's ``Storage`` object)."""

    _lock = threading.RLock()
    _clients: Dict[str, Any] = {}
    _env: Optional[Dict[str, str]] = None

    # -- configuration -----------------------------------------------------
    @classmethod
    def configure(cls, env: Optional[Dict[str, str]] = None) -> None:
        """Install an explicit configuration (tests) or re-read os.environ."""
        with cls._lock:
            cls.close()
            cls._env = dict(env) if env is not None else None

    @classmethod
    def reset(cls) -> None:
        cls.configure(None)

    @classmethod
    def _environ(cls) -> Dict[str, str]:
        return cls._env if cls._env is not None else dict(os.environ)

    @classmethod
    def _source_keys(cls) -> list[str]:
        """Names of configured sources (Storage.scala:140 sourcesPrefix scan)."""
        env = cls._environ()
        keys = set()
        for k in env:
            if k.startswith("PIO_STORAGE_SOURCES_"):
                rest = k[len("PIO_STORAGE_SOURCES_"):]
                name = rest.split("_", 1)[0]
                if name:
                    keys.add(name)
        return sorted(keys)

    @classmethod
    def _source_config(cls, name: str) -> tuple[str, StorageClientConfig]:
        env = cls._environ()
        prefix = f"PIO_STORAGE_SOURCES_{name}_"
        props = {
            k[len(prefix):]: v for k, v in env.items() if k.startswith(prefix)
        }
        type_name = props.pop("TYPE", None)
        if type_name is None:
            raise StorageError(
                f"Storage source {name} has no PIO_STORAGE_SOURCES_{name}_TYPE"
            )
        config = StorageClientConfig(
            parallel=props.pop("PARALLEL", "false").lower() == "true",
            test=props.pop("TEST", "false").lower() == "true",
            properties=props,
        )
        return type_name, config

    @classmethod
    def repository(cls, repo: str) -> tuple[str, str]:
        """(namespace, source-name) for a repository, with single-box defaults."""
        env = cls._environ()
        name = env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_NAME")
        source = env.get(f"PIO_STORAGE_REPOSITORIES_{repo}_SOURCE")
        if name and source:
            return name, source
        if name or source:
            # Half-configured repository is a misconfiguration, not a
            # fall-through (Storage.scala errors on incomplete repo config).
            raise StorageError(
                f"Repository {repo} needs BOTH PIO_STORAGE_REPOSITORIES_{repo}"
                f"_NAME and _SOURCE (got NAME={name!r}, SOURCE={source!r})"
            )
        # Defaults: one SQLite source for everything (zero-config single box).
        return {
            MetaDataRepository: ("pio_meta", "DEFAULT"),
            EventDataRepository: ("pio_event", "DEFAULT"),
            ModelDataRepository: ("pio_model", "DEFAULT"),
        }[repo]

    # -- clients and DAOs --------------------------------------------------
    @classmethod
    def _get_client(cls, source_name: str) -> Any:
        with cls._lock:
            if source_name in cls._clients:
                return cls._clients[source_name]
            if source_name == "DEFAULT" and source_name not in cls._source_keys():
                type_name = "sqlite"
                config = StorageClientConfig(
                    properties={
                        "PATH": os.path.join(pio_home(), "store", "pio.db")
                    }
                )
            else:
                type_name, config = cls._source_config(source_name)
            module_path = _BACKENDS.get(type_name)
            if module_path is None:
                raise StorageError(
                    f"Unknown storage backend type {type_name!r} "
                    f"(known: {sorted(_BACKENDS)})"
                )
            module = importlib.import_module(module_path)
            client = module.StorageClient(config)
            cls._clients[source_name] = (client, module, config)
            return cls._clients[source_name]

    @classmethod
    def get_data_object(cls, repo: str, iface: str) -> Any:
        """DAO factory (Storage.scala getDataObject:276-303)."""
        namespace, source_name = cls.repository(repo)
        client, module, config = cls._get_client(source_name)
        dao_cls: Optional[Type[Any]] = module.DATA_OBJECTS.get(iface)
        if dao_cls is None:
            raise StorageError(
                f"Backend {module.__name__} does not implement {iface}"
            )
        return dao_cls(client, config, prefix=namespace + "_")

    # Typed accessors (Storage.scala:364-407)
    @classmethod
    def get_meta_data_apps(cls) -> Apps:
        return cls.get_data_object(MetaDataRepository, "Apps")

    @classmethod
    def get_meta_data_access_keys(cls) -> AccessKeys:
        return cls.get_data_object(MetaDataRepository, "AccessKeys")

    @classmethod
    def get_meta_data_channels(cls) -> Channels:
        return cls.get_data_object(MetaDataRepository, "Channels")

    @classmethod
    def get_meta_data_engine_instances(cls) -> EngineInstances:
        return cls.get_data_object(MetaDataRepository, "EngineInstances")

    @classmethod
    def get_meta_data_engine_manifests(cls) -> EngineManifests:
        return cls.get_data_object(MetaDataRepository, "EngineManifests")

    @classmethod
    def get_meta_data_evaluation_instances(cls) -> EvaluationInstances:
        return cls.get_data_object(MetaDataRepository, "EvaluationInstances")

    @classmethod
    def get_model_data_models(cls) -> Models:
        return cls.get_data_object(ModelDataRepository, "Models")

    @classmethod
    def get_events(cls) -> Events:
        """The event DAO (Storage.getLEvents/getPEvents:387-393 — the L/P
        split collapses on TPU; see base.Events docstring)."""
        return cls.get_data_object(EventDataRepository, "Events")

    @classmethod
    def verify_all_data_objects(cls) -> bool:
        """End-to-end config validation (Storage.verifyAllDataObjects:338-361)."""
        cls.get_meta_data_apps()
        cls.get_meta_data_access_keys()
        cls.get_meta_data_channels()
        cls.get_meta_data_engine_instances()
        cls.get_meta_data_engine_manifests()
        cls.get_meta_data_evaluation_instances()
        cls.get_model_data_models()
        events = cls.get_events()
        events.init(0)
        events.remove(0)
        return True

    @classmethod
    def close(cls) -> None:
        with cls._lock:
            for client, _module, _config in cls._clients.values():
                try:
                    client.close()
                except Exception:
                    pass
            cls._clients.clear()
