"""In-memory storage backend — the test backend and default for unit work.

The reference gains the same capability through JDBC-against-test-DBs plus
``StorageClientConfig.test`` (Storage.scala:62,78-81); here an explicit
in-memory backend keeps the conformance suite hermetic.

Repository namespaces (``PIO_STORAGE_REPOSITORIES_<REPO>_NAME``) isolate
tables exactly like the reference's namespaced HBase tables / JDBC table
prefixes: each DAO operates on the per-namespace table set for its prefix.
"""

from __future__ import annotations

import dataclasses
import threading
import uuid
from datetime import datetime
from typing import Any, Dict, Iterator, Optional, Sequence, Tuple

from incubator_predictionio_tpu.data.event import Event, new_event_id, validate_event
from incubator_predictionio_tpu.data.storage import base
from incubator_predictionio_tpu.utils.times import to_millis, wall_millis
from incubator_predictionio_tpu.data.storage.base import UNSET


class _Namespace:
    """One repository namespace's tables."""

    def __init__(self) -> None:
        # (app_id, channel_id) -> {event_id: Event}
        self.events: Dict[Tuple[int, Optional[int]], Dict[str, Event]] = {}
        # (app_id, channel_id) -> append-ordered write tail of
        # (Event, append_wall_ms) pairs (upserts append again — a new
        # write in the cross-backend order contract; the wall stamp is
        # the freshness-tracing anchor: event APPENDED, not event TIME).
        # Backs the speed layer's tail_cursor/read_interactions_since.
        self.event_tail: Dict[Tuple[int, Optional[int]], list] = {}
        # tail generation per table: bumped by remove() so stale cursors
        # are detected even after the table refills past the old count
        self.event_tail_gen: Dict[Tuple[int, Optional[int]], int] = {}
        self.apps: Dict[int, base.App] = {}
        self.access_keys: Dict[str, base.AccessKey] = {}
        self.channels: Dict[int, base.Channel] = {}
        self.engine_instances: Dict[str, base.EngineInstance] = {}
        self.engine_manifests: Dict[Tuple[str, str], base.EngineManifest] = {}
        self.evaluation_instances: Dict[str, base.EvaluationInstance] = {}
        self.models: Dict[str, base.Model] = {}
        self._next = 1

    def next_free_id(self, taken: Dict[int, Any]) -> int:
        while self._next in taken:
            self._next += 1
        out = self._next
        self._next += 1
        return out


class StorageClient(base.BaseStorageClient):
    """Holds all in-memory namespaces for one source."""

    def __init__(self, config: base.StorageClientConfig):
        super().__init__(config)
        self.lock = threading.RLock()
        self.namespaces: Dict[str, _Namespace] = {}

    def ns(self, prefix: str) -> _Namespace:
        with self.lock:
            return self.namespaces.setdefault(prefix, _Namespace())

    def close(self) -> None:
        pass


def _match(
    e: Event,
    start_ms: Optional[int],
    until_ms: Optional[int],
    entity_type: Optional[str],
    entity_id: Optional[str],
    event_names: Optional[Sequence[str]],
    target_entity_type: Any,
    target_entity_id: Any,
) -> bool:
    # compare at MILLISECOND granularity — the durable backends store
    # epoch millis (sqlite event_time INTEGER, cpplog time_ms), so the
    # in-memory model must not discriminate at sub-ms precision they
    # cannot represent (order contract, base.py Events.find). Callers
    # pass the bounds pre-converted (hot path: the aggregator replays
    # through find()).
    if start_ms is not None or until_ms is not None:
        t = to_millis(e.event_time)
        if start_ms is not None and t < start_ms:
            return False
        if until_ms is not None and t >= until_ms:
            return False
    if entity_type is not None and e.entity_type != entity_type:
        return False
    if entity_id is not None and e.entity_id != entity_id:
        return False
    if event_names is not None and e.event not in event_names:
        return False
    if target_entity_type is not UNSET and e.target_entity_type != target_entity_type:
        return False
    if target_entity_id is not UNSET and e.target_entity_id != target_entity_id:
        return False
    return True


class _MemoryDAO:
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.client = client
        self.t = client.ns(prefix)


class MemoryEvents(_MemoryDAO, base.Events):
    FAST_LOCAL = True  # dict index: EventServer ingests inline

    def _table(self, app_id: int, channel_id: Optional[int]) -> Dict[str, Event]:
        return self.t.events.setdefault((app_id, channel_id), {})

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self.client.lock:
            self._table(app_id, channel_id)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self.client.lock:
            self.t.events.pop((app_id, channel_id), None)
            self.t.event_tail.pop((app_id, channel_id), None)
            key = (app_id, channel_id)
            self.t.event_tail_gen[key] = \
                self.t.event_tail_gen.get(key, 0) + 1
        return True

    def close(self) -> None:
        pass

    def _tail_tombstone(self, app_id: int, channel_id: Optional[int],
                        event_id: str) -> None:
        """Null out the newest tail occurrence of an event id (caller
        holds the client lock). Positions are PRESERVED — the tail
        cursor counts slots, so a tombstone must not shift it."""
        tail = self.t.event_tail.get((app_id, channel_id))
        if not tail:
            return
        for i in range(len(tail) - 1, -1, -1):
            entry = tail[i]
            if entry is not None and entry[0].event_id == event_id:
                tail[i] = None
                return

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        validate_event(event)
        with self.client.lock:
            eid = event.event_id or new_event_id()
            table = self._table(app_id, channel_id)
            # upsert moves the event to the END of insertion order — the
            # cross-backend tie-break contract for equal event times (an
            # upsert is a new write; cpplog's append-only log and
            # sqlite's REPLACE rowid both behave this way)
            if table.pop(eid, None) is not None:
                # the superseded write must not replay to tail readers
                self._tail_tombstone(app_id, channel_id, eid)
            table[eid] = event.with_id(eid)
            self.t.event_tail.setdefault((app_id, channel_id), []).append(
                (table[eid], wall_millis()))
        return eid

    # -- speed-layer tail cursor -------------------------------------------
    def tail_cursor(self, app_id: int,
                    channel_id: Optional[int] = None) -> int:
        with self.client.lock:
            key = (app_id, channel_id)
            gen = self.t.event_tail_gen.get(key, 0)
            return (gen << self.TAIL_GEN_SHIFT) | len(
                self.t.event_tail.get(key, ()))

    def read_interactions_since(
        self,
        cursor: int,
        app_id: int,
        channel_id: Optional[int] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_names: Sequence[str] = ("rate",),
        value_prop: Optional[str] = None,
        event_values: Optional[Dict[str, float]] = None,
        default_value: float = 1.0,
    ):
        import numpy as np

        with self.client.lock:
            key = (app_id, channel_id)
            gen = self.t.event_tail_gen.get(key, 0)
            tail = self.t.event_tail.get(key, ())
            pos = len(tail)
            new_cursor = (gen << self.TAIL_GEN_SHIFT) | pos
            cur_gen = max(int(cursor), 0) >> self.TAIL_GEN_SHIFT
            cur_pos = max(int(cursor), 0) & (
                (1 << self.TAIL_GEN_SHIFT) - 1)
            if cur_gen != gen or cur_pos > pos:
                # log rewritten since the caller's cursor: empty tail +
                # reset — the caller resynchronizes from scratch
                return (base.Interactions(
                            user_idx=np.empty(0, np.int32),
                            item_idx=np.empty(0, np.int32),
                            values=np.empty(0, np.float32),
                            user_ids=[], item_ids=[]),
                        np.empty(0, np.int64), np.empty(0, np.int64),
                        new_cursor, True)
            rows = list(tail[cur_pos:pos])
        fixed = event_values or {}
        names = set(event_names)
        users: Dict[str, int] = {}
        items: Dict[str, int] = {}
        uidx: list = []
        iidx: list = []
        vals: list = []
        times: list = []
        appends: list = []
        for entry in rows:
            if entry is None:  # tombstoned (deleted/superseded) slot
                continue
            e, appended_ms = entry
            if (e.event not in names or e.entity_type != entity_type
                    or e.target_entity_type != target_entity_type
                    or e.target_entity_id is None):
                continue
            if e.event in fixed:
                v = fixed[e.event]
            elif value_prop is not None:
                raw = e.properties.to_jsonable().get(value_prop)
                if not isinstance(raw, (int, float)) or isinstance(raw, bool):
                    continue
                v = float(raw)
            else:
                v = default_value
            uidx.append(users.setdefault(e.entity_id, len(users)))
            iidx.append(items.setdefault(e.target_entity_id, len(items)))
            vals.append(v)
            times.append(to_millis(e.event_time))
            appends.append(appended_ms)
        inter = base.Interactions(
            user_idx=np.asarray(uidx, np.int32),
            item_idx=np.asarray(iidx, np.int32),
            values=np.asarray(vals, np.float32),
            user_ids=list(users),
            item_ids=list(items),
        )
        return (inter, np.asarray(times, np.int64),
                np.asarray(appends, np.int64), new_cursor, False)

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        with self.client.lock:
            return self._table(app_id, channel_id).get(event_id)

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self.client.lock:
            gone = self._table(app_id, channel_id).pop(
                event_id, None) is not None
            if gone:
                # deleted events must not replay through the speed
                # layer's tail read (cpplog's scans skip tombstones; the
                # in-memory model must match)
                self._tail_tombstone(app_id, channel_id, event_id)
            return gone

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        with self.client.lock:
            rows = list(self._table(app_id, channel_id).values())
        start_ms = None if start_time is None else to_millis(start_time)
        until_ms = None if until_time is None else to_millis(until_time)
        rows = [
            e for e in rows
            if _match(e, start_ms, until_ms, entity_type, entity_id,
                      event_names, target_entity_type, target_entity_id)
        ]
        # cross-backend order contract: (event_time AT MILLIS, insertion/
        # upsert order) — the stable sort keeps the table's insertion
        # order for equal-milli times (sub-ms differences are invisible
        # to the durable backends and must not order here either);
        # ``reversed`` is the exact reverse of the forward sequence (ties
        # included), matching the native log's backward walk and sqlite's
        # (event_time, rowid) DESC
        rows.sort(key=lambda e: to_millis(e.event_time))
        if reversed:
            rows = rows[::-1]
        if limit is not None and limit >= 0:
            rows = rows[:limit]
        return iter(rows)


class MemoryApps(_MemoryDAO, base.Apps):
    def insert(self, app: base.App) -> Optional[int]:
        with self.client.lock:
            if any(a.name == app.name for a in self.t.apps.values()):
                return None
            if app.id != 0:
                if app.id in self.t.apps:
                    return None
                app_id = app.id
            else:
                app_id = self.t.next_free_id(self.t.apps)
            self.t.apps[app_id] = base.App(app_id, app.name, app.description)
            return app_id

    def get(self, app_id: int) -> Optional[base.App]:
        with self.client.lock:
            return self.t.apps.get(app_id)

    def get_by_name(self, name: str) -> Optional[base.App]:
        with self.client.lock:
            return next(
                (a for a in self.t.apps.values() if a.name == name), None
            )

    def get_all(self) -> list[base.App]:
        with self.client.lock:
            return list(self.t.apps.values())

    def update(self, app: base.App) -> bool:
        with self.client.lock:
            if app.id not in self.t.apps:
                return False
            self.t.apps[app.id] = app
            return True

    def delete(self, app_id: int) -> bool:
        with self.client.lock:
            return self.t.apps.pop(app_id, None) is not None


class MemoryAccessKeys(_MemoryDAO, base.AccessKeys):
    def insert(self, k: base.AccessKey) -> Optional[str]:
        with self.client.lock:
            key = k.key or base.generate_access_key()
            if key in self.t.access_keys:
                return None
            self.t.access_keys[key] = base.AccessKey(key, k.appid, tuple(k.events))
            return key

    def get(self, key: str) -> Optional[base.AccessKey]:
        with self.client.lock:
            return self.t.access_keys.get(key)

    def get_all(self) -> list[base.AccessKey]:
        with self.client.lock:
            return list(self.t.access_keys.values())

    def get_by_appid(self, appid: int) -> list[base.AccessKey]:
        with self.client.lock:
            return [k for k in self.t.access_keys.values() if k.appid == appid]

    def update(self, k: base.AccessKey) -> bool:
        with self.client.lock:
            if k.key not in self.t.access_keys:
                return False
            self.t.access_keys[k.key] = k
            return True

    def delete(self, key: str) -> bool:
        with self.client.lock:
            return self.t.access_keys.pop(key, None) is not None


class MemoryChannels(_MemoryDAO, base.Channels):
    def insert(self, channel: base.Channel) -> Optional[int]:
        with self.client.lock:
            if any(
                c.appid == channel.appid and c.name == channel.name
                for c in self.t.channels.values()
            ):
                return None
            if channel.id != 0:
                if channel.id in self.t.channels:
                    return None
                cid = channel.id
            else:
                cid = self.t.next_free_id(self.t.channels)
            self.t.channels[cid] = base.Channel(cid, channel.name, channel.appid)
            return cid

    def get(self, channel_id: int) -> Optional[base.Channel]:
        with self.client.lock:
            return self.t.channels.get(channel_id)

    def get_by_appid(self, appid: int) -> list[base.Channel]:
        with self.client.lock:
            return [c for c in self.t.channels.values() if c.appid == appid]

    def delete(self, channel_id: int) -> bool:
        with self.client.lock:
            return self.t.channels.pop(channel_id, None) is not None


class MemoryEngineInstances(_MemoryDAO, base.EngineInstances):
    def insert(self, i: base.EngineInstance) -> str:
        with self.client.lock:
            iid = i.id or uuid.uuid4().hex
            self.t.engine_instances[iid] = (
                i if i.id else dataclasses.replace(i, id=iid)
            )
            return iid

    def get(self, instance_id: str) -> Optional[base.EngineInstance]:
        with self.client.lock:
            return self.t.engine_instances.get(instance_id)

    def get_all(self) -> list[base.EngineInstance]:
        with self.client.lock:
            return list(self.t.engine_instances.values())

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[base.EngineInstance]:
        with self.client.lock:
            rows = [
                i for i in self.t.engine_instances.values()
                if i.status == "COMPLETED"
                and i.engine_id == engine_id
                and i.engine_version == engine_version
                and i.engine_variant == engine_variant
            ]
        rows.sort(key=lambda i: i.start_time, reverse=True)
        return rows

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[base.EngineInstance]:
        rows = self.get_completed(engine_id, engine_version, engine_variant)
        return rows[0] if rows else None

    def update(self, i: base.EngineInstance) -> bool:
        with self.client.lock:
            if i.id not in self.t.engine_instances:
                return False
            self.t.engine_instances[i.id] = i
            return True

    def delete(self, instance_id: str) -> bool:
        with self.client.lock:
            return self.t.engine_instances.pop(instance_id, None) is not None


class MemoryEvaluationInstances(_MemoryDAO, base.EvaluationInstances):
    def insert(self, i: base.EvaluationInstance) -> str:
        with self.client.lock:
            iid = i.id or uuid.uuid4().hex
            self.t.evaluation_instances[iid] = (
                i if i.id else dataclasses.replace(i, id=iid)
            )
            return iid

    def get(self, instance_id: str) -> Optional[base.EvaluationInstance]:
        with self.client.lock:
            return self.t.evaluation_instances.get(instance_id)

    def get_all(self) -> list[base.EvaluationInstance]:
        with self.client.lock:
            return list(self.t.evaluation_instances.values())

    def get_completed(self) -> list[base.EvaluationInstance]:
        with self.client.lock:
            rows = [
                i for i in self.t.evaluation_instances.values()
                if i.status == "EVALCOMPLETED"
            ]
        rows.sort(key=lambda i: i.start_time, reverse=True)
        return rows

    def update(self, i: base.EvaluationInstance) -> bool:
        with self.client.lock:
            if i.id not in self.t.evaluation_instances:
                return False
            self.t.evaluation_instances[i.id] = i
            return True

    def delete(self, instance_id: str) -> bool:
        with self.client.lock:
            return self.t.evaluation_instances.pop(instance_id, None) is not None


class MemoryEngineManifests(_MemoryDAO, base.EngineManifests):
    def insert(self, m: base.EngineManifest) -> None:
        with self.client.lock:
            self.t.engine_manifests[(m.id, m.version)] = m

    def get(self, manifest_id: str, version: str) -> Optional[base.EngineManifest]:
        with self.client.lock:
            return self.t.engine_manifests.get((manifest_id, version))

    def get_all(self) -> list[base.EngineManifest]:
        with self.client.lock:
            return list(self.t.engine_manifests.values())

    def update(self, m: base.EngineManifest, upsert: bool = False) -> bool:
        with self.client.lock:
            if (m.id, m.version) not in self.t.engine_manifests and not upsert:
                return False
            self.t.engine_manifests[(m.id, m.version)] = m
            return True

    def delete(self, manifest_id: str, version: str) -> bool:
        with self.client.lock:
            return (
                self.t.engine_manifests.pop((manifest_id, version), None)
                is not None
            )


class MemoryModels(_MemoryDAO, base.Models):
    def insert(self, model: base.Model) -> None:
        with self.client.lock:
            self.t.models[model.id] = model

    def get(self, model_id: str) -> Optional[base.Model]:
        with self.client.lock:
            return self.t.models.get(model_id)

    def delete(self, model_id: str) -> None:
        with self.client.lock:
            self.t.models.pop(model_id, None)


#: DAO registry used by the Storage registry's lookup (the equivalent of the
#: reference's classname convention, Storage.scala:286-303).
DATA_OBJECTS = {
    "Events": MemoryEvents,
    "Apps": MemoryApps,
    "AccessKeys": MemoryAccessKeys,
    "Channels": MemoryChannels,
    "EngineInstances": MemoryEngineInstances,
    "EngineManifests": MemoryEngineManifests,
    "EvaluationInstances": MemoryEvaluationInstances,
    "Models": MemoryModels,
}
