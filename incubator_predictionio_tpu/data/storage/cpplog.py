"""``cpplog`` event backend — the native append-only event-store engine.

The high-throughput event store, playing the HBase driver's role in the
reference (data/.../storage/hbase/HB{L,P}Events.scala: hashed row keys, one
table per app/channel, server-side scan filters). Storage engine is
``native/src/eventlog.cc`` (C++, ctypes-bound): one framed append-only log
file per (namespace, app, channel); record headers carry event time and
FNV-1a hashes of the filterable fields so time-range / entity / event-name
scans are pushed down to C++ without parsing JSON; deletes are tombstones.
The DAO re-checks every predicate on the JSON payload, so hash collisions
cannot produce wrong results — only wasted candidate reads.

Events only (``PIO_STORAGE_REPOSITORIES_EVENTDATA_{NAME,SOURCE}`` →
``TYPE=cpplog``); metadata/models stay on sqlite/memory/localfs, mirroring
how the reference mixes HBase event data with JDBC/ES metadata.

Like the localfs model store, a log directory is owned by one server
process at a time.
"""

from __future__ import annotations

import ctypes
import json
import logging
import threading
from collections import deque
from datetime import datetime
from pathlib import Path
from typing import Any, Iterator, Optional, Sequence

from incubator_predictionio_tpu import native
from incubator_predictionio_tpu.data.event import (
    Event,
    new_event_id,
    validate_event,
)
from incubator_predictionio_tpu.data.storage import base
from incubator_predictionio_tpu.data.storage.base import UNSET
from incubator_predictionio_tpu.utils.times import to_millis

logger = logging.getLogger(__name__)

_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1

#: auto shard-count floor: below this many entries per shard the thread
#: spawn + table-merge overhead outweighs the parallel scan (an explicit
#: PIO_SCAN_SHARDS bypasses the floor — the differential tests exercise
#: shard counts on tiny logs)
_MIN_SCAN_ENTRIES_PER_SHARD = 200_000


def _h(s: Optional[str]) -> int:
    return 0 if s is None else native.fnv1a64(s.encode("utf-8"))


#: group-commit outcome sentinel: the merged append hit the sidecar
#: limits, so the caller must retry its own batch alone (see
#: CppLogEvents.insert_interactions)
_RETRY_SOLO = object()


class _PendingInsert:
    """One caller's prepped columnar batch, waiting in the group-commit
    queue. ``key`` is the scalar field tuple (app, channel, entity types,
    event name, value prop) — only identical keys merge."""

    __slots__ = ("key", "n", "times", "uidx", "iidx", "vals", "utab",
                 "itab", "done", "ids", "error")

    def __init__(self, key, n, times, uidx, iidx, vals, utab, itab):
        self.key = key
        self.n = n
        self.times = times
        self.uidx = uidx
        self.iidx = iidx
        self.vals = vals
        self.utab = utab
        self.itab = itab
        self.done = threading.Event()
        self.ids = None
        self.error = None


class StorageClient(base.BaseStorageClient):
    """Holds the log directory and open native handles."""

    def __init__(self, config: base.StorageClientConfig):
        super().__init__(config)
        lib = native.load()
        if lib is None:
            raise base.StorageError(
                "cpplog backend requires the native library (g++ toolchain)")
        self.lib = lib
        from incubator_predictionio_tpu.data.storage import pio_home
        path = config.properties.get("PATH") or str(
            Path(pio_home()) / "cpplog")
        self.dir = Path(path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.lock = threading.RLock()
        self._handles: dict[str, int] = {}
        # handle read-pins: a lock-narrowed scan (CppLogEvents.
        # scan_interactions) runs its native calls WITHOUT holding
        # self.lock, so drop/close/compact — which free or swap the
        # native handle — must wait until in-flight readers drain.
        # Condition(self.lock) releases the (R)Lock while waiting, so a
        # pinned reader can still take the lock briefly (revalidation,
        # cache writes) without deadlocking the waiter.
        self._pins: dict[str, int] = {}
        self._pins_cv = threading.Condition(self.lock)
        # process-local log generations: bumped whenever a log's entry
        # numbering is rewritten (compact/drop), so tail cursors from
        # before the rewrite are detectable even after the entry count
        # grows past its old value (speed-layer resync contract)
        self._generations: dict[str, int] = {}
        # multi-writer layout state (all guarded by self.lock; the
        # annotations are VERIFIED by pio-lint's unguarded-shared-state
        # pass, docs/lint.md): resolved shard counts per meta file,
        # per-shard append locks, and the cold-tier existence cache
        self._shard_counts: dict[str, int] = {}  # pio-lint: guarded-by(lock)
        self._shard_locks: dict[str, threading.Lock] = {}  # pio-lint: guarded-by(lock)
        self._has_cold: dict[str, bool] = {}  # pio-lint: guarded-by(lock)
        # per-shard REWRITE epochs (replication): bumped only when a
        # segment file's existing bytes are rewritten (roll/compact/
        # drop) — append-only growth (including tombstone markers) does
        # NOT bump it, so a follower tailing the file byte-level keeps
        # its prefix valid across deletes and resyncs only on rewrites.
        # In-memory only: a leader restart reads as an epoch change,
        # which conservatively triggers a follower resync.
        self._repl_epochs: dict[str, int] = {}  # pio-lint: guarded-by(lock)
        # per-log COUNT OBSERVATIONS: (entry_count, wall_ms) snapshots —
        # "at wall w this process saw the log hold c entries". Pushed by
        # appends (exact: the count just before/after the write) AND by
        # every tail read / tail_cursor call, so a pure READER process
        # (the split-deployment prediction server polling a log the
        # event server writes) still bounds append times by its own poll
        # cadence. The freshness trace stamps a tail [lo, hi) with the
        # NEWEST observation whose count <= lo: every entry past lo was
        # appended after that wall, so age is only ever OVERSTATED —
        # exactly (base.py contract) — by at most one append batch
        # in-process and one poll interval cross-process. No covering
        # observation -> -1 (unattributable, dropped from the trace).
        # Cleared on generation bump (entries renumber).
        self._count_marks: dict[str, "deque"] = {}

    def generation(self, ns: str, app_id: int,
                   channel_id: Optional[int]) -> int:
        key = str(self._file(ns, app_id, channel_id))
        with self.lock:
            return self._generations.get(key, 0)

    def bump_generation_locked(self, path) -> None:
        key = str(path)
        self._generations[key] = self._generations.get(key, 0) + 1
        # entries renumber: every count observation is now meaningless
        self._count_marks.pop(key, None)

    def bump_epoch_locked(self, hot_path) -> None:
        """Mark a shard's segment files as REWRITTEN (roll/compact/
        drop): replication followers discard their byte-level prefix
        and resync the shard."""
        key = str(hot_path)
        self._repl_epochs[key] = self._repl_epochs.get(key, 0) + 1

    def epoch_locked(self, hot_path) -> int:
        return self._repl_epochs.get(str(hot_path), 0)

    def note_count_locked(self, path, count: int) -> None:
        """Record one count observation ("the log held ``count`` entries
        now") — the freshness trace's append-stamp source. Appends push
        their before/after counts (exact stamps); tail reads and
        tail_cursor push what they saw (the cross-process bound). Caller
        holds the client lock."""
        from incubator_predictionio_tpu.utils.times import wall_millis

        marks = self._count_marks.get(str(path))
        if marks is None:
            marks = self._count_marks[str(path)] = deque(maxlen=4096)
        count = int(count)
        if marks and marks[-1][0] == count:
            # same count seen later: the newer wall is the TIGHTER lower
            # bound for entries appended past it
            marks[-1] = (count, wall_millis())
            return
        marks.append((count, wall_millis()))

    def append_wall_since_locked(self, path, lo: int) -> int:
        """Append-wall lower bound (epoch ms) for entries at/after
        position ``lo``: the NEWEST count observation with count <= lo —
        every entry past ``lo`` was appended after that wall, so the
        batch's age can only be OVERSTATED (base.py contract), never
        fabricated fresh. -1 when no observation covers ``lo`` (the
        entries predate everything this process has seen — e.g. a log
        written before the first poll). Caller holds the client lock."""
        marks = self._count_marks.get(str(path))
        if marks:
            for count, wall in reversed(marks):
                if count <= lo:
                    return wall
        return -1

    def pin(self, ns: str, app_id: int, channel_id: Optional[int]) -> str:
        """Mark the (ns, app, channel) handle as read-busy; returns the
        key for :meth:`unpin`. Caller must unpin in a finally block."""
        key = str(self._file(ns, app_id, channel_id))
        with self.lock:
            self._pins[key] = self._pins.get(key, 0) + 1
        return key

    def unpin(self, key: str) -> None:
        with self.lock:
            n = self._pins.get(key, 0) - 1
            if n > 0:
                self._pins[key] = n
            else:
                self._pins.pop(key, None)
            self._pins_cv.notify_all()

    def _wait_unpinned_locked(self, key: Optional[str] = None) -> None:
        """Block (lock released while waiting) until no reader pins the
        key — or, with key=None, until no reader pins anything. Scans are
        finite, so this always terminates."""
        if key is None:
            while any(self._pins.values()):
                self._pins_cv.wait()
        else:
            while self._pins.get(key, 0) > 0:
                self._pins_cv.wait()

    def _file(self, ns: str, app_id: int, channel_id: Optional[int],
              shard: int = 0) -> Path:
        """Hot segment of writer shard ``shard``. Shard 0 keeps the
        legacy single-writer name, so existing logs ARE shard 0 of a
        1-shard layout — no migration."""
        chan = 0 if channel_id is None else channel_id
        stem = f"{ns}app{app_id}_ch{chan}"
        if shard:
            return self.dir / f"{stem}.w{shard}.log"
        return self.dir / f"{stem}.log"

    def _meta_file(self, ns: str, app_id: int,
                   channel_id: Optional[int]) -> Path:
        chan = 0 if channel_id is None else channel_id
        return self.dir / f"{ns}app{app_id}_ch{chan}.shards"

    @staticmethod
    def _cold(path: Path) -> Path:
        """Cold-tier segment of a hot file (sealed rolls accumulate
        here; background compaction only ever rewrites this file)."""
        return path.with_name(path.name + ".cold")

    def shards(self, ns: str, app_id: int,
               channel_id: Optional[int]) -> int:
        """Writer-shard count for this (ns, app, channel) log. Fixed at
        log creation: a ``<stem>.shards`` meta file pins it; a NEW log
        (no meta, no legacy file) takes ``PIO_LOG_SHARDS`` and persists
        it, so readers and writers of an existing log can never disagree
        with the layout on disk."""
        import os

        mkey = str(self._meta_file(ns, app_id, channel_id))
        with self.lock:
            n = self._shard_counts.get(mkey)
            if n is not None:
                return n
            meta = Path(mkey)
            if meta.exists():
                try:
                    n = max(int(json.loads(meta.read_text())["shards"]), 1)
                except (ValueError, KeyError, OSError):
                    n = 1
            elif self._file(ns, app_id, channel_id).exists():
                n = 1  # legacy single-writer log predating the meta
            else:
                try:
                    n = max(int(os.environ.get("PIO_LOG_SHARDS", "1")), 1)
                except ValueError:
                    n = 1
                if n > 1:
                    meta.write_text(json.dumps({"shards": n}))
            self._shard_counts[mkey] = n
            return n

    def set_shards(self, ns: str, app_id: int, channel_id: Optional[int],
                   n: int) -> None:
        """Pin the shard count (replication followers mirror the
        leader's layout before the first apply). Refuses to change the
        layout of a log that already has data."""
        n = max(int(n), 1)
        with self.lock:
            cur = self.shards(ns, app_id, channel_id)
            if cur == n:
                return
            # only DATA pins the layout: a status probe on a follower
            # that hasn't been configured yet materializes empty
            # segment files (handle_path creates on open), and those
            # must not wedge the follower on its first configure
            empties = []
            for k in range(cur):
                hot = self._file(ns, app_id, channel_id, k)
                for path in (self._cold(hot), hot):
                    if not path.exists():
                        continue
                    h = self.handle_path(path)
                    if int(self.lib.pio_evlog_entry_count(h)) > 0:
                        raise base.StorageError(
                            f"cannot reshape an existing log from {cur} "
                            f"to {n} writer shards")
                    empties.append((hot, path))
            for hot, path in empties:
                key = str(path)
                self._wait_unpinned_locked(key)
                h = self._handles.pop(key, None)
                if h is not None:
                    self.lib.pio_evlog_close(h)
                path.unlink(missing_ok=True)
                self._has_cold.pop(str(hot), None)
            meta = self._meta_file(ns, app_id, channel_id)
            if n > 1:
                meta.write_text(json.dumps({"shards": n}))
            else:
                meta.unlink(missing_ok=True)
            self._shard_counts[str(meta)] = n

    def has_cold(self, path: Path) -> bool:
        key = str(path)
        with self.lock:
            v = self._has_cold.get(key)
            if v is None:
                v = self._has_cold[key] = self._cold(path).exists()
            return v

    def shard_lock(self, path) -> threading.Lock:
        """Per-shard append lock: writers to DIFFERENT shards never
        contend on it, which is the whole multi-writer point (the native
        per-handle mutex is the last line of defense, not the
        serialization point)."""
        key = str(path)
        with self.lock:
            lk = self._shard_locks.get(key)
            if lk is None:
                lk = self._shard_locks[key] = threading.Lock()
            return lk

    def handle_path(self, path) -> int:
        """Open (or return the cached) native handle for an explicit
        segment file — shard hots and cold tiers share one handle
        table."""
        key = str(path)
        with self.lock:
            h = self._handles.get(key)
            if h is None:
                h = self.lib.pio_evlog_open(key.encode())
                if not h:
                    raise base.StorageError(f"cannot open event log {key}")
                self._handles[key] = h
            return h

    def handle(self, ns: str, app_id: int, channel_id: Optional[int]) -> int:
        # resolve (and persist) the shard count BEFORE the open creates
        # the shard-0 file: a bare legacy .log with no meta pins the log
        # to one writer forever, so the meta must hit disk first
        self.shards(ns, app_id, channel_id)
        return self.handle_path(self._file(ns, app_id, channel_id))

    def close_path_locked(self, path) -> None:
        """Close one segment's cached handle (caller holds the lock and
        has waited out pins) — the reload/roll seam."""
        h = self._handles.pop(str(path), None)
        if h is not None:
            self.lib.pio_evlog_close(h)

    def drop(self, ns: str, app_id: int, channel_id: Optional[int]) -> bool:
        nsh = self.shards(ns, app_id, channel_id)
        with self.lock:
            for k in range(nsh):
                hot = self._file(ns, app_id, channel_id, k)
                for path in (self._cold(hot), hot):
                    key = str(path)
                    self._wait_unpinned_locked(key)
                    h = self._handles.pop(key, None)
                    if h is not None:
                        self.lib.pio_evlog_close(h)
                    path.unlink(missing_ok=True)
                    self._has_cold.pop(str(hot), None)
                from incubator_predictionio_tpu.data.storage import (
                    traincache,
                )
                traincache.invalidate(hot)
                self.bump_generation_locked(hot)
                self.bump_epoch_locked(hot)
            meta = self._meta_file(ns, app_id, channel_id)
            meta.unlink(missing_ok=True)
            self._shard_counts.pop(str(meta), None)
        return True

    def sync(self) -> None:
        """fdatasync every open log (durability point; appends only fflush —
        torn tails are dropped by the reopen scan in eventlog.cc)."""
        with self.lock:
            for key, h in self._handles.items():
                if self.lib.pio_evlog_sync(h) != 0:
                    raise base.StorageError(
                        f"fdatasync failed on event log {key}")

    def close(self) -> None:
        import logging
        with self.lock:
            self._wait_unpinned_locked()
            for key, h in self._handles.items():
                if self.lib.pio_evlog_sync(h) != 0:
                    logging.getLogger(__name__).warning(
                        "fdatasync failed on event log %s at close; recent "
                        "appends may not be durable", key)
                self.lib.pio_evlog_close(h)
            self._handles.clear()


class CppLogEvents(base.Events):
    """Events DAO over the native log (contract: LEvents.scala:40-492)."""

    FAST_LOCAL = True  # native append, no fsync per op: ingest inline
    #: insert_interactions coalesces concurrent callers into one native
    #: append (see __init__) — the EventServer keys its dispatch policy
    #: on this declared capability, not on private method names
    GROUP_COMMIT = True

    def __init__(self, client: StorageClient,
                 config: base.StorageClientConfig, prefix: str = ""):
        self.client = client
        self.ns = prefix
        # group-commit state for insert_interactions (the REST batch hot
        # path): concurrent wire batches coalesce into ONE native append
        # under the client lock. The per-append fixed cost (~0.3 ms:
        # ctypes crossing + C++ buffered-write epilogue) otherwise caps
        # 50-event wire batches at ~28k ev/s no matter how many clients
        # post concurrently, because the client lock serializes appends.
        self._gc_mu = threading.Lock()
        self._gc_pending: list = []
        # persistent fan-out pool for sharded appends (spawning threads
        # per append costs more than a small native append itself);
        # created lazily under the client lock  # pio-lint: guarded-by(client.lock)
        self._fanout_pool = None
        # observability (served under /stats.json "groupCommit"): how
        # well concurrent callers coalesce — appends vs caller batches
        # is the amortization factor operators tune client counts by
        self._gc_appends = 0       # native appends performed
        self._gc_caller_batches = 0  # caller batches those appends carried
        self._gc_events = 0        # events written through group commit
        self._gc_max_merge = 0     # largest events-per-append seen
        # events landed per writer shard (sharded layouts only) — the
        # skew signal behind pio_ingest_shard_events{shard}
        self._shard_events: dict[int, int] = {}  # pio-lint: guarded-by(_gc_mu)
        # sub-metrics of the last full sharded scan (shard count, native
        # lock-held wall, merge/total walls — _merge_shards fills the
        # same dict the bench reads), exported as gauges at scrape time
        self._last_scan_stats: dict = {}
        # scrape-time bridge into the process registry: group-commit and
        # scan counters show up on every server's GET /metrics. Named
        # registration (replaces the previous backend's hook) + weakref
        # (a dropped Events object must be collectable) keep
        # Storage.reset()/re-configure cycles from accumulating hooks.
        import weakref

        from incubator_predictionio_tpu.obs import metrics as obs_metrics

        ref = weakref.ref(self)

        def collect() -> None:
            ev = ref()
            if ev is not None:
                ev._export_native_metrics()

        obs_metrics.REGISTRY.register_collector("cpplog_native", collect)

    def _export_native_metrics(self) -> None:
        """Snapshot the native-side counters into registry gauges
        (gauges, not counters: the registry mirrors a snapshot owned by
        the storage layer; process restarts and backend swaps reset it).
        Runs only at scrape time — zero cost on the ingest hot path."""
        from incubator_predictionio_tpu.obs import metrics as obs_metrics

        reg = obs_metrics.REGISTRY
        gc = self.group_commit_stats()
        reg.gauge("pio_group_commit_appends",
                  "native appends performed by the group commit"
                  ).set(gc["appends"])
        reg.gauge("pio_group_commit_caller_batches",
                  "caller batches carried by those appends"
                  ).set(gc["callerBatches"])
        reg.gauge("pio_group_commit_events",
                  "events written through the group commit"
                  ).set(gc["events"])
        reg.gauge("pio_group_commit_mean_events_per_append",
                  "achieved coalescing: events per native append"
                  ).set(gc["meanEventsPerAppend"])
        scan = self._last_scan_stats
        if scan:
            reg.gauge("pio_scan_shards",
                      "shard count of the last full event-log scan"
                      ).set(scan.get("scan_shards", 0))
            reg.gauge("pio_scan_lock_held_seconds",
                      "native log-mutex wall held by the last scan's "
                      "snapshots (writers stalled at most this long)"
                      ).set(scan.get("scan_lock_held_s", 0.0))
            reg.gauge("pio_scan_wall_seconds",
                      "total wall of the last full scan"
                      ).set(scan.get("scan_wall_s", 0.0))
            reg.gauge("pio_scan_rows",
                      "interaction rows the last full scan returned"
                      ).set(scan.get("scan_rows", 0))
        with self._gc_mu:
            shard_events = dict(self._shard_events)
        if shard_events:
            g = reg.gauge(
                "pio_ingest_shard_events",
                "events landed per writer shard since server start "
                "(watch the spread for writer-shard skew)",
                labels=("shard",))
            for k, v in shard_events.items():
                g.labels(shard=str(k)).set(v)

    def _export_retrain_delta(self, tail_rows: int) -> None:
        """pio_retrain_delta_rows — the event delta the last cache-served
        scan actually re-scanned (the O(delta) steady-state figure).
        Booked once per scan on the host path; never inside a trace."""
        try:
            from incubator_predictionio_tpu.obs import metrics as obs_metrics

            obs_metrics.REGISTRY.gauge(
                "pio_retrain_delta_rows",
                "event rows appended since the previous training scan "
                "(the tail the cache fold re-scanned)",
            ).set(tail_rows)
        except Exception:
            logger.exception("retrain-delta gauge export failed")

    def _handle(self, app_id: int, channel_id: Optional[int]) -> int:
        return self.client.handle(self.ns, app_id, channel_id)

    # -- multi-writer layout ----------------------------------------------
    def _nshards(self, app_id: int, channel_id: Optional[int]) -> int:
        return self.client.shards(self.ns, app_id, channel_id)

    def _is_plain(self, app_id: int, channel_id: Optional[int]) -> bool:
        """True for the legacy layout (one writer, no cold tier) —
        every method keeps its original single-file code path then,
        byte-for-byte."""
        if self._nshards(app_id, channel_id) != 1:
            return False
        return not self.client.has_cold(
            self.client._file(self.ns, app_id, channel_id))

    def _hot_path(self, app_id, channel_id, shard: int) -> Path:
        return self.client._file(self.ns, app_id, channel_id, shard)

    def _unit_paths(self, app_id, channel_id) -> list:
        """Segment files in merge order: for each shard, cold tier first
        (entries there precede every hot entry of the shard), then hot.
        → [(shard, path, is_hot)]."""
        out = []
        for k in range(self._nshards(app_id, channel_id)):
            hot = self._hot_path(app_id, channel_id, k)
            if self.client.has_cold(hot):
                out.append((k, self.client._cold(hot), False))
            out.append((k, hot, True))
        return out

    def _snapshot_shards_locked(self, app_id, channel_id) -> list:
        """Under the client lock: per-shard layout snapshot →
        [(shard, hot_path, gen, [(path, handle, count)], total)]."""
        lib = self.client.lib
        shards: dict[int, list] = {}
        order: list[int] = []
        for k, path, _hot in self._unit_paths(app_id, channel_id):
            h = self.client.handle_path(path)
            cnt = int(lib.pio_evlog_entry_count(h))
            if k not in shards:
                shards[k] = []
                order.append(k)
            shards[k].append((path, h, cnt))
        out = []
        for k in order:
            hot = self._hot_path(app_id, channel_id, k)
            gen = self.client._generations.get(str(hot), 0)
            units = shards[k]
            out.append((k, hot, gen, units, sum(c for _, _, c in units)))
        return out

    def _pin_units_locked(self, snap) -> list:
        pins = []
        for _k, _hot, _gen, units, _tot in snap:
            for path, _h, _cnt in units:
                key = str(path)
                self.client._pins[key] = self.client._pins.get(key, 0) + 1
                pins.append(key)
        return pins

    def _spray(self, uidx, utab, nshards: int):
        """Per-row writer shard from the FNV-1a hash of the user entity
        id — an entity's whole history lands in one shard, so per-entity
        event order survives sharding."""
        import numpy as np

        hashes = native.fnv1a64_table(utab.blob, utab.offsets)
        tab_shard = (hashes % np.uint64(nshards)).astype(np.int64)
        return tab_shard[uidx]

    def _scan_units(self, units, start_time, until_time, entity_type,
                    target_entity_type, names, fixed, value_prop,
                    default_value, stats=None, shard_sink=None):
        """Fan the native scan out over SEGMENT FILES (shard hots and
        cold tiers) instead of entry ranges of one file — the
        multi-writer generalization of :meth:`_scan_sharded`. ``units``
        is [(handle, lo, hi)] in merge order; the merge itself is the
        same TableMerger discipline (global first-seen interning in unit
        order, one stable time sort when an inversion exists), so the
        result is byte-identical to a single-writer scan of the same
        events whenever event times are distinct. Caller must have
        pinned every unit's path."""
        import time as _time
        from concurrent.futures import ThreadPoolExecutor

        t_all0 = _time.perf_counter()

        def run(u):
            h, lo, hi = u
            t0 = _time.perf_counter()
            out = self._scan_native(
                h, start_time, until_time, entity_type,
                target_entity_type, names, fixed, value_prop,
                default_value, min_entry_idx=lo, max_entry_idx=hi,
                with_times=True, n_threads=1 if len(units) > 1 else 0)
            return out, _time.perf_counter() - t0

        if len(units) == 1:
            return self._merge_shards(iter([run(units[0])]), 1, t_all0,
                                      stats, shard_sink)
        with ThreadPoolExecutor(max_workers=len(units)) as pool:
            futs = [pool.submit(run, u) for u in units]
            return self._merge_shards(
                iter(f.result() for f in futs), len(units), t_all0,
                stats, shard_sink)

    # -- lifecycle ---------------------------------------------------------
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        self._handle(app_id, channel_id)
        return True

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        return self.client.drop(self.ns, app_id, channel_id)

    def close(self) -> None:  # client-owned handles stay for other DAOs
        with self.client.lock:
            pool, self._fanout_pool = self._fanout_pool, None
        if pool is not None:
            pool.shutdown(wait=False)

    # -- record io ---------------------------------------------------------
    def _read_raw(self, h: int, index: int) -> Optional[bytes]:
        cap = 4096
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self.client.lib.pio_evlog_read(h, index, buf, cap)
            if n < 0:
                return None
            if n <= cap:
                return buf.raw[:n]
            cap = n

    def _read(self, h: int, index: int) -> Optional[dict]:
        payload = self._read_raw(h, index)
        if payload is None:
            return None
        return json.loads(payload.decode("utf-8"))

    def _candidates_by_id(self, h: int, event_id: str) -> list[int]:
        cap = 64
        out = (ctypes.c_int64 * cap)()
        n = self.client.lib.pio_evlog_find_id(h, _h(event_id), out, cap)
        return list(out[:n])

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        # one code path: a single insert is a batch of one (gets the same
        # upsert semantics and the sidecar fast-scan block)
        return self.insert_batch([event], app_id, channel_id)[0]

    @staticmethod
    def _derive_event_ids(seed: int, n: int) -> list:
        """The 32-hex event ids pio_evlog_append_interactions generates for
        ``id_seed=seed`` — byte-identical to eventlog.cc (splitmix64 over
        seed^k and seed+golden+k), so a caller routing a batch through the
        columnar import can report the stored ids without reading back."""
        import numpy as np

        def mix(x):
            x = x + np.uint64(0x9E3779B97F4A7C15)
            x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            return x ^ (x >> np.uint64(31))

        with np.errstate(over="ignore"):
            k = np.arange(n, dtype=np.uint64)
            s = np.uint64(seed)
            ida = mix(s ^ k)
            idb = mix(s + np.uint64(0x9E3779B97F4A7C15) + k)
        # render all n ids with ONE hexlify over a packed big-endian
        # buffer: per-id f-string formatting was the ingest hot path's
        # largest single Python cost (~1 us/id dwarfs the ~0.2 us/row
        # native append at batch scale)
        import binascii

        buf = np.empty((n, 2), dtype=">u8")
        buf[:, 0] = ida
        buf[:, 1] = idb
        hexstr = binascii.hexlify(buf.tobytes()).decode("ascii")
        return [hexstr[i:i + 32] for i in range(0, 32 * n, 32)]

    def _uniform_batch(self, events: Sequence[Event]):
        """events → (Interactions, etype, tetype, name, vprop, times_ms)
        when the whole batch can take the columnar import, else None.

        The equivalence conditions live in ONE place —
        ``base.uniform_interactions`` — shared with the CLI import gate
        (cli/commands.py), so the two paths cannot drift. The gate's
        screens imply full ``validate_event`` validity for every batch it
        ACCEPTS (see its docstring), so no per-event re-validation here —
        rejected batches fall to the generic path, which validates. NOTE
        the one observable delta, documented in docs/data-collection.md:
        columnar records report creationTime == eventTime (the compact
        sidecar stores one timestamp)."""
        return base.uniform_interactions(events)

    def insert_batch(self, events: Sequence[Event], app_id: int,
                     channel_id: Optional[int] = None) -> list:
        """Bulk fast path: one framed batch write (pio_evlog_append_bulk).

        Hashing, sidecar construction, and framing happen in C++; Python
        serializes the JSON document and packs the numeric properties. Each
        record gets a binary sidecar block (the columnar-scan fast path)
        unless a field exceeds the sidecar's length limits.

        Uniform id-less interaction batches (the REST batch endpoint's hot
        shape) route through the fully-native columnar import instead —
        compact records, C++ rendering, and training-projection
        maintenance — with the generated ids derived in Python from the
        same seed formula."""
        import secrets
        import struct

        import numpy as np

        n = len(events)
        if n == 0:
            return []
        if n >= 8:
            fast = self._uniform_batch(events)
            if fast is not None:
                inter, etype, tetype, name, vprop, times = fast
                seed = int.from_bytes(secrets.token_bytes(8), "little")
                key = (app_id, channel_id, etype, tetype, name, vprop)
                try:
                    prep = self._prep_columnar(inter, times)
                    with self.client.lock:
                        rc, ids = self._append_columnar_any(
                            key, n, *prep, seed=seed)
                except base.StorageError:
                    # safe to fall through to the generic path: the -2
                    # (sidecar-limits) case rejects BEFORE any write, and a
                    # write failure truncates the log back to the batch
                    # start (eventlog.cc append_interactions is
                    # all-or-nothing), so nothing partial remains
                    rc, ids = 0, None
                if rc == n:
                    return ids
        # last-wins for duplicate explicit ids WITHIN the batch too (sqlite
        # INSERT OR REPLACE parity): earlier occurrences are dropped from
        # the write set, since the per-event tombstone scan below can only
        # see records already in the log
        last_pos: dict[str, int] = {
            e.event_id: k for k, e in enumerate(events) if e.event_id
        }
        if not self._is_plain(app_id, channel_id):
            return self._insert_batch_sharded(events, app_id, channel_id,
                                              last_pos)
        with self.client.lock:
            h = self._handle(app_id, channel_id)
            ids: list[str] = []
            times = np.empty(n, np.int64)
            offs = np.empty(7 * n + 1, np.int64)
            meta = bytearray(8 * n)
            chunks: list[bytes] = []
            skipped = 0
            pos = 0
            offs[0] = 0
            j = 0
            for k, event in enumerate(events):
                validate_event(event)
                if event.event_id:
                    eid = event.event_id
                    if last_pos[eid] != k:  # superseded later in this batch
                        ids.append(eid)
                        skipped += 1
                        continue
                    # upsert parity with insert(): tombstone existing record
                    for idx in self._candidates_by_id(h, eid):
                        obj = self._read(h, idx)
                        if obj is not None and obj.get("eventId") == eid:
                            self.client.lib.pio_evlog_tombstone(h, idx)
                else:
                    eid = new_event_id()
                ids.append(eid)
                w = k - skipped  # position in the write set
                payload = json.dumps(
                    event.with_id(eid).to_jsonable(), separators=(",", ":")
                ).encode("utf-8")
                times[w] = to_millis(event.event_time)
                etype_b = event.entity_type.encode("utf-8")
                ent_b = event.entity_id.encode("utf-8")
                name_b = event.event.encode("utf-8")
                tet_b = (event.target_entity_type or "").encode("utf-8")
                tei_b = (event.target_entity_id or "").encode("utf-8")
                has_target = event.target_entity_id is not None
                # numeric properties for the sidecar's value lookup
                props_blob = b""
                n_props = 0
                sidecar_ok = max(
                    len(etype_b), len(ent_b), len(name_b),
                    len(tet_b), len(tei_b)) < 0xFFFF
                if sidecar_ok:
                    parts = []
                    for key, v in event.properties.to_jsonable().items():
                        if isinstance(v, bool) or \
                                not isinstance(v, (int, float)):
                            continue
                        kb = key.encode("utf-8")
                        if len(kb) > 255 or n_props == 255:
                            # a numeric prop the sidecar cannot carry: the
                            # sidecar would disagree with the JSON, so this
                            # record must use the JSON path
                            sidecar_ok = False
                            break
                        parts.append(struct.pack("<B", len(kb)) + kb
                                     + struct.pack("<d", float(v)))
                        n_props += 1
                    if sidecar_ok:
                        props_blob = b"".join(parts)
                    else:
                        n_props = 0
                struct.pack_into("<BBBBI", meta, 8 * w,
                                 1 if has_target else 0,
                                 1 if sidecar_ok else 0,
                                 n_props, 0, len(props_blob))
                for field in (etype_b, ent_b, name_b, eid.encode("utf-8"),
                              tet_b, tei_b, props_blob + payload):
                    chunks.append(field)
                    pos += len(field)
                    j += 1
                    offs[j] = pos
            n_write = n - skipped
            buf = b"".join(chunks)
            rc = self.client.lib.pio_evlog_append_bulk(
                h, n_write,
                times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                buf,
                offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                bytes(meta),
            )
            if rc != n_write:
                raise base.StorageError("bulk event append failed")
            if n_write:
                end = self.client.lib.pio_evlog_entry_count(h)
                path = self.client._file(self.ns, app_id, channel_id)
                self.client.note_count_locked(path, end - n_write)
                self.client.note_count_locked(path, end)
        return ids

    def _insert_batch_sharded(self, events: Sequence[Event], app_id: int,
                              channel_id: Optional[int],
                              last_pos: dict) -> list:
        """Generic (per-Event) insert for sharded/tiered layouts:
        events spray to writer shards by entity-id hash (the same
        policy as the columnar path, so an entity's history stays in
        one shard) and each shard takes ONE bulk append. Explicit-id
        upserts probe EVERY segment of every shard — the prior record
        may live anywhere when the entity id changed between writes —
        and a tombstone landing in a COLD segment bumps that shard's
        generation (the marker shifts the shard's merged entry
        numbering, so tail cursors must resync)."""
        import struct

        import numpy as np

        nsh = self._nshards(app_id, channel_id)
        n = len(events)
        ids: list = [None] * n
        with self.client.lock:
            units = [(k, path, self.client.handle_path(path), is_hot)
                     for k, path, is_hot in
                     self._unit_paths(app_id, channel_id)]

            def probe_tombstone(eid: str) -> None:
                for uk, _upath, uh, u_hot in units:
                    for idx in self._candidates_by_id(uh, eid):
                        obj = self._read(uh, idx)
                        if obj is not None and obj.get("eventId") == eid:
                            self.client.lib.pio_evlog_tombstone(uh, idx)
                            if not u_hot:
                                self.client.bump_generation_locked(
                                    self._hot_path(app_id, channel_id,
                                                   uk))

            write_rows: dict[int, list] = {}  # shard -> [(event, eid)]
            for i, event in enumerate(events):
                validate_event(event)
                if event.event_id:
                    eid = event.event_id
                    ids[i] = eid
                    if last_pos[eid] != i:  # superseded later in batch
                        continue
                    probe_tombstone(eid)
                else:
                    eid = new_event_id()
                    ids[i] = eid
                shard = native.fnv1a64(
                    event.entity_id.encode("utf-8")) % nsh
                write_rows.setdefault(shard, []).append((event, eid))
            for shard in sorted(write_rows):
                rows = write_rows[shard]
                path = self._hot_path(app_id, channel_id, shard)
                h = self.client.handle_path(path)
                m = len(rows)
                times = np.empty(m, np.int64)
                offs = np.empty(7 * m + 1, np.int64)
                meta = bytearray(8 * m)
                chunks: list[bytes] = []
                pos = 0
                offs[0] = 0
                j = 0
                for w, (event, eid) in enumerate(rows):
                    payload = json.dumps(
                        event.with_id(eid).to_jsonable(),
                        separators=(",", ":")).encode("utf-8")
                    times[w] = to_millis(event.event_time)
                    etype_b = event.entity_type.encode("utf-8")
                    ent_b = event.entity_id.encode("utf-8")
                    name_b = event.event.encode("utf-8")
                    tet_b = (event.target_entity_type or ""
                             ).encode("utf-8")
                    tei_b = (event.target_entity_id or ""
                             ).encode("utf-8")
                    has_target = event.target_entity_id is not None
                    props_blob = b""
                    n_props = 0
                    sidecar_ok = max(
                        len(etype_b), len(ent_b), len(name_b),
                        len(tet_b), len(tei_b)) < 0xFFFF
                    if sidecar_ok:
                        parts = []
                        for pkey, v in \
                                event.properties.to_jsonable().items():
                            if isinstance(v, bool) or \
                                    not isinstance(v, (int, float)):
                                continue
                            kb = pkey.encode("utf-8")
                            if len(kb) > 255 or n_props == 255:
                                sidecar_ok = False
                                break
                            parts.append(
                                struct.pack("<B", len(kb)) + kb
                                + struct.pack("<d", float(v)))
                            n_props += 1
                        if sidecar_ok:
                            props_blob = b"".join(parts)
                        else:
                            n_props = 0
                    struct.pack_into("<BBBBI", meta, 8 * w,
                                     1 if has_target else 0,
                                     1 if sidecar_ok else 0,
                                     n_props, 0, len(props_blob))
                    for field in (etype_b, ent_b, name_b,
                                  eid.encode("utf-8"), tet_b, tei_b,
                                  props_blob + payload):
                        chunks.append(field)
                        pos += len(field)
                        j += 1
                        offs[j] = pos
                buf = b"".join(chunks)
                rc = self.client.lib.pio_evlog_append_bulk(
                    h, m,
                    times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    buf,
                    offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    bytes(meta))
                if rc != m:
                    raise base.StorageError("bulk event append failed")
                end = self.client.lib.pio_evlog_entry_count(h)
                self.client.note_count_locked(path, end - m)
                self.client.note_count_locked(path, end)
        return ids

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        with self.client.lock:
            if self._is_plain(app_id, channel_id):
                handles = [self._handle(app_id, channel_id)]
            else:
                handles = [self.client.handle_path(p) for _k, p, _hot
                           in self._unit_paths(app_id, channel_id)]
            for h in handles:
                for idx in self._candidates_by_id(h, event_id):
                    obj = self._read(h, idx)
                    if obj is not None and obj.get("eventId") == event_id:
                        return Event.from_jsonable(obj)
            return None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self.client.lock:
            if self._is_plain(app_id, channel_id):
                h = self._handle(app_id, channel_id)
                for idx in self._candidates_by_id(h, event_id):
                    obj = self._read(h, idx)
                    if obj is not None and obj.get("eventId") == event_id:
                        return self.client.lib.pio_evlog_tombstone(
                            h, idx) == 0
                return False
            for k, path, is_hot in self._unit_paths(app_id, channel_id):
                h = self.client.handle_path(path)
                for idx in self._candidates_by_id(h, event_id):
                    obj = self._read(h, idx)
                    if obj is not None and obj.get("eventId") == event_id:
                        ok = self.client.lib.pio_evlog_tombstone(
                            h, idx) == 0
                        if ok and not is_hot:
                            # the marker appended to the COLD tier sits
                            # between cold and hot in merge order, so
                            # the shard's merged entry numbering shifts:
                            # tail cursors must resync
                            self.client.bump_generation_locked(
                                self._hot_path(app_id, channel_id, k))
                        return ok
            return False

    # -- query -------------------------------------------------------------
    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        names = None if event_names is None else list(event_names)
        if names is not None and not names:
            return iter(())  # IN () matches nothing (sqlite parity)
        want = -1 if limit is None or limit < 0 else limit
        if want == 0:
            return iter(())
        if not self._is_plain(app_id, channel_id):
            return self._find_units(
                app_id, channel_id, start_time, until_time, entity_type,
                entity_id, names, target_entity_type, target_entity_id,
                want, reversed)
        n_names = 0 if names is None else len(names)
        name_arr = ((ctypes.c_uint64 * n_names)(*map(_h, names))
                    if n_names else None)
        # the target-entity predicates are not in the native header, so the
        # C-side limit can only apply when they are absent
        post_filter = target_entity_type is not UNSET or \
            target_entity_id is not UNSET
        c_limit = -1 if post_filter else want

        # hold the client lock only across the native query and the raw
        # payload copies (memcpy): remove()/close() take the same lock
        # before freeing the handle, so the handle stays alive, while the
        # expensive JSON parsing below never blocks other DAO operations.
        # The returned iterator (plain list) never touches native state.
        raw: list[bytes] = []
        with self.client.lock:
            h = self._handle(app_id, channel_id)
            lib = self.client.lib
            total = lib.pio_evlog_count(h)
            cap = total if c_limit < 0 else min(total, c_limit)
            out = (ctypes.c_int64 * max(cap, 1))()
            n = lib.pio_evlog_query(
                h,
                _I64_MIN if start_time is None else to_millis(start_time),
                _I64_MAX if until_time is None else to_millis(until_time),
                _h(entity_type) if entity_type is not None else 0,
                _h(entity_id) if entity_id is not None else 0,
                name_arr, n_names, 1 if reversed else 0, c_limit, out, cap,
            )
            if post_filter and want >= 0:
                # limited query whose predicates live only in Python: parse
                # and filter IN-lock so reading stops at `want` matches —
                # copying all candidates first would be O(log size)
                results = self._filter_parsed(
                    (self._read_raw(h, out[i]) for i in range(n)),
                    entity_type, entity_id, names,
                    target_entity_type, target_entity_id, want)
                return iter(results)
            for i in range(n):
                payload = self._read_raw(h, out[i])
                if payload is not None:
                    raw.append(payload)

        # unlimited (or natively limited) queries: the expensive JSON
        # parsing runs outside the lock so other DAO ops are not stalled
        results = self._filter_parsed(
            iter(raw), entity_type, entity_id, names,
            target_entity_type, target_entity_id, want)
        return iter(results)

    def _find_units(self, app_id, channel_id, start_time, until_time,
                    entity_type, entity_id, names, target_entity_type,
                    target_entity_id, want: int, rev: bool):
        """find() over a sharded/tiered layout: one native query per
        segment file, per-unit parse, then a merge on (time, unit
        order). Within a unit the native query's (time, append) order
        is preserved; across units, equal timestamps order by unit
        index — cross-shard append-order ties were never defined (the
        writers race on the wire too)."""
        n_names = 0 if names is None else len(names)
        name_arr = ((ctypes.c_uint64 * n_names)(*map(_h, names))
                    if n_names else None)
        parsed: list = []  # (time_ms, unit_idx, seq, Event)
        with self.client.lock:
            lib = self.client.lib
            for u, (_k, path, _hot) in enumerate(
                    self._unit_paths(app_id, channel_id)):
                h = self.client.handle_path(path)
                total = lib.pio_evlog_count(h)
                out = (ctypes.c_int64 * max(total, 1))()
                m = lib.pio_evlog_query(
                    h,
                    _I64_MIN if start_time is None
                    else to_millis(start_time),
                    _I64_MAX if until_time is None
                    else to_millis(until_time),
                    _h(entity_type) if entity_type is not None else 0,
                    _h(entity_id) if entity_id is not None else 0,
                    name_arr, n_names, 1 if rev else 0, -1, out, total,
                )
                evs = self._filter_parsed(
                    (self._read_raw(h, out[i]) for i in range(m)),
                    entity_type, entity_id, names,
                    target_entity_type, target_entity_id, -1)
                for seq, ev in enumerate(evs):
                    parsed.append((to_millis(ev.event_time), u, seq, ev))
        parsed.sort(key=(lambda t: (-t[0], t[1], t[2])) if rev
                    else (lambda t: (t[0], t[1], t[2])))
        results = [t[3] for t in parsed]
        if want >= 0:
            results = results[:want]
        return iter(results)

    def scan_interactions(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_names: Sequence[str] = ("rate",),
        value_prop: Optional[str] = None,
        event_values: Optional[dict] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        default_value: float = 1.0,
        use_cache: bool = True,
        seed_cache: bool = True,
        stats: Optional[dict] = None,
        shard_sink=None,
    ) -> base.Interactions:
        """Columnar scan, sharded across ``PIO_SCAN_SHARDS`` threads over
        disjoint entry ranges (ctypes releases the GIL; each shard interns
        into a private id table, merged deterministically in shard order —
        the result is byte-identical to a sequential scan for every shard
        count, including ids and row order).

        Locking: the client lock is held only to snapshot the log's
        entry/dead counts and pin the handle; the scan runs with the lock
        RELEASED (the native side holds its own mutex only for a header
        snapshot — eventlog.cc), so concurrent event writes proceed while
        a training scan is in flight. The snapshot end bound keeps rows
        appended mid-scan out of the result, and the snapshot is
        revalidated (dead count unchanged) before it may seed the
        projection cache.

        Stored-value queries (one event name, a ``value_prop``, no fixed
        override) are served from the training-projection cache when one is
        valid (traincache.py): only the log *tail* appended since the cache
        was written is re-scanned, and the merged result is folded back.
        Everything else — and any shape the fold cannot prove equivalent —
        takes the full sharded scan, which then (re)seeds the cache at
        training scale.

        cpplog-specific extras (the bench and the pipelined ingest path;
        other backends ignore them): ``use_cache``/``seed_cache`` bypass
        the projection cache's read/write legs, ``stats`` (a dict) is
        filled with the scan sub-metrics (shard count, per-shard walls,
        native-lock-held wall), and ``shard_sink(k, uidx, iidx, vals,
        times)`` receives each completed shard in shard order — indices
        already remapped into the global id tables — while later shards
        are still scanning (ops/sparse.StreamingPrep consumes this)."""
        from incubator_predictionio_tpu.data.storage import traincache

        names = [str(n) for n in event_names]
        fixed = event_values or {}
        if not self._is_plain(app_id, channel_id):
            return self._scan_interactions_units(
                app_id, channel_id, entity_type, target_entity_type,
                names, fixed, value_prop, default_value, start_time,
                until_time, stats, shard_sink)
        servable = (
            len(names) == 1 and value_prop is not None
            and names[0] not in fixed
        )
        with self.client.lock:
            h = self._handle(app_id, channel_id)
            lib = self.client.lib
            cpath = traincache.path_for(
                self.client._file(self.ns, app_id, channel_id))
            raw = lib.pio_evlog_entry_count(h)
            dead = lib.pio_evlog_dead_count(h)
            pin = self.client.pin(self.ns, app_id, channel_id)
        try:
            if servable and use_cache:
                cache = traincache.load(cpath)
                if cache is not None and (
                        cache.spec.entity_type == entity_type
                        and cache.spec.target_entity_type
                        == target_entity_type
                        and cache.spec.event_name == names[0]
                        and cache.spec.value_prop == value_prop
                        and cache.dead_count == dead
                        and cache.raw_count <= raw):
                    inter = self._serve_from_cache(
                        h, cache, cpath, raw, dead, entity_type,
                        target_entity_type, names[0], value_prop,
                        start_time, until_time, stats=stats)
                    if inter is not None:
                        return inter
            unbounded = start_time is None and until_time is None
            seed = servable and unbounded and seed_cache
            # stats always collect into a dict (the caller's, or our
            # own) so the last full scan's sub-metrics stay readable by
            # the /metrics bridge even for callers that pass none
            stats = {} if stats is None else stats
            inter, times = self._scan_sharded(
                h, raw, start_time, until_time, entity_type,
                target_entity_type, names, fixed, value_prop,
                default_value, stats=stats, shard_sink=shard_sink)
            self._last_scan_stats = stats
            stats.setdefault("scan_source", "scan")
            # times are always non-decreasing here: _merge_shards restores
            # global time order whenever the log held an inversion
            if seed and len(inter) >= traincache.MIN_NNZ:
                self._seed_cache_revalidated(
                    h, cpath, traincache.TrainCache(
                        spec=traincache.Spec(
                            entity_type, target_entity_type,
                            names[0], value_prop),
                        uidx=inter.user_idx, iidx=inter.item_idx,
                        vals=inter.values, times=times,
                        user_tab=inter.user_ids, item_tab=inter.item_ids,
                        raw_count=raw, dead_count=dead),
                    dead,
                    plan=(traincache.plan_path_for(
                        str(cpath)[: -len(".traincache")]), None))
            return inter
        finally:
            self.client.unpin(pin)

    def _scan_interactions_units(self, app_id, channel_id, entity_type,
                                 target_entity_type, names, fixed,
                                 value_prop, default_value, start_time,
                                 until_time, stats, shard_sink):
        """Training scan over a sharded/tiered layout: every segment
        (cold tier before hot, shard order) scans CONCURRENTLY and the
        results merge under the TableMerger discipline — byte-identical
        to the single-writer scan of the same events whenever event
        times are distinct (_merge_shards restores global time order;
        equal-time ties across writer shards order by segment, an order
        a single writer never defined either). The projection cache
        stays plain-layout-only: a sharded training scan always runs
        the full fan-out, which IS the parallel fast path."""
        with self.client.lock:
            snap = self._snapshot_shards_locked(app_id, channel_id)
            pins = self._pin_units_locked(snap)
        try:
            units = []
            for _k, _hot, _gen, segs, _tot in snap:
                for _path, h, cnt in segs:
                    units.append((h, 0, cnt))
            stats = {} if stats is None else stats
            inter, _times = self._scan_units(
                units, start_time, until_time, entity_type,
                target_entity_type, names, fixed, value_prop,
                default_value, stats=stats, shard_sink=shard_sink)
            self._last_scan_stats = stats
            stats.setdefault("scan_source", "scan")
            return inter
        finally:
            for key in pins:
                self.client.unpin(key)

    # -- speed-layer tail cursor -------------------------------------------
    def tail_cursor(self, app_id: int,
                    channel_id: Optional[int] = None) -> int:
        """Monotonic write cursor = (log generation << TAIL_GEN_SHIFT) |
        raw entry count. Compaction/drop renumber entries and bump the
        generation, which read_interactions_since surfaces as a RESET —
        a bare count comparison would miss "compacted, then appended
        past the old count before the next poll".

        Sharded/tiered layouts return a :class:`base.VectorCursor` —
        one component per writer shard, each (generation <<
        TAIL_GEN_SHIFT) | merged (cold + hot) count — whose comparison
        semantics make every overlay/controller predicate behave: any
        component behind reads as "behind", any generation mismatch
        resets."""
        with self.client.lock:
            if self._is_plain(app_id, channel_id):
                h = self._handle(app_id, channel_id)
                path = self.client._file(self.ns, app_id, channel_id)
                gen = self.client._generations.get(str(path), 0)
                count = int(self.client.lib.pio_evlog_entry_count(h))
                # count observation: anchors the freshness bound for a
                # pure READER process (the subscriber calls this at
                # startup)
                self.client.note_count_locked(path, count)
                return (gen << self.TAIL_GEN_SHIFT) | count
            snap = self._snapshot_shards_locked(app_id, channel_id)
            comps = []
            for _k, hot, gen, _segs, total in snap:
                self.client.note_count_locked(hot, total)
                comps.append((gen << self.TAIL_GEN_SHIFT) | total)
            return base.VectorCursor(comps)

    def read_interactions_since(
        self,
        cursor: int,
        app_id: int,
        channel_id: Optional[int] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_names: Sequence[str] = ("rate",),
        value_prop: Optional[str] = None,
        event_values: Optional[dict] = None,
        default_value: float = 1.0,
    ):
        """Tail scan over entries [cursor_pos, entry_count) →
        (Interactions, times, append_ms, new_cursor, reset). Rides the
        bounded-range sharded scan (entry order, lock-free on a pinned
        handle) — the same O(delta) machinery the traincache fold uses,
        so polling the tail costs the tail, not the log. A cursor minted
        before a compaction/drop (generation mismatch) returns an EMPTY
        tail with ``reset=True`` — the subscriber resynchronizes.

        Append stamps resolve from the client's python-side COUNT
        observations at BATCH granularity (the native record has no
        append-wall column): every row in this tail read carries the
        newest observed wall at which the log still held <= cursor
        entries, so a row's age is conservatively OVERSTATED — by at
        most one append batch when this process wrote the events, and by
        at most one poll interval when another process did (each tail
        read records its own observation, so a pure reader bounds the
        next delta by its poll cadence). Entries that predate every
        observation (a log written before the subscriber's first look)
        report -1 and drop out of the freshness trace."""
        import numpy as np

        names = [str(n) for n in event_names]
        fixed = event_values or {}
        if not self._is_plain(app_id, channel_id):
            return self._read_tail_units(
                cursor, app_id, channel_id, entity_type,
                target_entity_type, names, fixed, value_prop,
                default_value)
        gen_mask = (1 << self.TAIL_GEN_SHIFT) - 1
        with self.client.lock:
            h = self._handle(app_id, channel_id)
            path = self.client._file(self.ns, app_id, channel_id)
            gen = self.client._generations.get(str(path), 0)
            raw = int(self.client.lib.pio_evlog_entry_count(h))
            pin = self.client.pin(self.ns, app_id, channel_id)
        try:
            new_cursor = (gen << self.TAIL_GEN_SHIFT) | raw
            cur = max(int(cursor), 0)
            cur_gen, lo = cur >> self.TAIL_GEN_SHIFT, cur & gen_mask
            reset = cur_gen != gen or lo > raw
            if reset or raw <= lo:
                with self.client.lock:
                    if not reset:
                        self.client.note_count_locked(path, raw)
                empty = base.Interactions(
                    user_idx=np.empty(0, np.int32),
                    item_idx=np.empty(0, np.int32),
                    values=np.empty(0, np.float32),
                    user_ids=base.IdTable(b"", np.zeros(1, np.int64)),
                    item_ids=base.IdTable(b"", np.zeros(1, np.int64)))
                return (empty, np.empty(0, np.int64),
                        np.empty(0, np.int64), new_cursor, reset)
            with self.client.lock:
                append_wall = self.client.append_wall_since_locked(
                    path, lo)
                # this read's own observation bounds the NEXT delta
                self.client.note_count_locked(path, raw)
            # tail reads book their scan sub-metrics too (scan_source
            # "tail"): between retrains the controller's staleness
            # inputs come from exactly these polls, so /metrics must
            # not freeze at the last FULL scan's numbers
            stats: dict = {}
            inter, times = self._scan_sharded(
                h, raw, None, None, entity_type, target_entity_type,
                names, fixed, value_prop, default_value,
                min_entry_idx=lo, stats=stats)
            stats["scan_source"] = "tail"
            self._last_scan_stats = stats
            append_ms = np.full(len(inter), append_wall, np.int64)
            return inter, times, append_ms, new_cursor, False
        finally:
            self.client.unpin(pin)

    def _read_tail_units(self, cursor, app_id, channel_id, entity_type,
                         target_entity_type, names, fixed, value_prop,
                         default_value):
        """Vector-cursor tail read for sharded/tiered layouts: one
        cursor component per writer shard, each (gen << SHIFT) | merged
        (cold + hot) count. Any component's generation mismatch — or a
        scalar/mis-shaped cursor, e.g. one minted before the layout
        changed — resets the WHOLE tail (the merged stream renumbers).
        Append stamps take the MIN over the contributing shards'
        observations: ages stay conservatively overstated, exactly the
        base.py contract."""
        import numpy as np

        gen_mask = (1 << self.TAIL_GEN_SHIFT) - 1
        with self.client.lock:
            snap = self._snapshot_shards_locked(app_id, channel_id)
            pins = self._pin_units_locked(snap)
        try:
            new_cursor = base.VectorCursor(
                (gen << self.TAIL_GEN_SHIFT) | total
                for _k, _hot, gen, _segs, total in snap)
            comps = None
            if isinstance(cursor, (tuple, list)) \
                    and len(cursor) == len(snap):
                comps = [max(int(c), 0) for c in cursor]
            reset = comps is None
            units = []
            if not reset:
                for (_k, _hot, gen, segs, total), comp in zip(snap,
                                                              comps):
                    cgen = comp >> self.TAIL_GEN_SHIFT
                    lo = comp & gen_mask
                    if cgen != gen or lo > total:
                        reset = True
                        break
                    # map the shard-merged lo across its cold/hot split
                    off = 0
                    for _path, h, cnt in segs:
                        seg_lo = min(max(lo - off, 0), cnt)
                        if seg_lo < cnt:
                            units.append((h, seg_lo, cnt))
                        off += cnt
            if reset or not units:
                with self.client.lock:
                    if not reset:
                        for _k, hot, _gen, _segs, total in snap:
                            self.client.note_count_locked(hot, total)
                empty = base.Interactions(
                    user_idx=np.empty(0, np.int32),
                    item_idx=np.empty(0, np.int32),
                    values=np.empty(0, np.float32),
                    user_ids=base.IdTable(b"", np.zeros(1, np.int64)),
                    item_ids=base.IdTable(b"", np.zeros(1, np.int64)))
                return (empty, np.empty(0, np.int64),
                        np.empty(0, np.int64), new_cursor, reset)
            with self.client.lock:
                walls = []
                for (_k, hot, _gen, _segs, total), comp in zip(snap,
                                                               comps):
                    lo = comp & gen_mask
                    if total > lo:  # this shard contributes rows
                        walls.append(
                            self.client.append_wall_since_locked(hot,
                                                                 lo))
                    self.client.note_count_locked(hot, total)
                append_wall = (-1 if not walls or min(walls) < 0
                               else min(walls))
            stats: dict = {}
            inter, times = self._scan_units(
                units, None, None, entity_type, target_entity_type,
                names, fixed, value_prop, default_value, stats=stats)
            stats["scan_source"] = "tail"
            self._last_scan_stats = stats
            append_ms = np.full(len(inter), append_wall, np.int64)
            return inter, times, append_ms, new_cursor, False
        finally:
            for key in pins:
                self.client.unpin(key)

    def _seed_cache_revalidated(self, h, cpath, cache, dead: int,
                                plan=None) -> None:
        """Publish a projection cache built from a lock-free scan: the
        (potentially hundreds-of-MB) file is serialized OUTSIDE the
        client lock; only the snapshot revalidation + atomic rename run
        under it. Commits only while the dead count still matches the
        scan's snapshot — a delete that landed during the scan may have
        killed rows the result still carries, and a cache seeded from it
        would serve stale rows later.

        ``plan``: optional ``(plan_path, (user_degrees, item_degrees) |
        None)`` — the prep-plan sidecar published (or recomputed) next to
        the cache, keyed to the same snapshot, so the next training prep
        skips its degree pass (O(delta) steady-state retrain)."""
        import numpy as np

        from incubator_predictionio_tpu.data.storage import traincache

        staged = traincache.stage(cpath, cache)
        committed = False
        try:
            with self.client.lock:
                if self.client.lib.pio_evlog_dead_count(h) == dead:
                    staged.commit()
                    committed = True
        finally:
            if not committed:
                staged.abort()
        if committed and plan is not None:
            ppath, degrees = plan
            if degrees is None:
                degrees = (
                    np.bincount(cache.uidx, minlength=len(cache.user_tab)
                                ).astype(np.int64),
                    np.bincount(cache.iidx, minlength=len(cache.item_tab)
                                ).astype(np.int64))
            try:
                traincache.save_plan(ppath, cache.spec, cache.raw_count,
                                     cache.dead_count, *degrees)
            except OSError:
                logger.exception("prep-plan sidecar write failed")

    @staticmethod
    def _resolve_shards(span: int) -> int:
        """Shard count for a scan over ``span`` entries. PIO_SCAN_SHARDS
        is read per call (tests and operators override at runtime): an
        explicit positive value is honored exactly; unset/0 = auto —
        min(usable cores, 8), with no sharding below
        _MIN_SCAN_ENTRIES_PER_SHARD entries per shard (thread spawn and
        merge overhead dwarfs tiny scans)."""
        import os

        if span <= 1:
            return 1
        try:
            n = int(os.environ.get("PIO_SCAN_SHARDS", "0"))
        except ValueError:
            n = 0
        if n <= 0:
            try:
                cores = len(os.sched_getaffinity(0))
            except (AttributeError, OSError):
                cores = os.cpu_count() or 1
            n = min(max(cores, 1), 8,
                    max(span // _MIN_SCAN_ENTRIES_PER_SHARD, 1))
        return max(1, min(n, span))

    def _scan_sharded(self, h, hi_entry, start_time, until_time,
                      entity_type, target_entity_type, names, fixed,
                      value_prop, default_value, min_entry_idx: int = 0,
                      stats: Optional[dict] = None, shard_sink=None):
        """Fan the native scan out over disjoint entry ranges of
        [min_entry_idx, hi_entry) → (Interactions, times).

        Each shard scans in ENTRY order with a private id table; shards
        are merged in shard order (traincache.TableMerger — global
        first-seen interning), then global time order is restored with
        one stable sort, which reproduces the sequential scan's
        (time, append-order) output exactly; already-ordered logs (every
        bulk import) skip the sort. Caller must hold the client lock or
        have pinned the handle; the native calls themselves hold the log
        mutex only for their header snapshots, so shards really run in
        parallel and writers are never stalled."""
        import time as _time

        from concurrent.futures import ThreadPoolExecutor

        lo = max(int(min_entry_idx), 0)
        span = max(int(hi_entry) - lo, 0)
        shards = self._resolve_shards(span)
        bounds = [lo + (span * k) // shards for k in range(shards + 1)]
        bounds[-1] = int(hi_entry)
        t_all0 = _time.perf_counter()

        def run(k: int):
            t0 = _time.perf_counter()
            out = self._scan_native(
                h, start_time, until_time, entity_type,
                target_entity_type, names, fixed, value_prop,
                default_value, min_entry_idx=bounds[k],
                max_entry_idx=bounds[k + 1], with_times=True,
                n_threads=1 if shards > 1 else 0)
            return out, _time.perf_counter() - t0

        if shards == 1:
            shard_results = [run(0)]
        else:
            with ThreadPoolExecutor(max_workers=shards) as pool:
                futs = [pool.submit(run, k) for k in range(shards)]
                # in-order merge: shard k's table merge must follow
                # shards 0..k-1 (first-seen determinism), so results are
                # consumed in shard order — completed early shards merge
                # on this thread while later shards are still scanning
                shard_results = iter(f.result() for f in futs)
                return self._merge_shards(
                    shard_results, shards, t_all0, stats, shard_sink)
        return self._merge_shards(iter(shard_results), shards, t_all0,
                                  stats, shard_sink)

    def _merge_shards(self, shard_results, shards, t_all0, stats,
                      shard_sink):
        import time as _time

        import numpy as np

        from incubator_predictionio_tpu.data.storage import traincache

        umerge, imerge = traincache.TableMerger(), traincache.TableMerger()
        u_parts, i_parts, v_parts, t_parts = [], [], [], []
        first_tabs = None
        walls: list = []
        merge_wall = 0.0
        lock_ns = 0
        k = 0
        for (s_inter, s_times, s_lock_ns), wall in shard_results:
            t0 = _time.perf_counter()
            uremap = umerge.add(s_inter.user_ids)
            iremap = imerge.add(s_inter.item_ids)
            uidx, iidx = s_inter.user_idx, s_inter.item_idx
            if k > 0:  # shard 0's remap is the identity by construction
                uidx, iidx = uremap[uidx], iremap[iidx]
            else:
                first_tabs = (s_inter.user_ids, s_inter.item_ids)
            u_parts.append(uidx)
            i_parts.append(iidx)
            v_parts.append(s_inter.values)
            t_parts.append(s_times)
            if shard_sink is not None:
                shard_sink(k, uidx, iidx, s_inter.values, s_times)
            merge_wall += _time.perf_counter() - t0
            walls.append(wall)
            lock_ns += s_lock_ns
            k += 1
        if len(u_parts) == 1:
            uidx, iidx = u_parts[0], i_parts[0]
            vals, times = v_parts[0], t_parts[0]
            utab, itab = first_tabs
        else:
            uidx = np.concatenate(u_parts)
            iidx = np.concatenate(i_parts)
            vals = np.concatenate(v_parts)
            times = np.concatenate(t_parts)
            utab, itab = umerge.table(), imerge.table()
        reordered = False
        if len(times) > 1 and np.any(np.diff(times) < 0):
            order = np.argsort(times, kind="stable")
            uidx, iidx = uidx[order], iidx[order]
            vals, times = vals[order], times[order]
            # first-seen interning must follow the REORDERED row sequence
            uidx, utab = traincache.first_seen_reindex(uidx, utab)
            iidx, itab = traincache.first_seen_reindex(iidx, itab)
            reordered = True
        if stats is not None:
            stats.update({
                "scan_shards": shards,
                "scan_shard_walls_s": [round(w, 3) for w in walls],
                "scan_lock_held_s": round(lock_ns / 1e9, 6),
                "scan_merge_wall_s": round(merge_wall, 3),
                "scan_wall_s": round(_time.perf_counter() - t_all0, 3),
                "scan_reordered": reordered,
                "scan_rows": int(len(vals)),
            })
        inter = base.Interactions(
            user_idx=uidx, item_idx=iidx, values=vals,
            user_ids=utab, item_ids=itab,
        )
        return inter, times

    def _scan_native(self, h, start_time, until_time, entity_type,
                     target_entity_type, names, fixed, value_prop,
                     default_value, min_entry_idx: int = 0,
                     max_entry_idx: int = -1, with_times: bool = False,
                     n_threads: int = 0):
        """One native scan call → (Interactions, times|None, lock_ns).
        Caller must hold the client lock or have pinned the handle (the
        native call itself locks the log mutex only for its snapshot).
        ``max_entry_idx >= 0`` bounds the entry range and switches the
        output to ENTRY order (see eventlog.cc); -1 keeps the historical
        time order through the end of the log."""
        import numpy as np

        lib = self.client.lib
        c_names = (ctypes.c_char_p * max(len(names), 1))(
            *[n.encode("utf-8") for n in names] or [None])
        c_fixed = (ctypes.c_double * max(len(names), 1))(
            *[float(fixed.get(n, float("nan"))) for n in names] or [0.0])
        res = lib.pio_evlog_scan_interactions(
            h,
            _I64_MIN if start_time is None else to_millis(start_time),
            _I64_MAX if until_time is None else to_millis(until_time),
            min_entry_idx, max_entry_idx,
            entity_type.encode("utf-8"),
            target_entity_type.encode("utf-8"),
            c_names, c_fixed, len(names),
            None if value_prop is None else value_prop.encode("utf-8"),
            float(default_value), n_threads,
        )
        try:
            nnz = lib.pio_scan_nnz(res)
            lock_ns = int(lib.pio_scan_lock_held_ns(res))
            uidx = np.empty(nnz, np.int32)
            iidx = np.empty(nnz, np.int32)
            vals = np.empty(nnz, np.float32)
            times = np.empty(nnz, np.int64) if with_times else None
            if nnz:
                lib.pio_scan_fill(
                    res,
                    uidx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    iidx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                )
                if with_times:
                    lib.pio_scan_fill_times(
                        res,
                        times.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            user_ids = self._scan_ids(res, 0)
            item_ids = self._scan_ids(res, 1)
        finally:
            lib.pio_scan_free(res)
        inter = base.Interactions(
            user_idx=uidx, item_idx=iidx, values=vals,
            user_ids=user_ids, item_ids=item_ids,
        )
        return inter, times, lock_ns

    def _serve_from_cache(self, h, cache, cpath, raw, dead, entity_type,
                          target_entity_type, name, value_prop,
                          start_time, until_time, stats=None):
        """Tail-scan + merge + time-filter; None → caller full-scans.
        Caller has validated the cache and PINNED the handle (the client
        lock is NOT held — the tail scan runs lock-free; the fold write
        revalidates the snapshot under the lock).

        ``stats`` gains the continuation-retrain telemetry:
        ``scan_source`` ("cache"), ``scan_tail_rows`` (the event delta —
        also exported as the ``pio_retrain_delta_rows`` gauge) and the
        per-side degree histograms (``plan_user_degrees`` /
        ``plan_item_degrees``) maintained O(delta) through the prep-plan
        sidecar so training prep can skip its degree pass."""
        import dataclasses

        import numpy as np

        from incubator_predictionio_tpu.data.storage import traincache

        # the plan sidecar sits next to the cache: <log>.prepplan. Only
        # unbounded scans can use (or maintain) it — a time-filtered
        # query's degrees would describe the wrong row set, so it must
        # not pay the sidecar read at all
        unbounded = start_time is None and until_time is None
        ppath = traincache.plan_path_for(
            str(cpath)[: -len(".traincache")])
        plan = (traincache.load_plan(
            ppath, cache.spec, cache.raw_count, cache.dead_count)
            if unbounded else None)
        tail_rows = 0
        if raw > cache.raw_count:
            # records appended since the cache was written: scan just
            # them — bounded at the snapshot count so rows appended
            # mid-scan stay in the tail for the next fold
            tail, tail_times = self._scan_sharded(
                h, raw, None, None, entity_type, target_entity_type,
                [name], {}, value_prop, 1.0,
                min_entry_idx=cache.raw_count)
            if len(tail):
                if len(cache) and tail_times[0] < cache.times[-1]:
                    return None  # out-of-order tail: merge would reorder
                utab, uremap = traincache.merge_tables(
                    cache.user_tab, tail.user_ids)
                itab, iremap = traincache.merge_tables(
                    cache.item_tab, tail.item_ids)
                tail_u, tail_i = uremap[tail.user_idx], iremap[tail.item_idx]
                tail_rows = len(tail)
                cache = dataclasses.replace(
                    cache,
                    uidx=np.concatenate([cache.uidx, tail_u]),
                    iidx=np.concatenate([cache.iidx, tail_i]),
                    vals=np.concatenate([cache.vals, tail.values]),
                    times=np.concatenate([cache.times, tail_times]),
                    user_tab=utab, item_tab=itab,
                    raw_count=raw, dead_count=dead)
                if plan is not None:
                    # O(delta) plan maintenance: pad the histograms to
                    # the merged table sizes, add the tail's counts
                    ud = np.zeros(len(utab), np.int64)
                    ud[:len(plan[0])] = plan[0]
                    id_ = np.zeros(len(itab), np.int64)
                    id_[:len(plan[1])] = plan[1]
                    ud += np.bincount(tail_u, minlength=len(utab))
                    id_ += np.bincount(tail_i, minlength=len(itab))
                    plan = (ud, id_)
                if len(tail) * 100 >= len(cache):
                    # persist the fold only when the tail is ≥1% of the
                    # cache: smaller tails re-scan in microseconds, while
                    # the rewrite is O(cache) disk traffic per train.
                    # A missing plan bootstraps HERE (one O(n) bincount)
                    # so the sidecar write happens exactly once
                    if plan is None and unbounded:
                        plan = (np.bincount(
                                    cache.uidx,
                                    minlength=len(cache.user_tab)
                                ).astype(np.int64),
                                np.bincount(
                                    cache.iidx,
                                    minlength=len(cache.item_tab)
                                ).astype(np.int64))
                    self._seed_cache_revalidated(h, cpath, cache, dead,
                                                 plan=(ppath, plan))
            # empty tail: skip the rewrite — re-checking the tail is a
            # cheap header walk, rewriting the cache is not
        if stats is not None and unbounded:
            stats["scan_source"] = "cache"
            stats["scan_tail_rows"] = int(tail_rows)
            stats["scan_rows"] = int(len(cache))
            if plan is None:
                # bootstrap: one O(n) bincount now buys O(delta) forever
                plan = (np.bincount(cache.uidx,
                                    minlength=len(cache.user_tab)
                                    ).astype(np.int64),
                        np.bincount(cache.iidx,
                                    minlength=len(cache.item_tab)
                                    ).astype(np.int64))
                if tail_rows == 0:
                    # only key the sidecar to a snapshot that is actually
                    # on disk — an unpersisted fold's key would never
                    # match the next scan's cache load (the persisted
                    # fold saved its plan above)
                    try:
                        traincache.save_plan(ppath, cache.spec,
                                             cache.raw_count,
                                             cache.dead_count, *plan)
                    except OSError:
                        logger.exception(
                            "prep-plan bootstrap write failed")
            stats["plan_user_degrees"] = plan[0]
            stats["plan_item_degrees"] = plan[1]
            self._export_retrain_delta(tail_rows)
        if start_time is None and until_time is None:
            return base.Interactions(
                user_idx=cache.uidx, item_idx=cache.iidx, values=cache.vals,
                user_ids=cache.user_tab, item_ids=cache.item_tab)
        lo = _I64_MIN if start_time is None else to_millis(start_time)
        hi = _I64_MAX if until_time is None else to_millis(until_time)
        keep = (cache.times >= lo) & (cache.times < hi)
        uidx, utab = traincache.first_seen_reindex(
            cache.uidx[keep], cache.user_tab)
        iidx, itab = traincache.first_seen_reindex(
            cache.iidx[keep], cache.item_tab)
        return base.Interactions(
            user_idx=uidx, item_idx=iidx, values=cache.vals[keep],
            user_ids=utab, item_ids=itab)

    def _scan_ids(self, res: int, which: int) -> base.IdTable:
        """Copy the C++ id table out as an arrow-style IdTable — offsets +
        byte blob flow through as numpy/bytes, no per-id Python strings
        until serving translation (eventlog.cc pio_scan_copy_ids)."""
        import numpy as np

        lib = self.client.lib
        n = lib.pio_scan_n_ids(res, which)
        nbytes = int(lib.pio_scan_ids_bytes(res, which))
        buf = ctypes.create_string_buffer(max(nbytes, 1))
        offs = np.empty(n + 1, np.int64)
        lib.pio_scan_copy_ids(
            res, which, buf,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return base.IdTable(buf.raw[:nbytes], offs)

    def insert_interactions(
        self,
        inter: base.Interactions,
        app_id: int,
        channel_id: Optional[int] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_name: str = "rate",
        value_prop: str = "rating",
        times: Optional[Any] = None,
    ) -> list:
        """Columnar insert that RETURNS the stored event ids — the REST
        batch route's doc-level fast path (no per-event Python objects
        anywhere between the wire and the log). Ids come from the shared
        seed formula (:meth:`_derive_event_ids`).

        Group-committed: concurrent callers enqueue their prepped batch,
        and whichever thread holds the client lock drains the queue and
        appends every compatible pending batch as one native call (ids
        sliced per caller from one seed run). Within a caller's batch,
        log order is preserved; across concurrent callers, order was
        never defined (they race on the wire too)."""
        n = len(inter)
        if n == 0:
            return []
        prep = self._prep_columnar(inter, times)
        key = (app_id, channel_id, entity_type, target_entity_type,
               event_name, value_prop)
        item = _PendingInsert(key, n, *prep)
        with self._gc_mu:
            self._gc_pending.append(item)
        if not item.done.is_set():
            with self.client.lock:
                with self._gc_mu:
                    batch, self._gc_pending = self._gc_pending, []
                if batch:
                    self._commit_pending_locked(batch)
        item.done.wait()
        if item.error is _RETRY_SOLO:
            # a merged append hit the sidecar limits (rc=-2, nothing
            # written): one oversized sub-batch poisons the whole merge,
            # so each caller retries alone — clean batches land, the
            # offending one raises (and the server falls back to the
            # generic per-event path, exactly the un-merged semantics)
            return self._insert_interactions_direct(key, n, *prep)
        if item.error is not None:
            raise item.error
        return item.ids

    def group_commit_stats(self) -> dict:
        """Coalescing counters for /stats.json: events-per-append is the
        amortization the group commit actually achieved."""
        with self._gc_mu:
            appends = self._gc_appends
            return {
                # counters are backend-global and never rotate — NOT the
                # per-app hourly window the surrounding stats use
                "scope": "all apps/channels, since server start",
                "appends": appends,
                "callerBatches": self._gc_caller_batches,
                "events": self._gc_events,
                "maxMergedEvents": self._gc_max_merge,
                "meanEventsPerAppend": (
                    round(self._gc_events / appends, 1) if appends else 0.0),
            }

    def _insert_interactions_direct(self, key, n, times_arr, uidx, iidx,
                                    vals, utab, itab) -> list:
        """Single un-grouped columnar insert (the group-commit retry
        leg). Same observable behavior as a lone insert_interactions."""
        import secrets

        seed = int.from_bytes(secrets.token_bytes(8), "little")
        with self.client.lock:
            rc, ids = self._append_columnar_any(
                key, n, times_arr, uidx, iidx, vals, utab, itab, seed)
        if rc == -2:
            raise base.StorageError(
                "batch exceeds the native sidecar limits (id/field too "
                "long or non-finite value)")
        if rc != n:
            raise base.StorageError("columnar interaction import failed")
        return ids

    def _commit_pending_locked(self, batch: list) -> None:
        """Leader leg of the group commit: append every drained batch,
        merging batches that share the scalar field tuple. Caller holds
        the client lock. Every item's ``done`` event is set on every
        path — a stranded waiter would hang a server thread forever."""
        import secrets

        groups: dict = {}
        for it in batch:
            groups.setdefault(it.key, []).append(it)
        for key, items in groups.items():
            try:
                if len(items) == 1:
                    it = items[0]
                    n, merged = it.n, (it.times, it.uidx, it.iidx,
                                       it.vals, it.utab, it.itab)
                else:
                    n, merged = self._merge_pending(items)
                seed = int.from_bytes(secrets.token_bytes(8), "little")
                rc, ids = self._append_columnar_any(key, n, *merged,
                                                    seed=seed)
                if rc == n:
                    with self._gc_mu:
                        self._gc_appends += 1
                        self._gc_caller_batches += len(items)
                        self._gc_events += n
                        self._gc_max_merge = max(self._gc_max_merge, n)
                    off = 0
                    for it in items:
                        it.ids = ids[off:off + it.n]
                        off += it.n
                elif rc == -2:
                    if len(items) == 1:
                        items[0].error = base.StorageError(
                            "batch exceeds the native sidecar limits "
                            "(id/field too long or non-finite value)")
                    else:
                        for it in items:
                            it.error = _RETRY_SOLO
                else:
                    err = base.StorageError(
                        "columnar interaction import failed")
                    for it in items:
                        it.error = err
            except Exception as e:  # noqa: BLE001 — must reach waiters
                for it in items:
                    if it.ids is None and it.error is None:
                        it.error = e
            finally:
                for it in items:
                    it.done.set()

    @staticmethod
    def _merge_pending(items: list):
        """Concatenate pending batches into one columnar append: id
        tables are concatenated (duplicates across sub-batches are fine —
        the table is a lookup blob, not a unique index) and each
        sub-batch's dense indices are shifted by the entries before it."""
        import numpy as np

        from incubator_predictionio_tpu.utils.times import now_utc

        times_parts, uidx_parts, iidx_parts, vals_parts = [], [], [], []
        ublobs, iblobs = [], []
        uoffs_parts = [np.zeros(1, np.int64)]
        ioffs_parts = [np.zeros(1, np.int64)]
        u_entries = u_bytes = i_entries = i_bytes = 0
        # one shared 'now' + a running offset for implicit-time sub-batches:
        # per-sub-batch now() stamps can repeat within a millisecond, and a
        # backward jump at a merge seam would dirty the native sorted index
        # and defeat incremental projection maintenance — under exactly the
        # concurrent load group commit exists for
        now_ms = None
        impl_off = 0
        for it in items:
            t = it.times
            if t is None:
                if now_ms is None:
                    now_ms = to_millis(now_utc())
                t = now_ms + impl_off + np.arange(it.n, dtype=np.int64)
                impl_off += it.n
            times_parts.append(t)
            uidx_parts.append(it.uidx + np.int32(u_entries))
            iidx_parts.append(it.iidx + np.int32(i_entries))
            vals_parts.append(it.vals)
            uoffs_parts.append(it.utab.offsets[1:] + u_bytes)
            ioffs_parts.append(it.itab.offsets[1:] + i_bytes)
            ublobs.append(it.utab.blob)
            iblobs.append(it.itab.blob)
            u_entries += len(it.utab)
            u_bytes += len(it.utab.blob)
            i_entries += len(it.itab)
            i_bytes += len(it.itab.blob)
        n = sum(it.n for it in items)
        return n, (
            np.concatenate(times_parts),
            np.concatenate(uidx_parts),
            np.concatenate(iidx_parts),
            np.concatenate(vals_parts),
            base.IdTable(b"".join(ublobs), np.concatenate(uoffs_parts)),
            base.IdTable(b"".join(iblobs), np.concatenate(ioffs_parts)),
        )

    def _prep_columnar(self, inter: base.Interactions, times,
                       base_time: Optional[datetime] = None):
        """Validate + coerce one columnar batch to the native append's
        array layout. ``times_arr`` stays None when neither explicit
        times nor a base_time were given — the commit leg stamps 'now'
        then, so a batch queued behind a slow group commit is stamped at
        write time, not enqueue time."""
        import numpy as np

        n = len(inter)
        if times is None:
            if base_time is None:
                times_arr = None
            else:
                times_arr = to_millis(base_time) + np.arange(n,
                                                             dtype=np.int64)
        else:
            times_arr = np.ascontiguousarray(times, np.int64)
            if times_arr.shape != (n,):
                raise ValueError(
                    f"times must have shape ({n},), got {times_arr.shape}")
        uidx = np.ascontiguousarray(inter.user_idx, np.int32)
        iidx = np.ascontiguousarray(inter.item_idx, np.int32)
        vals = np.ascontiguousarray(inter.values, np.float32)
        if iidx.shape != (n,) or vals.shape != (n,):
            raise ValueError(
                "user_idx/item_idx/values must all have shape "
                f"({n},), got {iidx.shape} / {vals.shape}")
        utab = (inter.user_ids if isinstance(inter.user_ids, base.IdTable)
                else base.IdTable.from_list(inter.user_ids))
        itab = (inter.item_ids if isinstance(inter.item_ids, base.IdTable)
                else base.IdTable.from_list(inter.item_ids))
        return times_arr, uidx, iidx, vals, utab, itab

    def _append_columnar_locked(self, key, n, times_arr, uidx, iidx, vals,
                                utab, itab, seed: int) -> int:
        """One native columnar append + training-projection maintenance.
        Caller holds the client lock. Returns the native rc (n on
        success, -2 when the sidecar limits reject the batch — nothing
        written in that case; eventlog.cc append_interactions is
        all-or-nothing)."""
        import numpy as np

        from incubator_predictionio_tpu.utils.times import now_utc

        (app_id, channel_id, entity_type, target_entity_type,
         event_name, value_prop) = key
        if times_arr is None:
            times_arr = to_millis(now_utc()) + np.arange(n, dtype=np.int64)
        uoffs = np.ascontiguousarray(utab.offsets, np.int64)
        ioffs = np.ascontiguousarray(itab.offsets, np.int64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        h = self._handle(app_id, channel_id)
        raw_before = self.client.lib.pio_evlog_entry_count(h)
        dead_before = self.client.lib.pio_evlog_dead_count(h)
        rc = self.client.lib.pio_evlog_append_interactions(
            h, n,
            times_arr.ctypes.data_as(i64p),
            uidx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            iidx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            utab.blob, uoffs.ctypes.data_as(i64p), len(utab),
            itab.blob, ioffs.ctypes.data_as(i64p), len(itab),
            entity_type.encode("utf-8"),
            target_entity_type.encode("utf-8"),
            event_name.encode("utf-8"),
            value_prop.encode("utf-8"),
            # the seed makes the generated event ids (and so the log
            # bytes) reproducible — for deterministic re-imports and
            # the thread-count byte-identity test
            seed,
        )
        if rc == n:
            path = self.client._file(self.ns, app_id, channel_id)
            self.client.note_count_locked(path, raw_before)
            self.client.note_count_locked(path, raw_before + n)
            try:
                self._maintain_cache_after_import(
                    h, app_id, channel_id, raw_before, dead_before,
                    uidx, iidx, vals, times_arr, utab, itab,
                    entity_type, target_entity_type, event_name,
                    value_prop)
            except Exception:
                # the append already succeeded durably; the projection
                # is an optimization the next scan rebuilds — raising
                # here would make callers believe nothing was written
                # (and retry-writers would then DUPLICATE the batch)
                logger.exception(
                    "training-projection maintenance failed after a "
                    "successful import (next scan rebuilds it)")
        return rc

    @staticmethod
    def _columnar_rejected(key, n, uidx, iidx, vals, utab, itab) -> bool:
        """True when the native columnar append would return -2 —
        mirrors the exact reject conditions of eventlog.cc
        pio_evlog_append_interactions (scalar field lengths, id
        lengths, finite values, index ranges), evaluated BEFORE any
        write so a sharded fan-out stays all-or-nothing across shards
        (a single-file append is natively all-or-nothing; N per-shard
        appends are not, unless nothing can reject mid-flight)."""
        import numpy as np

        (_a, _c, etype, tetype, name, vprop) = key
        if (len(etype.encode("utf-8")) >= 0xFFFF
                or len(tetype.encode("utf-8")) >= 0xFFFF
                or len(name.encode("utf-8")) >= 0xFFFF
                or len(vprop.encode("utf-8")) > 255):
            return True
        for tab in (utab, itab):
            if len(tab) and int(np.diff(tab.offsets).max()) >= 0xFFFF:
                return True
        if n and not np.isfinite(vals).all():
            return True
        if n and (int(uidx.min()) < 0 or int(uidx.max()) >= len(utab)
                  or int(iidx.min()) < 0 or int(iidx.max()) >= len(itab)):
            return True
        return False

    def _append_columnar_any(self, key, n, times_arr, uidx, iidx, vals,
                             utab, itab, seed: int):
        """Columnar append dispatch → (rc, ids | None). Caller holds
        the client lock. The plain layout takes the original
        single-writer path (ids from the shared seed formula); sharded
        layouts spray rows by user-id hash and append to every target
        shard concurrently."""
        app_id, channel_id = key[0], key[1]
        if self._is_plain(app_id, channel_id):
            rc = self._append_columnar_locked(
                key, n, times_arr, uidx, iidx, vals, utab, itab, seed)
            return rc, (self._derive_event_ids(seed, n) if rc == n
                        else None)
        return self._append_columnar_sharded(
            key, n, times_arr, uidx, iidx, vals, utab, itab, seed)

    def _append_columnar_sharded(self, key, n, times_arr, uidx, iidx,
                                 vals, utab, itab, seed: int):
        """Spray one columnar batch across the writer shards and append
        to each target shard CONCURRENTLY — ctypes releases the GIL, so
        the per-shard native appends (hashing + record rendering + the
        buffered write, all in C++) really overlap; this fan-out is the
        multi-writer throughput win the bench measures. Returns
        (rc, ids) with ids in CALLER order (derived per shard from a
        shard-mixed seed). Caller holds the client lock; workers touch
        only pre-resolved handles and per-shard locks (lock order:
        client lock → shard lock, same as replication_apply).

        All-or-nothing: the -2 screen runs up front (mirroring the
        native conditions), so per-shard appends cannot reject
        mid-fan-out; a residual IO failure raises StorageError loudly
        rather than reporting a partial write."""
        from concurrent.futures import ThreadPoolExecutor

        import numpy as np

        from incubator_predictionio_tpu.utils.times import now_utc

        app_id, channel_id = key[0], key[1]
        (_a, _c, etype, tetype, name, vprop) = key
        if self._columnar_rejected(key, n, uidx, iidx, vals, utab, itab):
            return -2, None
        if times_arr is None:
            times_arr = to_millis(now_utc()) + np.arange(n,
                                                         dtype=np.int64)
        nsh = self._nshards(app_id, channel_id)
        row_shard = self._spray(uidx, utab, nsh)
        golden = 0x9E3779B97F4A7C15
        plan = []
        for k in range(nsh):
            rows = np.nonzero(row_shard == k)[0]
            if not len(rows):
                continue
            path = self._hot_path(app_id, channel_id, k)
            seed_k = (seed ^ (golden * (k + 1))) & 0xFFFFFFFFFFFFFFFF
            # handles, locks, and counts resolve HERE, under the client
            # lock — the workers must never take it (they'd deadlock
            # against this thread waiting on their results)
            plan.append((k, rows, path,
                         self.client.handle_path(path),
                         self.client.shard_lock(path), seed_k,
                         (np.ascontiguousarray(times_arr[rows]),
                          np.ascontiguousarray(uidx[rows]),
                          np.ascontiguousarray(iidx[rows]),
                          np.ascontiguousarray(vals[rows]))))
        uoffs = np.ascontiguousarray(utab.offsets, np.int64)
        ioffs = np.ascontiguousarray(itab.offsets, np.int64)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        etype_b = etype.encode("utf-8")
        tetype_b = tetype.encode("utf-8")
        name_b = name.encode("utf-8")
        vprop_b = vprop.encode("utf-8")
        lib = self.client.lib

        def commit(entry):
            _k, rows, _path, h, lk, seed_k, arrs = entry
            t_arr, s_uidx, s_iidx, s_vals = arrs
            with lk:
                return lib.pio_evlog_append_interactions(
                    h, len(rows), t_arr.ctypes.data_as(i64p),
                    s_uidx.ctypes.data_as(i32p),
                    s_iidx.ctypes.data_as(i32p),
                    s_vals.ctypes.data_as(
                        ctypes.POINTER(ctypes.c_float)),
                    utab.blob, uoffs.ctypes.data_as(i64p), len(utab),
                    itab.blob, ioffs.ctypes.data_as(i64p), len(itab),
                    etype_b, tetype_b, name_b, vprop_b, seed_k)

        import os as _os

        if len(plan) == 1 or (_os.cpu_count() or 1) == 1:
            # one target shard — or one core, where fan-out threads can
            # only add scheduling overhead to CPU-bound native renders
            rcs = [commit(entry) for entry in plan]
        else:
            with self.client.lock:  # reentrant: the append path holds it
                pool = self._fanout_pool
                if pool is None or pool._max_workers < len(plan):
                    if pool is not None:
                        pool.shutdown(wait=False)
                    pool = self._fanout_pool = ThreadPoolExecutor(
                        max_workers=max(len(plan), 4),
                        thread_name_prefix="cpplog-fanout")
            rcs = list(pool.map(commit, plan))
        failed = [entry[0] for entry, rc in zip(plan, rcs)
                  if rc != len(entry[1])]
        if failed:
            raise base.StorageError(
                f"sharded columnar append failed on shard(s) {failed} "
                "(pre-screened batch: IO error, not a reject)")
        ids_arr = np.empty(n, dtype=object)
        for (k, rows, path, h, _lk, seed_k, _arrs), rc in zip(plan, rcs):
            end = int(lib.pio_evlog_entry_count(h))
            self.client.note_count_locked(path, end - len(rows))
            self.client.note_count_locked(path, end)
            ids_arr[rows] = self._derive_event_ids(seed_k, len(rows))
        self._book_shard_events(plan)
        self.maybe_roll(app_id, channel_id)
        return n, ids_arr.tolist()

    def _book_shard_events(self, plan) -> None:
        """Per-shard ingest accounting for /metrics
        (pio_ingest_shard_events{shard}): operators watch the spread
        for writer-shard skew (observability.md runbook)."""
        with self._gc_mu:
            for k, rows, *_rest in plan:
                self._shard_events[k] = (
                    self._shard_events.get(k, 0) + len(rows))

    def import_interactions(
        self,
        inter: base.Interactions,
        app_id: int,
        channel_id: Optional[int] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_name: str = "rate",
        value_prop: str = "rating",
        times: Optional[Any] = None,
        base_time: Optional[datetime] = None,
        chunk: int = 20_000,
        id_seed: Optional[int] = None,
    ) -> int:
        """Fully-native columnar bulk import (pio_evlog_append_interactions):
        record rendering (JSON + sidecar + framed headers), hashing, and the
        single buffered write all happen in C++ — no per-event Python
        objects. Falls back to the generic per-Event path when a field
        exceeds the sidecar limits (rc=-2)."""
        import secrets

        n = len(inter)
        if n == 0:
            return 0
        times_arr, uidx, iidx, vals, utab, itab = self._prep_columnar(
            inter, times, base_time)
        key = (app_id, channel_id, entity_type, target_entity_type,
               event_name, value_prop)
        seed = (int.from_bytes(secrets.token_bytes(8), "little")
                if id_seed is None else (id_seed & 0xFFFFFFFFFFFFFFFF))
        with self.client.lock:
            rc, _ids = self._append_columnar_any(
                key, n, times_arr, uidx, iidx, vals, utab, itab, seed)
        if rc == -2:  # sidecar limits exceeded: generic per-Event path
            if id_seed is not None:
                # the generic path generates random event ids — honoring
                # the caller's byte-reproducibility request is impossible,
                # so fail loudly instead of silently losing determinism
                raise base.StorageError(
                    "id_seed requested but the data exceeds the native "
                    "sidecar limits (id/field too long or non-finite "
                    "value); the per-Event fallback cannot produce "
                    "deterministic ids")
            return super().import_interactions(
                inter, app_id, channel_id, entity_type, target_entity_type,
                event_name, value_prop, times, base_time, chunk)
        if rc != n:
            raise base.StorageError("columnar interaction import failed")
        return n

    def _maintain_cache_after_import(self, h, app_id, channel_id,
                                     raw_before, dead_before, uidx, iidx,
                                     vals, times_arr, utab, itab,
                                     entity_type, target_entity_type,
                                     event_name, value_prop) -> None:
        """Create or extend the training projection from the batch's own
        columnar arrays — the import has them in hand, so maintaining the
        projection here is nearly free vs. rebuilding it from a full scan
        (traincache.py rationale). Covered cases: a fresh log at training
        scale (create), or an up-to-date cache with an in-order batch
        (append). Anything else leaves the batch in the log tail, which the
        next scan folds. Caller holds the client lock; the native append
        has already succeeded (raw count is now raw_before + n)."""
        import dataclasses

        import numpy as np

        from incubator_predictionio_tpu.data.storage import traincache

        n = len(uidx)
        if value_prop is None:
            return
        monotone = n < 2 or not np.any(np.diff(times_arr) < 0)
        if not monotone:
            return
        cpath = traincache.path_for(
            self.client._file(self.ns, app_id, channel_id))
        spec = traincache.Spec(entity_type, target_entity_type, event_name,
                               value_prop)
        # re-intern in first-seen order: the batch's tables may hold
        # unreferenced or differently-ordered ids, and the cache must be
        # indistinguishable from a fresh native scan (the cross-backend
        # first-seen contract, tests/test_storage_conformance.py)
        if raw_before == 0 and n >= traincache.MIN_NNZ:
            new_u, new_utab = traincache.first_seen_reindex(uidx, utab)
            new_i, new_itab = traincache.first_seen_reindex(iidx, itab)
            traincache.write(cpath, traincache.TrainCache(
                spec=spec, uidx=new_u, iidx=new_i,
                vals=np.asarray(vals, np.float32),
                times=np.asarray(times_arr, np.int64),
                user_tab=new_utab, item_tab=new_itab,
                raw_count=raw_before + n, dead_count=dead_before))
            return
        cache = traincache.load(cpath)
        if cache is None or cache.spec != spec:
            return
        if cache.raw_count != raw_before or cache.dead_count != dead_before:
            return  # gap or deletes: the next scan's fold handles it
        if n * 20 < len(cache):
            # appending rewrites the whole projection file: a batch below
            # 5% of the cache isn't worth O(cache) disk traffic per
            # import — it stays in the log tail, which scans fold cheaply
            return
        if len(cache) and n and times_arr[0] < cache.times[-1]:
            return  # out-of-order batch: appending would break time order
        new_u, new_utab = traincache.first_seen_reindex(uidx, utab)
        new_i, new_itab = traincache.first_seen_reindex(iidx, itab)
        m_utab, uremap = traincache.merge_tables(cache.user_tab, new_utab)
        m_itab, iremap = traincache.merge_tables(cache.item_tab, new_itab)
        traincache.write(cpath, dataclasses.replace(
            cache,
            uidx=np.concatenate([cache.uidx, uremap[new_u]]),
            iidx=np.concatenate([cache.iidx, iremap[new_i]]),
            vals=np.concatenate([cache.vals, np.asarray(vals, np.float32)]),
            times=np.concatenate([cache.times,
                                  np.asarray(times_arr, np.int64)]),
            user_tab=m_utab, item_tab=m_itab,
            raw_count=raw_before + n, dead_count=dead_before))

    def compact(self, app_id: int,
                channel_id: Optional[int] = None) -> dict:
        """Rewrite the log in the CURRENT on-disk format, keeping only
        live records — the store-migration verb behind ``pio upgrade``
        (the reference migrates HBase schemas via its upgrade tool,
        data/.../storage/hbase/upgrade/Upgrade.scala; here the format
        deltas that have accrued are tombstoned records occupying space
        and pre-sidecar bare-JSON records that every scan must
        JSON-parse).

        Fully native (pio_evlog_compact_copy): live records that already
        carry a sidecar — including compact bulk-imported records —
        byte-copy unchanged, bare-JSON records gain a sidecar built in
        C++ from the span parser, and the copy lands in a temp file that
        atomically replaces the original. No Python Event objects exist
        on this path, ids/times/bytes are preserved exactly, and log
        (append) order survives — the equal-time tie-break contract. The
        training projection is invalidated (entry numbering changes).

        Sharded/tiered layouts compact PER SEGMENT — each cold tier and
        each hot segment rewrites independently (small files, bounded
        pause), with one generation bump per shard so pinned readers
        and speed-overlay cursors resync exactly as on the plain
        layout. Returns ``{"events", "bytes_before", "bytes_after"}``
        aggregated over every segment."""
        import os

        from incubator_predictionio_tpu.data.storage import traincache

        events = bytes_before = bytes_after = 0
        with self.client.lock:
            by_shard: dict[int, list] = {}
            for k, path, _hot in self._unit_paths(app_id, channel_id):
                by_shard.setdefault(k, []).append(path)
            for k, paths in by_shard.items():
                hot = self._hot_path(app_id, channel_id, k)
                for path in paths:
                    # compaction renumbers entries and swaps the handle:
                    # wait out any lock-narrowed scan still reading it
                    self.client._wait_unpinned_locked(str(path))
                    h = self.client.handle_path(path)
                    bytes_before += (path.stat().st_size
                                     if path.exists() else 0)
                    tmp_path = path.with_name(path.name + ".compact")
                    live = self.client.lib.pio_evlog_compact_copy(
                        h, str(tmp_path).encode("utf-8"))
                    if live < 0:
                        tmp_path.unlink(missing_ok=True)
                        raise base.StorageError(
                            f"compaction failed for {path.name}")
                    self.client.close_path_locked(path)
                    os.replace(tmp_path, path)
                    events += int(live)
                    bytes_after += (path.stat().st_size
                                    if path.exists() else 0)
                traincache.invalidate(hot)
                # entry numbering may have changed (tombstones
                # dropped): tail cursors minted before this compaction
                # are now invalid, and replication followers must
                # resync the rewritten segment bytes
                self.client.bump_generation_locked(hot)
                self.client.bump_epoch_locked(hot)
        return {"events": events, "bytes_before": bytes_before,
                "bytes_after": bytes_after}

    def maybe_roll(self, app_id: int, channel_id: Optional[int] = None,
                   limit_bytes: Optional[int] = None) -> int:
        """Segment tiering: seal every hot segment that outgrew the
        limit by folding its LIVE records onto the shard's cold tier
        (via the native compact copy, which also resolves hot-internal
        tombstones — a raw byte concat would carry tombstone target
        indices local to the old hot file) and truncating the hot file
        to empty. The hot segment stays small, so appends and tail
        polls touch a small file and compaction rewrites bounded
        segments instead of one monolith. The cold file is the
        concatenation of sealed hots in seal order, so the shard's
        merged (cold-then-hot) stream keeps its order; the roll still
        BUMPS the shard's generation and rewrite epoch — entry
        numbering changed, cursors resync exactly as on compaction and
        followers resync the shard.

        ``limit_bytes``: explicit threshold; default reads
        ``PIO_LOG_HOT_BYTES`` per call (unset/0 = tiering off — the
        opportunistic call on every sharded append is then a single
        getenv). Returns the number of shards rolled."""
        import os

        from incubator_predictionio_tpu.data.storage import traincache

        if limit_bytes is None:
            try:
                limit_bytes = int(
                    os.environ.get("PIO_LOG_HOT_BYTES", "0"))
            except ValueError:
                limit_bytes = 0
        if limit_bytes <= 0:
            return 0
        rolled = 0
        with self.client.lock:
            for k in range(self._nshards(app_id, channel_id)):
                hot = self._hot_path(app_id, channel_id, k)
                try:
                    if (not hot.exists()
                            or hot.stat().st_size < limit_bytes):
                        continue
                except OSError:
                    continue
                cold = self.client._cold(hot)
                if (self.client._pins.get(str(hot), 0)
                        or self.client._pins.get(str(cold), 0)):
                    # a lock-narrowed scan is reading this shard: the
                    # roll is opportunistic (appends call it inline),
                    # so SKIP rather than stall the append path behind
                    # a training scan — the next append retries
                    continue
                h = self.client.handle_path(hot)
                tmp = hot.with_name(hot.name + ".roll")
                live = self.client.lib.pio_evlog_compact_copy(
                    h, str(tmp).encode("utf-8"))
                if live < 0:
                    tmp.unlink(missing_ok=True)
                    raise base.StorageError(
                        f"segment roll failed for {hot.name}")
                self.client.close_path_locked(hot)
                self.client.close_path_locked(cold)
                with open(cold, "ab") as dst, open(tmp, "rb") as src:
                    import shutil

                    shutil.copyfileobj(src, dst)
                    dst.flush()
                    os.fsync(dst.fileno())
                tmp.unlink(missing_ok=True)
                with open(hot, "r+b") as f:
                    f.truncate(0)
                self.client._has_cold[str(hot)] = True
                traincache.invalidate(hot)
                self.client.bump_generation_locked(hot)
                self.client.bump_epoch_locked(hot)
                rolled += 1
        return rolled

    # -- async replication (leader side + follower apply) -----------------
    def replication_status(self, app_id: int,
                           channel_id: Optional[int] = None) -> dict:
        """Leader-side layout snapshot for a follower's tail loop:
        per-shard generation, rewrite epoch, and per-tier entry counts.
        The epoch is the follower's resync signal — it moves only when
        segment bytes were REWRITTEN (roll/compact/drop/restart), never
        on append-only growth, so deletes replicate as plain frames."""
        with self.client.lock:
            snap = self._snapshot_shards_locked(app_id, channel_id)
            out = []
            for k, hot, gen, segs, total in snap:
                cold_cnt = hot_cnt = 0
                for path, _h, cnt in segs:
                    if str(path) == str(hot):
                        hot_cnt = cnt
                    else:
                        cold_cnt = cnt
                out.append({
                    "shard": k, "gen": gen,
                    "epoch": self.client.epoch_locked(hot),
                    "cold": cold_cnt, "hot": hot_cnt, "total": total,
                })
            return {"shards": len(snap), "status": out}

    def replication_read(self, app_id: int,
                         channel_id: Optional[int] = None,
                         shard: int = 0, tier: str = "hot",
                         from_entry: int = 0, epoch: int = 0,
                         max_bytes: int = 4 << 20) -> dict:
        """Read whole record frames from one segment file for byte-level
        log shipping: the follower's copy stays bit-identical to the
        leader's prefix, so tombstone target indices, sidecars, and
        hashes all carry over. Raises when the segment's rewrite epoch
        moved past the follower's view (stale frames must not land)."""
        with self.client.lock:
            hot = self._hot_path(app_id, channel_id, shard)
            if int(epoch) != self.client.epoch_locked(hot):
                raise base.StorageError(
                    f"replication epoch moved for shard {shard} "
                    "(segment rewritten); resync required")
            path = hot if tier == "hot" else self.client._cold(hot)
            h = self.client.handle_path(path)
            lib = self.client.lib
            cap = max(int(max_bytes), 1 << 16)
            n_out = ctypes.c_int64(0)
            for _attempt in range(2):
                buf = ctypes.create_string_buffer(cap)
                got = lib.pio_evlog_read_frames(
                    h, int(from_entry), cap, buf,
                    ctypes.byref(n_out))
                if got >= 0:
                    return {"epoch": int(epoch),
                            "from_entry": int(from_entry),
                            "n_entries": int(n_out.value),
                            "frames": buf.raw[:got]}
                if got == -1:
                    raise base.StorageError(
                        f"replication read failed for {path.name} at "
                        f"entry {from_entry}")
                cap = -got  # one frame alone exceeds the budget
            raise base.StorageError(
                f"replication frame exceeds retry budget on {path.name}")

    def replication_apply(self, app_id: int,
                          channel_id: Optional[int] = None,
                          shard: int = 0, tier: str = "hot",
                          from_entry: int = 0,
                          frames: bytes = b"") -> int:
        """Follower-side apply: append shipped frames to the local
        segment at exactly ``from_entry``. Idempotent on replay (local
        count already past from_entry → no-op), loud on gaps. Returns
        the local entry count after the apply."""
        with self.client.lock:
            hot = self._hot_path(app_id, channel_id, shard)
            path = hot if tier == "hot" else self.client._cold(hot)
            lk = self.client.shard_lock(path)
            h = self.client.handle_path(path)
            lib = self.client.lib
            with lk:
                local = int(lib.pio_evlog_entry_count(h))
                if local > int(from_entry):
                    return local  # replayed frames: already applied
                if local < int(from_entry):
                    raise base.StorageError(
                        f"replication gap on shard {shard} ({tier}): "
                        f"local count {local} < leader from_entry "
                        f"{from_entry}")
                if not frames:
                    return local
                new_count = lib.pio_evlog_append_frames(
                    h, frames, len(frames))
                if new_count < 0:
                    raise base.StorageError(
                        f"replication apply failed on {path.name}")
            if tier == "cold":
                self.client._has_cold[str(hot)] = True
            else:
                self.client.note_count_locked(hot, int(new_count))
            return int(new_count)

    def replication_configure(self, app_id: int,
                              channel_id: Optional[int] = None,
                              shards: int = 1) -> int:
        """Mirror the leader's writer-shard layout on a follower before
        the first apply."""
        self.client.set_shards(self.ns, app_id, channel_id, int(shards))
        return self._nshards(app_id, channel_id)

    def replication_reset(self, app_id: int,
                          channel_id: Optional[int] = None,
                          shard: int = 0) -> bool:
        """Drop one local shard's segment files (follower resync after
        a leader rewrite-epoch change): cursors minted from this
        follower bump exactly as on a local compaction."""
        from incubator_predictionio_tpu.data.storage import traincache

        with self.client.lock:
            hot = self._hot_path(app_id, channel_id, shard)
            for path in (self.client._cold(hot), hot):
                key = str(path)
                self.client._wait_unpinned_locked(key)
                self.client.close_path_locked(path)
                path.unlink(missing_ok=True)
            self.client._has_cold.pop(str(hot), None)
            traincache.invalidate(hot)
            self.client.bump_generation_locked(hot)
        return True

    @staticmethod
    def _filter_parsed(payloads, entity_type, entity_id, names,
                       target_entity_type, target_entity_id,
                       want: int) -> list[Event]:
        results: list[Event] = []
        for payload in payloads:
            if payload is None:
                continue
            ev = Event.from_jsonable(json.loads(payload.decode("utf-8")))
            # exact re-checks: hashes prune, Python decides
            if entity_type is not None and ev.entity_type != entity_type:
                continue
            if entity_id is not None and ev.entity_id != entity_id:
                continue
            if names is not None and ev.event not in names:
                continue
            if target_entity_type is not UNSET and \
                    ev.target_entity_type != target_entity_type:
                continue
            if target_entity_id is not UNSET and \
                    ev.target_entity_id != target_entity_id:
                continue
            results.append(ev)
            if want >= 0 and len(results) >= want:
                break  # stop reading/parsing as soon as the limit is met
        return results


DATA_OBJECTS = {"Events": CppLogEvents}
