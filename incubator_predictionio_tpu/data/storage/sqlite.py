"""SQLite storage backend — the durable single-box backend.

Parity target: the reference's JDBC driver, which implements the *full*
backend surface (events + all metadata + model blobs) on PostgreSQL/MySQL
(data/.../storage/jdbc/, 1393 LoC: JDBCLEvents, JDBCPEvents, JDBCApps,
JDBCAccessKeys, JDBCChannels, JDBCEngineInstances, JDBCEvaluationInstances,
JDBCModels, JDBCUtils). SQLite gives the same durability contract with zero
service dependencies; the DAO layer is schema-compatible with a Postgres
driver should one be added (SQL here is deliberately generic).

Repository namespaces (``PIO_STORAGE_REPOSITORIES_<REPO>_NAME``) map to an
``ns`` column in every table — the same isolation the reference gets from
per-namespace table names (jdbc/JDBCUtils tableName). Event times are stored
as epoch-millis integers for fast range scans (jdbc/JDBCLEvents.scala:44-66).

Concurrency: one connection per thread for file databases (WAL), one shared
connection for ``:memory:``; ALL statements — reads included — run under the
client lock so no thread observes another's uncommitted transaction on the
shared connection.
"""

from __future__ import annotations

import dataclasses
import json
import sqlite3
import threading
import uuid
from datetime import datetime
from pathlib import Path
from typing import Any, Iterator, Optional, Sequence

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event, new_event_id, validate_event
from incubator_predictionio_tpu.data.storage import base
from incubator_predictionio_tpu.data.storage.base import UNSET
from incubator_predictionio_tpu.utils.times import from_millis, to_millis


class StorageClient(base.BaseStorageClient):
    """One SQLite database file (``:memory:`` supported for tests)."""

    def __init__(self, config: base.StorageClientConfig):
        super().__init__(config)
        path = config.properties.get("PATH", "")
        if not path or path == ":memory:":
            self._path = ":memory:"
        else:
            p = Path(path).expanduser()
            p.parent.mkdir(parents=True, exist_ok=True)
            self._path = str(p)
        self._local = threading.local()
        self._memory_conn: Optional[sqlite3.Connection] = None
        self._all_conns: list[sqlite3.Connection] = []
        self._lock = threading.RLock()
        self._init_schema()

    @property
    def conn(self) -> sqlite3.Connection:
        # ":memory:" must share one connection; files get one per thread.
        if self._path == ":memory:":
            with self._lock:
                if self._memory_conn is None:
                    self._memory_conn = sqlite3.connect(
                        ":memory:", check_same_thread=False
                    )
                return self._memory_conn
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path)
            conn.execute("PRAGMA journal_mode=WAL")
            self._local.conn = conn
            with self._lock:
                self._all_conns.append(conn)
        return conn

    @property
    def lock(self) -> threading.RLock:
        return self._lock

    def _init_schema(self) -> None:
        with self._lock, self.conn as c:
            c.executescript(
                """
                CREATE TABLE IF NOT EXISTS events (
                    ns TEXT NOT NULL,
                    id TEXT NOT NULL,
                    app_id INTEGER NOT NULL,
                    channel_id INTEGER NOT NULL DEFAULT -1,
                    event TEXT NOT NULL,
                    entity_type TEXT NOT NULL,
                    entity_id TEXT NOT NULL,
                    target_entity_type TEXT,
                    target_entity_id TEXT,
                    properties TEXT,
                    event_time INTEGER NOT NULL,
                    event_time_zone TEXT,
                    tags TEXT,
                    pr_id TEXT,
                    creation_time INTEGER NOT NULL,
                    PRIMARY KEY (ns, id, app_id, channel_id)
                );
                CREATE INDEX IF NOT EXISTS idx_events_scan
                    ON events (ns, app_id, channel_id, event_time);
                CREATE TABLE IF NOT EXISTS apps (
                    ns TEXT NOT NULL,
                    id INTEGER NOT NULL,
                    name TEXT NOT NULL,
                    description TEXT,
                    PRIMARY KEY (ns, id),
                    UNIQUE (ns, name)
                );
                CREATE TABLE IF NOT EXISTS access_keys (
                    ns TEXT NOT NULL,
                    key TEXT NOT NULL,
                    app_id INTEGER NOT NULL,
                    events TEXT NOT NULL,
                    PRIMARY KEY (ns, key)
                );
                CREATE TABLE IF NOT EXISTS channels (
                    ns TEXT NOT NULL,
                    id INTEGER NOT NULL,
                    name TEXT NOT NULL,
                    app_id INTEGER NOT NULL,
                    PRIMARY KEY (ns, id),
                    UNIQUE (ns, app_id, name)
                );
                CREATE TABLE IF NOT EXISTS engine_instances (
                    ns TEXT NOT NULL,
                    id TEXT NOT NULL,
                    status TEXT NOT NULL,
                    start_time INTEGER NOT NULL,
                    end_time INTEGER NOT NULL,
                    engine_id TEXT NOT NULL,
                    engine_version TEXT NOT NULL,
                    engine_variant TEXT NOT NULL,
                    engine_factory TEXT NOT NULL,
                    batch TEXT,
                    env TEXT,
                    runtime_conf TEXT,
                    data_source_params TEXT,
                    preparator_params TEXT,
                    algorithms_params TEXT,
                    serving_params TEXT,
                    PRIMARY KEY (ns, id)
                );
                CREATE TABLE IF NOT EXISTS engine_manifests (
                    ns TEXT NOT NULL,
                    id TEXT NOT NULL,
                    version TEXT NOT NULL,
                    name TEXT NOT NULL,
                    description TEXT,
                    files TEXT,
                    engine_factory TEXT NOT NULL,
                    PRIMARY KEY (ns, id, version)
                );
                CREATE TABLE IF NOT EXISTS evaluation_instances (
                    ns TEXT NOT NULL,
                    id TEXT NOT NULL,
                    status TEXT NOT NULL,
                    start_time INTEGER NOT NULL,
                    end_time INTEGER NOT NULL,
                    evaluation_class TEXT,
                    engine_params_generator_class TEXT,
                    batch TEXT,
                    env TEXT,
                    runtime_conf TEXT,
                    evaluator_results TEXT,
                    evaluator_results_html TEXT,
                    evaluator_results_json TEXT,
                    PRIMARY KEY (ns, id)
                );
                CREATE TABLE IF NOT EXISTS models (
                    ns TEXT NOT NULL,
                    id TEXT NOT NULL,
                    models BLOB NOT NULL,
                    PRIMARY KEY (ns, id)
                );
                """
            )

    def close(self) -> None:
        with self._lock:
            if self._memory_conn is not None:
                self._memory_conn.close()
                self._memory_conn = None
            for conn in self._all_conns:
                try:
                    conn.close()
                except Exception:
                    pass
            self._all_conns.clear()
            self._local = threading.local()


def _chan(channel_id: Optional[int]) -> int:
    return -1 if channel_id is None else channel_id


def _row_to_event(row: Sequence[Any]) -> Event:
    (eid, event, etype, entity_id, tetype, teid, props, etime, tags, pr_id,
     ctime) = row
    return Event(
        event=event,
        entity_type=etype,
        entity_id=entity_id,
        target_entity_type=tetype,
        target_entity_id=teid,
        properties=DataMap(json.loads(props) if props else {}),
        event_time=from_millis(etime),
        tags=tuple(json.loads(tags)) if tags else (),
        pr_id=pr_id,
        creation_time=from_millis(ctime),
        event_id=eid,
    )


_EVENT_COLS = (
    "id, event, entity_type, entity_id, target_entity_type, target_entity_id,"
    " properties, event_time, tags, pr_id, creation_time"
)


class _SQLiteDAO:
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.client = client
        self.ns = prefix

    def _query(self, sql: str, params: Sequence[Any]) -> list:
        with self.client.lock:
            return self.client.conn.execute(sql, params).fetchall()

    def _query_one(self, sql: str, params: Sequence[Any]) -> Optional[Sequence[Any]]:
        with self.client.lock:
            return self.client.conn.execute(sql, params).fetchone()


class SQLiteEvents(_SQLiteDAO, base.Events):
    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        return True  # single shared table, schema made at client init

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self.client.lock, self.client.conn as c:
            c.execute(
                "DELETE FROM events WHERE ns = ? AND app_id = ? AND channel_id = ?",
                (self.ns, app_id, _chan(channel_id)),
            )
        return True

    def close(self) -> None:
        pass

    def compact(self, app_id: int,
                channel_id: Optional[int] = None) -> dict:
        """``pio upgrade``'s sqlite leg: VACUUM reclaims the space DELETEd
        rows leave behind (the JDBC store has no other format debt).

        VACUUM rewrites the WHOLE database file, so it runs once per
        client lifetime (`pio upgrade` = one process = one VACUUM however
        many apps/channels it walks); later compact() calls of the same
        run only report their store's live-event count, with zero byte
        deltas."""
        import os

        path = self.client._path

        def size() -> int:
            return (os.path.getsize(path)
                    if path != ":memory:" and os.path.exists(path) else 0)

        with self.client.lock:
            conn = self.client.conn
            (n,) = conn.execute(
                "SELECT COUNT(*) FROM events WHERE ns = ? AND app_id = ? "
                "AND channel_id = ?",
                (self.ns, app_id, _chan(channel_id))).fetchone()
            if getattr(self.client, "_vacuumed", False):
                before = after = size()
            else:
                before = size()
                # VACUUM renumbers the implicit rowids of tables without
                # an INTEGER PRIMARY KEY and only *happens* to preserve
                # their relative order — but find()'s tie-break contract
                # rides on rowid order. Rebuild events in contract order
                # first so the fresh ascending rowids REENCODE that order
                # instead of depending on unspecified behavior. (An
                # out-of-band `sqlite3 db VACUUM` bypasses this rebuild —
                # run compaction through `pio upgrade`. Encoding the order
                # in a schema-level seq column would close that hole but
                # needs an ALTER TABLE migration for existing stores.)
                try:
                    conn.executescript(
                        "BEGIN;"
                        "CREATE TABLE events_compact AS SELECT * FROM"
                        " events ORDER BY event_time, rowid;"
                        "DELETE FROM events;"
                        "INSERT INTO events SELECT * FROM events_compact"
                        " ORDER BY rowid;"
                        "DROP TABLE events_compact;"
                        "COMMIT;")
                except Exception:
                    # a mid-script failure (disk full) leaves the open
                    # transaction holding the DELETE — roll it back or the
                    # next commit on this shared connection persists it
                    conn.rollback()
                    raise
                conn.execute("VACUUM")
                self.client._vacuumed = True
                after = size()
        return {"events": int(n), "bytes_before": before,
                "bytes_after": after}

    @staticmethod
    def _row(ns: str, eid: str, app_id: int, channel_id, event: Event):
        return (
            ns,
            eid,
            app_id,
            _chan(channel_id),
            event.event,
            event.entity_type,
            event.entity_id,
            event.target_entity_type,
            event.target_entity_id,
            json.dumps(event.properties.to_jsonable()),
            to_millis(event.event_time),
            str(event.event_time.tzinfo or "UTC"),
            json.dumps(list(event.tags)),
            event.pr_id,
            to_millis(event.creation_time),
        )

    _INSERT_SQL = ("INSERT OR REPLACE INTO events VALUES "
                   "(?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)")

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        validate_event(event)
        eid = event.event_id or new_event_id()
        with self.client.lock, self.client.conn as c:
            c.execute(self._INSERT_SQL,
                      self._row(self.ns, eid, app_id, channel_id, event))
        return eid

    def insert_batch(self, events: Sequence[Event], app_id: int,
                     channel_id: Optional[int] = None) -> list:
        """One executemany in ONE transaction — genuinely atomic (the
        generic base loop pays a transaction per event and compensates on
        failure; SQLite can simply roll the whole batch back). REPLACE
        keeps last-wins for duplicate explicit ids within the batch."""
        ids = []
        rows = []
        for event in events:
            validate_event(event)
            eid = event.event_id or new_event_id()
            ids.append(eid)
            rows.append(self._row(self.ns, eid, app_id, channel_id, event))
        with self.client.lock, self.client.conn as c:
            c.executemany(self._INSERT_SQL, rows)
        return ids

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        row = self._query_one(
            f"SELECT {_EVENT_COLS} FROM events "
            "WHERE ns = ? AND id = ? AND app_id = ? AND channel_id = ?",
            (self.ns, event_id, app_id, _chan(channel_id)),
        )
        return _row_to_event(row) if row else None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self.client.lock, self.client.conn as c:
            cur = c.execute(
                "DELETE FROM events "
                "WHERE ns = ? AND id = ? AND app_id = ? AND channel_id = ?",
                (self.ns, event_id, app_id, _chan(channel_id)),
            )
            return cur.rowcount > 0

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        # Same predicate assembly as jdbc/JDBCLEvents.scala:118-165.
        where = ["ns = ?", "app_id = ?", "channel_id = ?"]
        params: list[Any] = [self.ns, app_id, _chan(channel_id)]
        if start_time is not None:
            where.append("event_time >= ?")
            params.append(to_millis(start_time))
        if until_time is not None:
            where.append("event_time < ?")
            params.append(to_millis(until_time))
        if entity_type is not None:
            where.append("entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            where.append("entity_id = ?")
            params.append(entity_id)
        if event_names is not None:
            names = list(event_names)
            where.append(
                "event IN (%s)" % ",".join("?" * len(names)) if names else "0"
            )
            params.extend(names)
        if target_entity_type is not UNSET:
            if target_entity_type is None:
                where.append("target_entity_type IS NULL")
            else:
                where.append("target_entity_type = ?")
                params.append(target_entity_type)
        if target_entity_id is not UNSET:
            if target_entity_id is None:
                where.append("target_entity_id IS NULL")
            else:
                where.append("target_entity_id = ?")
                params.append(target_entity_id)
        # tie-break equal event times by rowid = insertion/upsert order
        # (INSERT OR REPLACE assigns a fresh rowid, so an upsert moves the
        # event to the end of its timestamp group — the cross-backend
        # contract shared with the native log and the memory backend);
        # reversed reverses ties too (DESC on both keys)
        order = "DESC" if reversed else "ASC"
        sql = (
            f"SELECT {_EVENT_COLS} FROM events WHERE " + " AND ".join(where)
            + f" ORDER BY event_time {order}, rowid {order}"
        )
        if limit is not None and limit >= 0:
            sql += " LIMIT ?"
            params.append(limit)
        rows = self._query(sql, params)
        return (_row_to_event(r) for r in rows)

    def scan_interactions(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_names: Sequence[str] = ("rate",),
        value_prop: Optional[str] = None,
        event_values: Optional[Dict[str, float]] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        default_value: float = 1.0,
        batch_rows: int = 500_000,
    ) -> base.Interactions:
        """Columnar scan resolved entirely in SQL — id interning via
        ``dense_rank`` windows and value extraction via ``json_extract``,
        so no :class:`Event` objects (and no Python JSON parsing) exist on
        the training path. Replaces the reference's partitioned
        ``JdbcRDD`` read (jdbc/JDBCPEvents.scala:64-88)."""
        import numpy as np

        fixed = dict(event_values or {})
        names = [str(n) for n in event_names]
        where = ["ns = ?", "app_id = ?", "channel_id = ?",
                 "entity_type = ?", "target_entity_type = ?",
                 "target_entity_id IS NOT NULL"]
        params: list[Any] = [self.ns, app_id, _chan(channel_id),
                             entity_type, target_entity_type]
        if names:
            where.append("event IN (%s)" % ",".join("?" * len(names)))
            params.extend(names)
        else:
            where.append("0")
        if start_time is not None:
            where.append("event_time >= ?")
            params.append(to_millis(start_time))
        if until_time is not None:
            where.append("event_time < ?")
            params.append(to_millis(until_time))

        # value: fixed per event name, else json_extract(value_prop), else
        # the default constant; rows whose value resolves NULL are skipped
        # (the generic scan's "rate event without a rating" rule)
        value_sql = "?"
        value_params: list[Any] = [default_value]
        if value_prop is not None:
            if '"' in value_prop or "\\" in value_prop:
                raise ValueError(
                    f"unsupported value_prop name: {value_prop!r}")
            # json_type guard: CAST('hi' AS REAL) would silently yield 0.0;
            # non-numeric properties must skip the row instead
            path = '\'$."%s"\'' % value_prop
            value_sql = (
                f"CASE WHEN json_type(properties, {path}) IN "
                "('integer','real') THEN "
                f"CAST(json_extract(properties, {path}) AS REAL) END"
            )
            value_params = []
        if fixed:
            cases = " ".join("WHEN ? THEN ?" for _ in fixed)
            value_sql = f"CASE event {cases} ELSE {value_sql} END"
            case_params: list[Any] = []
            for name, v in fixed.items():
                case_params.extend([name, float(v)])
            value_params = case_params + value_params

        cond = " AND ".join(where)
        # one inner row set shared by the COO stream and the id tables, so
        # the dense index space and the id tables always align (a row whose
        # value resolves NULL exists in neither). Materialized ONCE into a
        # temp table: the filter predicates and json_extract evaluate a
        # single time, then the COO stream and both id tables read the
        # materialized rows (previously three full passes).
        inner = (
            f"SELECT entity_id, target_entity_id, {value_sql} AS v,"
            # seq = base-table rowid: the (event_time, insertion/upsert
            # order) tie-break shared with find() and the native log
            f" event_time, rowid AS seq FROM events WHERE {cond}"
        )
        body_params = value_params + params
        u_chunks, i_chunks, v_chunks = [], [], []
        with self.client.lock:
            conn = self.client.conn
            conn.execute("DROP TABLE IF EXISTS temp.pio_scan")
            conn.execute(
                f"CREATE TEMP TABLE pio_scan AS SELECT * FROM ({inner})"
                " WHERE v IS NOT NULL", body_params)
            try:
                # first-seen (event-time, id) order for the id tables — the
                # cross-backend Interactions contract; dense ranks are keyed
                # on each entity's FIRST row in that order
                sql = (
                    "SELECT"
                    " dense_rank() OVER (ORDER BY u_ft, u_fid) - 1,"
                    " dense_rank() OVER (ORDER BY i_ft, i_fid) - 1,"
                    " v FROM ("
                    "SELECT v, event_time, seq,"
                    " FIRST_VALUE(event_time) OVER (PARTITION BY entity_id"
                    "   ORDER BY event_time, seq) AS u_ft,"
                    " FIRST_VALUE(seq) OVER (PARTITION BY entity_id"
                    "   ORDER BY event_time, seq) AS u_fid,"
                    " FIRST_VALUE(event_time) OVER"
                    "   (PARTITION BY target_entity_id"
                    "   ORDER BY event_time, seq) AS i_ft,"
                    " FIRST_VALUE(seq) OVER (PARTITION BY target_entity_id"
                    "   ORDER BY event_time, seq) AS i_fid"
                    " FROM temp.pio_scan)"
                    " ORDER BY event_time, seq"
                )
                cur = conn.execute(sql)
                while True:
                    rows = cur.fetchmany(batch_rows)
                    if not rows:
                        break
                    arr = np.array(rows, np.float64)
                    u_chunks.append(arr[:, 0].astype(np.int32))
                    i_chunks.append(arr[:, 1].astype(np.int32))
                    v_chunks.append(arr[:, 2].astype(np.float32))
                first_seen = (
                    "SELECT {col} FROM (SELECT {col}, event_time, seq,"
                    " ROW_NUMBER() OVER (PARTITION BY {col}"
                    "   ORDER BY event_time, seq) AS rn FROM temp.pio_scan)"
                    " WHERE rn = 1 ORDER BY event_time, seq"
                )
                user_ids = [r[0] for r in conn.execute(
                    first_seen.format(col="entity_id"))]
                item_ids = [r[0] for r in conn.execute(
                    first_seen.format(col="target_entity_id"))]
            finally:
                conn.execute("DROP TABLE IF EXISTS temp.pio_scan")
        empty = np.zeros(0, np.int32)
        return base.Interactions(
            user_idx=np.concatenate(u_chunks) if u_chunks else empty,
            item_idx=np.concatenate(i_chunks) if i_chunks else empty,
            values=(np.concatenate(v_chunks) if v_chunks
                    else np.zeros(0, np.float32)),
            user_ids=user_ids,
            item_ids=item_ids,
        )


class SQLiteApps(_SQLiteDAO, base.Apps):
    def insert(self, app: base.App) -> Optional[int]:
        with self.client.lock, self.client.conn as c:
            try:
                if app.id != 0:
                    app_id = app.id
                else:
                    row = c.execute(
                        "SELECT COALESCE(MAX(id), 0) + 1 FROM apps WHERE ns = ?",
                        (self.ns,),
                    ).fetchone()
                    app_id = row[0]
                c.execute(
                    "INSERT INTO apps (ns, id, name, description) VALUES (?,?,?,?)",
                    (self.ns, app_id, app.name, app.description),
                )
                return app_id
            except sqlite3.IntegrityError:
                return None

    def get(self, app_id: int) -> Optional[base.App]:
        row = self._query_one(
            "SELECT id, name, description FROM apps WHERE ns = ? AND id = ?",
            (self.ns, app_id),
        )
        return base.App(*row) if row else None

    def get_by_name(self, name: str) -> Optional[base.App]:
        row = self._query_one(
            "SELECT id, name, description FROM apps WHERE ns = ? AND name = ?",
            (self.ns, name),
        )
        return base.App(*row) if row else None

    def get_all(self) -> list[base.App]:
        rows = self._query(
            "SELECT id, name, description FROM apps WHERE ns = ?", (self.ns,)
        )
        return [base.App(*r) for r in rows]

    def update(self, app: base.App) -> bool:
        with self.client.lock, self.client.conn as c:
            cur = c.execute(
                "UPDATE apps SET name = ?, description = ? WHERE ns = ? AND id = ?",
                (app.name, app.description, self.ns, app.id),
            )
            return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM apps WHERE ns = ? AND id = ?", (self.ns, app_id)
            ).rowcount > 0


class SQLiteAccessKeys(_SQLiteDAO, base.AccessKeys):
    def insert(self, k: base.AccessKey) -> Optional[str]:
        key = k.key or base.generate_access_key()
        with self.client.lock, self.client.conn as c:
            try:
                c.execute(
                    "INSERT INTO access_keys (ns, key, app_id, events) "
                    "VALUES (?,?,?,?)",
                    (self.ns, key, k.appid, json.dumps(list(k.events))),
                )
                return key
            except sqlite3.IntegrityError:
                return None

    @staticmethod
    def _row(row: Sequence[Any]) -> base.AccessKey:
        return base.AccessKey(row[0], row[1], tuple(json.loads(row[2])))

    def get(self, key: str) -> Optional[base.AccessKey]:
        row = self._query_one(
            "SELECT key, app_id, events FROM access_keys "
            "WHERE ns = ? AND key = ?",
            (self.ns, key),
        )
        return self._row(row) if row else None

    def get_all(self) -> list[base.AccessKey]:
        rows = self._query(
            "SELECT key, app_id, events FROM access_keys WHERE ns = ?",
            (self.ns,),
        )
        return [self._row(r) for r in rows]

    def get_by_appid(self, appid: int) -> list[base.AccessKey]:
        rows = self._query(
            "SELECT key, app_id, events FROM access_keys "
            "WHERE ns = ? AND app_id = ?",
            (self.ns, appid),
        )
        return [self._row(r) for r in rows]

    def update(self, k: base.AccessKey) -> bool:
        with self.client.lock, self.client.conn as c:
            cur = c.execute(
                "UPDATE access_keys SET app_id = ?, events = ? "
                "WHERE ns = ? AND key = ?",
                (k.appid, json.dumps(list(k.events)), self.ns, k.key),
            )
            return cur.rowcount > 0

    def delete(self, key: str) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM access_keys WHERE ns = ? AND key = ?",
                (self.ns, key),
            ).rowcount > 0


class SQLiteChannels(_SQLiteDAO, base.Channels):
    def insert(self, channel: base.Channel) -> Optional[int]:
        with self.client.lock, self.client.conn as c:
            try:
                if channel.id != 0:
                    cid = channel.id
                else:
                    row = c.execute(
                        "SELECT COALESCE(MAX(id), 0) + 1 FROM channels "
                        "WHERE ns = ?",
                        (self.ns,),
                    ).fetchone()
                    cid = row[0]
                c.execute(
                    "INSERT INTO channels (ns, id, name, app_id) VALUES (?,?,?,?)",
                    (self.ns, cid, channel.name, channel.appid),
                )
                return cid
            except sqlite3.IntegrityError:
                return None

    def get(self, channel_id: int) -> Optional[base.Channel]:
        row = self._query_one(
            "SELECT id, name, app_id FROM channels WHERE ns = ? AND id = ?",
            (self.ns, channel_id),
        )
        return base.Channel(*row) if row else None

    def get_by_appid(self, appid: int) -> list[base.Channel]:
        rows = self._query(
            "SELECT id, name, app_id FROM channels WHERE ns = ? AND app_id = ?",
            (self.ns, appid),
        )
        return [base.Channel(*r) for r in rows]

    def delete(self, channel_id: int) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM channels WHERE ns = ? AND id = ?",
                (self.ns, channel_id),
            ).rowcount > 0


_EI_COLS = (
    "id, status, start_time, end_time, engine_id, engine_version,"
    " engine_variant, engine_factory, batch, env, runtime_conf,"
    " data_source_params, preparator_params, algorithms_params, serving_params"
)


def _row_to_engine_instance(row: Sequence[Any]) -> base.EngineInstance:
    return base.EngineInstance(
        id=row[0],
        status=row[1],
        start_time=from_millis(row[2]),
        end_time=from_millis(row[3]),
        engine_id=row[4],
        engine_version=row[5],
        engine_variant=row[6],
        engine_factory=row[7],
        batch=row[8] or "",
        env=json.loads(row[9]) if row[9] else {},
        runtime_conf=json.loads(row[10]) if row[10] else {},
        data_source_params=row[11] or "",
        preparator_params=row[12] or "",
        algorithms_params=row[13] or "",
        serving_params=row[14] or "",
    )


class SQLiteEngineInstances(_SQLiteDAO, base.EngineInstances):
    def insert(self, i: base.EngineInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        if not i.id:
            i = dataclasses.replace(i, id=iid)
        with self.client.lock, self.client.conn as c:
            c.execute(
                "INSERT OR REPLACE INTO engine_instances VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    self.ns, i.id, i.status, to_millis(i.start_time),
                    to_millis(i.end_time), i.engine_id, i.engine_version,
                    i.engine_variant, i.engine_factory, i.batch,
                    json.dumps(i.env), json.dumps(i.runtime_conf),
                    i.data_source_params, i.preparator_params,
                    i.algorithms_params, i.serving_params,
                ),
            )
        return iid

    def get(self, instance_id: str) -> Optional[base.EngineInstance]:
        row = self._query_one(
            f"SELECT {_EI_COLS} FROM engine_instances WHERE ns = ? AND id = ?",
            (self.ns, instance_id),
        )
        return _row_to_engine_instance(row) if row else None

    def get_all(self) -> list[base.EngineInstance]:
        rows = self._query(
            f"SELECT {_EI_COLS} FROM engine_instances WHERE ns = ?", (self.ns,)
        )
        return [_row_to_engine_instance(r) for r in rows]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[base.EngineInstance]:
        rows = self._query(
            f"SELECT {_EI_COLS} FROM engine_instances "
            "WHERE ns = ? AND status = 'COMPLETED'"
            " AND engine_id = ? AND engine_version = ? AND engine_variant = ?"
            " ORDER BY start_time DESC",
            (self.ns, engine_id, engine_version, engine_variant),
        )
        return [_row_to_engine_instance(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[base.EngineInstance]:
        rows = self.get_completed(engine_id, engine_version, engine_variant)
        return rows[0] if rows else None

    def update(self, i: base.EngineInstance) -> bool:
        if self.get(i.id) is None:
            return False
        self.insert(i)
        return True

    def delete(self, instance_id: str) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM engine_instances WHERE ns = ? AND id = ?",
                (self.ns, instance_id),
            ).rowcount > 0


class SQLiteEngineManifests(_SQLiteDAO, base.EngineManifests):
    @staticmethod
    def _row(row: Sequence[Any]) -> base.EngineManifest:
        return base.EngineManifest(
            id=row[0], version=row[1], name=row[2],
            engine_factory=row[3], description=row[4],
            files=tuple(json.loads(row[5])) if row[5] else (),
        )

    _COLS = "id, version, name, engine_factory, description, files"

    def insert(self, m: base.EngineManifest) -> None:
        with self.client.lock, self.client.conn as c:
            c.execute(
                "INSERT OR REPLACE INTO engine_manifests "
                "(ns, id, version, name, description, files, engine_factory) "
                "VALUES (?,?,?,?,?,?,?)",
                (self.ns, m.id, m.version, m.name, m.description,
                 json.dumps(list(m.files)), m.engine_factory),
            )

    def get(self, manifest_id: str, version: str) -> Optional[base.EngineManifest]:
        row = self._query_one(
            f"SELECT {self._COLS} FROM engine_manifests "
            "WHERE ns = ? AND id = ? AND version = ?",
            (self.ns, manifest_id, version),
        )
        return self._row(row) if row else None

    def get_all(self) -> list[base.EngineManifest]:
        rows = self._query(
            f"SELECT {self._COLS} FROM engine_manifests WHERE ns = ?",
            (self.ns,),
        )
        return [self._row(r) for r in rows]

    def update(self, m: base.EngineManifest, upsert: bool = False) -> bool:
        if not upsert and self.get(m.id, m.version) is None:
            return False
        self.insert(m)
        return True

    def delete(self, manifest_id: str, version: str) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM engine_manifests "
                "WHERE ns = ? AND id = ? AND version = ?",
                (self.ns, manifest_id, version),
            ).rowcount > 0


_EVI_COLS = (
    "id, status, start_time, end_time, evaluation_class,"
    " engine_params_generator_class, batch, env, runtime_conf,"
    " evaluator_results, evaluator_results_html, evaluator_results_json"
)


def _row_to_evaluation_instance(row: Sequence[Any]) -> base.EvaluationInstance:
    return base.EvaluationInstance(
        id=row[0],
        status=row[1],
        start_time=from_millis(row[2]),
        end_time=from_millis(row[3]),
        evaluation_class=row[4] or "",
        engine_params_generator_class=row[5] or "",
        batch=row[6] or "",
        env=json.loads(row[7]) if row[7] else {},
        runtime_conf=json.loads(row[8]) if row[8] else {},
        evaluator_results=row[9] or "",
        evaluator_results_html=row[10] or "",
        evaluator_results_json=row[11] or "",
    )


class SQLiteEvaluationInstances(_SQLiteDAO, base.EvaluationInstances):
    def insert(self, i: base.EvaluationInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        if not i.id:
            i = dataclasses.replace(i, id=iid)
        with self.client.lock, self.client.conn as c:
            c.execute(
                "INSERT OR REPLACE INTO evaluation_instances VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    self.ns, i.id, i.status, to_millis(i.start_time),
                    to_millis(i.end_time), i.evaluation_class,
                    i.engine_params_generator_class, i.batch,
                    json.dumps(i.env), json.dumps(i.runtime_conf),
                    i.evaluator_results, i.evaluator_results_html,
                    i.evaluator_results_json,
                ),
            )
        return iid

    def get(self, instance_id: str) -> Optional[base.EvaluationInstance]:
        row = self._query_one(
            f"SELECT {_EVI_COLS} FROM evaluation_instances "
            "WHERE ns = ? AND id = ?",
            (self.ns, instance_id),
        )
        return _row_to_evaluation_instance(row) if row else None

    def get_all(self) -> list[base.EvaluationInstance]:
        rows = self._query(
            f"SELECT {_EVI_COLS} FROM evaluation_instances WHERE ns = ?",
            (self.ns,),
        )
        return [_row_to_evaluation_instance(r) for r in rows]

    def get_completed(self) -> list[base.EvaluationInstance]:
        rows = self._query(
            f"SELECT {_EVI_COLS} FROM evaluation_instances "
            "WHERE ns = ? AND status = 'EVALCOMPLETED' ORDER BY start_time DESC",
            (self.ns,),
        )
        return [_row_to_evaluation_instance(r) for r in rows]

    def update(self, i: base.EvaluationInstance) -> bool:
        if self.get(i.id) is None:
            return False
        self.insert(i)
        return True

    def delete(self, instance_id: str) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM evaluation_instances WHERE ns = ? AND id = ?",
                (self.ns, instance_id),
            ).rowcount > 0


class SQLiteModels(_SQLiteDAO, base.Models):
    def insert(self, model: base.Model) -> None:
        with self.client.lock, self.client.conn as c:
            c.execute(
                "INSERT OR REPLACE INTO models (ns, id, models) VALUES (?,?,?)",
                (self.ns, model.id, model.models),
            )

    def get(self, model_id: str) -> Optional[base.Model]:
        row = self._query_one(
            "SELECT id, models FROM models WHERE ns = ? AND id = ?",
            (self.ns, model_id),
        )
        return base.Model(row[0], row[1]) if row else None

    def delete(self, model_id: str) -> None:
        with self.client.lock, self.client.conn as c:
            c.execute(
                "DELETE FROM models WHERE ns = ? AND id = ?",
                (self.ns, model_id),
            )


DATA_OBJECTS = {
    "Events": SQLiteEvents,
    "Apps": SQLiteApps,
    "AccessKeys": SQLiteAccessKeys,
    "Channels": SQLiteChannels,
    "EngineInstances": SQLiteEngineInstances,
    "EngineManifests": SQLiteEngineManifests,
    "EvaluationInstances": SQLiteEvaluationInstances,
    "Models": SQLiteModels,
}
