"""SQLite storage backend — the durable single-box backend.

Parity target: the reference's JDBC driver, which implements the *full*
backend surface (events + all metadata + model blobs) on PostgreSQL/MySQL
(data/.../storage/jdbc/, 1393 LoC: JDBCLEvents, JDBCPEvents, JDBCApps,
JDBCAccessKeys, JDBCChannels, JDBCEngineInstances, JDBCEvaluationInstances,
JDBCModels, JDBCUtils). SQLite gives the same durability contract with zero
service dependencies; the DAO layer is schema-compatible with a Postgres
driver should one be added (SQL here is deliberately generic).

Event rows store times as epoch-millis integers for fast range scans — the
same role as the reference's indexed ``eventTime`` columns
(jdbc/JDBCLEvents.scala:44-66).
"""

from __future__ import annotations

import dataclasses
import json
import sqlite3
import threading
import uuid
from datetime import datetime
from pathlib import Path
from typing import Any, Dict, Iterator, Optional, Sequence

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event, new_event_id, validate_event
from incubator_predictionio_tpu.data.storage import base
from incubator_predictionio_tpu.data.storage.base import UNSET
from incubator_predictionio_tpu.utils.times import from_millis, to_millis


class StorageClient(base.BaseStorageClient):
    """One SQLite database file (``:memory:`` supported for tests)."""

    def __init__(self, config: base.StorageClientConfig):
        super().__init__(config)
        path = config.properties.get("PATH", "")
        if not path or path == ":memory:":
            self._path = ":memory:"
        else:
            p = Path(path).expanduser()
            p.parent.mkdir(parents=True, exist_ok=True)
            self._path = str(p)
        self._local = threading.local()
        self._memory_conn: Optional[sqlite3.Connection] = None
        self._lock = threading.RLock()
        self._init_schema()

    @property
    def conn(self) -> sqlite3.Connection:
        # ":memory:" must share one connection; files get one per thread.
        if self._path == ":memory:":
            if self._memory_conn is None:
                self._memory_conn = sqlite3.connect(
                    ":memory:", check_same_thread=False
                )
            return self._memory_conn
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self._path)
            conn.execute("PRAGMA journal_mode=WAL")
            self._local.conn = conn
        return conn

    @property
    def lock(self) -> threading.RLock:
        return self._lock

    def _init_schema(self) -> None:
        with self._lock, self.conn as c:
            c.executescript(
                """
                CREATE TABLE IF NOT EXISTS events (
                    id TEXT NOT NULL,
                    app_id INTEGER NOT NULL,
                    channel_id INTEGER NOT NULL DEFAULT -1,
                    event TEXT NOT NULL,
                    entity_type TEXT NOT NULL,
                    entity_id TEXT NOT NULL,
                    target_entity_type TEXT,
                    target_entity_id TEXT,
                    properties TEXT,
                    event_time INTEGER NOT NULL,
                    event_time_zone TEXT,
                    tags TEXT,
                    pr_id TEXT,
                    creation_time INTEGER NOT NULL,
                    PRIMARY KEY (id, app_id, channel_id)
                );
                CREATE INDEX IF NOT EXISTS idx_events_scan
                    ON events (app_id, channel_id, event_time);
                CREATE TABLE IF NOT EXISTS apps (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    name TEXT NOT NULL UNIQUE,
                    description TEXT
                );
                CREATE TABLE IF NOT EXISTS access_keys (
                    key TEXT PRIMARY KEY,
                    app_id INTEGER NOT NULL,
                    events TEXT NOT NULL
                );
                CREATE TABLE IF NOT EXISTS channels (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    name TEXT NOT NULL,
                    app_id INTEGER NOT NULL,
                    UNIQUE (app_id, name)
                );
                CREATE TABLE IF NOT EXISTS engine_instances (
                    id TEXT PRIMARY KEY,
                    status TEXT NOT NULL,
                    start_time INTEGER NOT NULL,
                    end_time INTEGER NOT NULL,
                    engine_id TEXT NOT NULL,
                    engine_version TEXT NOT NULL,
                    engine_variant TEXT NOT NULL,
                    engine_factory TEXT NOT NULL,
                    batch TEXT,
                    env TEXT,
                    runtime_conf TEXT,
                    data_source_params TEXT,
                    preparator_params TEXT,
                    algorithms_params TEXT,
                    serving_params TEXT
                );
                CREATE TABLE IF NOT EXISTS evaluation_instances (
                    id TEXT PRIMARY KEY,
                    status TEXT NOT NULL,
                    start_time INTEGER NOT NULL,
                    end_time INTEGER NOT NULL,
                    evaluation_class TEXT,
                    engine_params_generator_class TEXT,
                    batch TEXT,
                    env TEXT,
                    runtime_conf TEXT,
                    evaluator_results TEXT,
                    evaluator_results_html TEXT,
                    evaluator_results_json TEXT
                );
                CREATE TABLE IF NOT EXISTS models (
                    id TEXT PRIMARY KEY,
                    models BLOB NOT NULL
                );
                """
            )

    def close(self) -> None:
        if self._memory_conn is not None:
            self._memory_conn.close()
            self._memory_conn = None
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None


def _chan(channel_id: Optional[int]) -> int:
    return -1 if channel_id is None else channel_id


def _row_to_event(row: Sequence[Any]) -> Event:
    (eid, event, etype, entity_id, tetype, teid, props, etime, tags, pr_id,
     ctime) = row
    return Event(
        event=event,
        entity_type=etype,
        entity_id=entity_id,
        target_entity_type=tetype,
        target_entity_id=teid,
        properties=DataMap(json.loads(props) if props else {}),
        event_time=from_millis(etime),
        tags=tuple(json.loads(tags)) if tags else (),
        pr_id=pr_id,
        creation_time=from_millis(ctime),
        event_id=eid,
    )


_EVENT_COLS = (
    "id, event, entity_type, entity_id, target_entity_type, target_entity_id,"
    " properties, event_time, tags, pr_id, creation_time"
)


class SQLiteEvents(base.Events):
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.client = client

    def init(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        return True  # single shared table, schema made at client init

    def remove(self, app_id: int, channel_id: Optional[int] = None) -> bool:
        with self.client.lock, self.client.conn as c:
            c.execute(
                "DELETE FROM events WHERE app_id = ? AND channel_id = ?",
                (app_id, _chan(channel_id)),
            )
        return True

    def close(self) -> None:
        pass

    def insert(self, event: Event, app_id: int,
               channel_id: Optional[int] = None) -> str:
        validate_event(event)
        eid = event.event_id or new_event_id()
        with self.client.lock, self.client.conn as c:
            c.execute(
                "INSERT OR REPLACE INTO events VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    eid,
                    app_id,
                    _chan(channel_id),
                    event.event,
                    event.entity_type,
                    event.entity_id,
                    event.target_entity_type,
                    event.target_entity_id,
                    json.dumps(event.properties.to_jsonable()),
                    to_millis(event.event_time),
                    str(event.event_time.tzinfo or "UTC"),
                    json.dumps(list(event.tags)),
                    event.pr_id,
                    to_millis(event.creation_time),
                ),
            )
        return eid

    def get(self, event_id: str, app_id: int,
            channel_id: Optional[int] = None) -> Optional[Event]:
        with self.client.lock:
            cur = self.client.conn.execute(
                f"SELECT {_EVENT_COLS} FROM events "
                "WHERE id = ? AND app_id = ? AND channel_id = ?",
                (event_id, app_id, _chan(channel_id)),
            )
            row = cur.fetchone()
        return _row_to_event(row) if row else None

    def delete(self, event_id: str, app_id: int,
               channel_id: Optional[int] = None) -> bool:
        with self.client.lock, self.client.conn as c:
            cur = c.execute(
                "DELETE FROM events WHERE id = ? AND app_id = ? AND channel_id = ?",
                (event_id, app_id, _chan(channel_id)),
            )
            return cur.rowcount > 0

    def find(
        self,
        app_id: int,
        channel_id: Optional[int] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        # Same predicate assembly as jdbc/JDBCLEvents.scala:118-165.
        where = ["app_id = ?", "channel_id = ?"]
        params: list[Any] = [app_id, _chan(channel_id)]
        if start_time is not None:
            where.append("event_time >= ?")
            params.append(to_millis(start_time))
        if until_time is not None:
            where.append("event_time < ?")
            params.append(to_millis(until_time))
        if entity_type is not None:
            where.append("entity_type = ?")
            params.append(entity_type)
        if entity_id is not None:
            where.append("entity_id = ?")
            params.append(entity_id)
        if event_names is not None:
            names = list(event_names)
            where.append(
                "event IN (%s)" % ",".join("?" * len(names)) if names else "0"
            )
            params.extend(names)
        if target_entity_type is not UNSET:
            if target_entity_type is None:
                where.append("target_entity_type IS NULL")
            else:
                where.append("target_entity_type = ?")
                params.append(target_entity_type)
        if target_entity_id is not UNSET:
            if target_entity_id is None:
                where.append("target_entity_id IS NULL")
            else:
                where.append("target_entity_id = ?")
                params.append(target_entity_id)
        sql = (
            f"SELECT {_EVENT_COLS} FROM events WHERE " + " AND ".join(where)
            + f" ORDER BY event_time {'DESC' if reversed else 'ASC'}, id"
        )
        if limit is not None and limit >= 0:
            sql += " LIMIT ?"
            params.append(limit)
        with self.client.lock:
            rows = self.client.conn.execute(sql, params).fetchall()
        return (_row_to_event(r) for r in rows)


class SQLiteApps(base.Apps):
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.client = client

    def insert(self, app: base.App) -> Optional[int]:
        with self.client.lock, self.client.conn as c:
            try:
                if app.id != 0:
                    c.execute(
                        "INSERT INTO apps (id, name, description) VALUES (?,?,?)",
                        (app.id, app.name, app.description),
                    )
                    return app.id
                cur = c.execute(
                    "INSERT INTO apps (name, description) VALUES (?,?)",
                    (app.name, app.description),
                )
                return cur.lastrowid
            except sqlite3.IntegrityError:
                return None

    def get(self, app_id: int) -> Optional[base.App]:
        row = self.client.conn.execute(
            "SELECT id, name, description FROM apps WHERE id = ?", (app_id,)
        ).fetchone()
        return base.App(*row) if row else None

    def get_by_name(self, name: str) -> Optional[base.App]:
        row = self.client.conn.execute(
            "SELECT id, name, description FROM apps WHERE name = ?", (name,)
        ).fetchone()
        return base.App(*row) if row else None

    def get_all(self) -> list[base.App]:
        rows = self.client.conn.execute(
            "SELECT id, name, description FROM apps"
        ).fetchall()
        return [base.App(*r) for r in rows]

    def update(self, app: base.App) -> bool:
        with self.client.lock, self.client.conn as c:
            cur = c.execute(
                "UPDATE apps SET name = ?, description = ? WHERE id = ?",
                (app.name, app.description, app.id),
            )
            return cur.rowcount > 0

    def delete(self, app_id: int) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM apps WHERE id = ?", (app_id,)
            ).rowcount > 0


class SQLiteAccessKeys(base.AccessKeys):
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.client = client

    def insert(self, k: base.AccessKey) -> Optional[str]:
        key = k.key or base.generate_access_key()
        with self.client.lock, self.client.conn as c:
            try:
                c.execute(
                    "INSERT INTO access_keys (key, app_id, events) VALUES (?,?,?)",
                    (key, k.appid, json.dumps(list(k.events))),
                )
                return key
            except sqlite3.IntegrityError:
                return None

    @staticmethod
    def _row(row: Sequence[Any]) -> base.AccessKey:
        return base.AccessKey(row[0], row[1], tuple(json.loads(row[2])))

    def get(self, key: str) -> Optional[base.AccessKey]:
        row = self.client.conn.execute(
            "SELECT key, app_id, events FROM access_keys WHERE key = ?", (key,)
        ).fetchone()
        return self._row(row) if row else None

    def get_all(self) -> list[base.AccessKey]:
        rows = self.client.conn.execute(
            "SELECT key, app_id, events FROM access_keys"
        ).fetchall()
        return [self._row(r) for r in rows]

    def get_by_appid(self, appid: int) -> list[base.AccessKey]:
        rows = self.client.conn.execute(
            "SELECT key, app_id, events FROM access_keys WHERE app_id = ?",
            (appid,),
        ).fetchall()
        return [self._row(r) for r in rows]

    def update(self, k: base.AccessKey) -> bool:
        with self.client.lock, self.client.conn as c:
            cur = c.execute(
                "UPDATE access_keys SET app_id = ?, events = ? WHERE key = ?",
                (k.appid, json.dumps(list(k.events)), k.key),
            )
            return cur.rowcount > 0

    def delete(self, key: str) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM access_keys WHERE key = ?", (key,)
            ).rowcount > 0


class SQLiteChannels(base.Channels):
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.client = client

    def insert(self, channel: base.Channel) -> Optional[int]:
        with self.client.lock, self.client.conn as c:
            try:
                if channel.id != 0:
                    c.execute(
                        "INSERT INTO channels (id, name, app_id) VALUES (?,?,?)",
                        (channel.id, channel.name, channel.appid),
                    )
                    return channel.id
                cur = c.execute(
                    "INSERT INTO channels (name, app_id) VALUES (?,?)",
                    (channel.name, channel.appid),
                )
                return cur.lastrowid
            except sqlite3.IntegrityError:
                return None

    def get(self, channel_id: int) -> Optional[base.Channel]:
        row = self.client.conn.execute(
            "SELECT id, name, app_id FROM channels WHERE id = ?", (channel_id,)
        ).fetchone()
        return base.Channel(*row) if row else None

    def get_by_appid(self, appid: int) -> list[base.Channel]:
        rows = self.client.conn.execute(
            "SELECT id, name, app_id FROM channels WHERE app_id = ?", (appid,)
        ).fetchall()
        return [base.Channel(*r) for r in rows]

    def delete(self, channel_id: int) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM channels WHERE id = ?", (channel_id,)
            ).rowcount > 0


_EI_COLS = (
    "id, status, start_time, end_time, engine_id, engine_version,"
    " engine_variant, engine_factory, batch, env, runtime_conf,"
    " data_source_params, preparator_params, algorithms_params, serving_params"
)


def _row_to_engine_instance(row: Sequence[Any]) -> base.EngineInstance:
    return base.EngineInstance(
        id=row[0],
        status=row[1],
        start_time=from_millis(row[2]),
        end_time=from_millis(row[3]),
        engine_id=row[4],
        engine_version=row[5],
        engine_variant=row[6],
        engine_factory=row[7],
        batch=row[8] or "",
        env=json.loads(row[9]) if row[9] else {},
        runtime_conf=json.loads(row[10]) if row[10] else {},
        data_source_params=row[11] or "",
        preparator_params=row[12] or "",
        algorithms_params=row[13] or "",
        serving_params=row[14] or "",
    )


class SQLiteEngineInstances(base.EngineInstances):
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.client = client

    def insert(self, i: base.EngineInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        if not i.id:
            i = dataclasses.replace(i, id=iid)
        with self.client.lock, self.client.conn as c:
            c.execute(
                "INSERT OR REPLACE INTO engine_instances VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    i.id, i.status, to_millis(i.start_time), to_millis(i.end_time),
                    i.engine_id, i.engine_version, i.engine_variant,
                    i.engine_factory, i.batch, json.dumps(i.env),
                    json.dumps(i.runtime_conf), i.data_source_params,
                    i.preparator_params, i.algorithms_params, i.serving_params,
                ),
            )
        return iid

    def get(self, instance_id: str) -> Optional[base.EngineInstance]:
        row = self.client.conn.execute(
            f"SELECT {_EI_COLS} FROM engine_instances WHERE id = ?",
            (instance_id,),
        ).fetchone()
        return _row_to_engine_instance(row) if row else None

    def get_all(self) -> list[base.EngineInstance]:
        rows = self.client.conn.execute(
            f"SELECT {_EI_COLS} FROM engine_instances"
        ).fetchall()
        return [_row_to_engine_instance(r) for r in rows]

    def get_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> list[base.EngineInstance]:
        rows = self.client.conn.execute(
            f"SELECT {_EI_COLS} FROM engine_instances WHERE status = 'COMPLETED'"
            " AND engine_id = ? AND engine_version = ? AND engine_variant = ?"
            " ORDER BY start_time DESC",
            (engine_id, engine_version, engine_variant),
        ).fetchall()
        return [_row_to_engine_instance(r) for r in rows]

    def get_latest_completed(
        self, engine_id: str, engine_version: str, engine_variant: str
    ) -> Optional[base.EngineInstance]:
        rows = self.get_completed(engine_id, engine_version, engine_variant)
        return rows[0] if rows else None

    def update(self, i: base.EngineInstance) -> bool:
        if self.get(i.id) is None:
            return False
        self.insert(i)
        return True

    def delete(self, instance_id: str) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM engine_instances WHERE id = ?", (instance_id,)
            ).rowcount > 0


_EVI_COLS = (
    "id, status, start_time, end_time, evaluation_class,"
    " engine_params_generator_class, batch, env, runtime_conf,"
    " evaluator_results, evaluator_results_html, evaluator_results_json"
)


def _row_to_evaluation_instance(row: Sequence[Any]) -> base.EvaluationInstance:
    return base.EvaluationInstance(
        id=row[0],
        status=row[1],
        start_time=from_millis(row[2]),
        end_time=from_millis(row[3]),
        evaluation_class=row[4] or "",
        engine_params_generator_class=row[5] or "",
        batch=row[6] or "",
        env=json.loads(row[7]) if row[7] else {},
        runtime_conf=json.loads(row[8]) if row[8] else {},
        evaluator_results=row[9] or "",
        evaluator_results_html=row[10] or "",
        evaluator_results_json=row[11] or "",
    )


class SQLiteEvaluationInstances(base.EvaluationInstances):
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.client = client

    def insert(self, i: base.EvaluationInstance) -> str:
        iid = i.id or uuid.uuid4().hex
        if not i.id:
            i = dataclasses.replace(i, id=iid)
        with self.client.lock, self.client.conn as c:
            c.execute(
                "INSERT OR REPLACE INTO evaluation_instances VALUES "
                "(?,?,?,?,?,?,?,?,?,?,?,?)",
                (
                    i.id, i.status, to_millis(i.start_time), to_millis(i.end_time),
                    i.evaluation_class, i.engine_params_generator_class, i.batch,
                    json.dumps(i.env), json.dumps(i.runtime_conf),
                    i.evaluator_results, i.evaluator_results_html,
                    i.evaluator_results_json,
                ),
            )
        return iid

    def get(self, instance_id: str) -> Optional[base.EvaluationInstance]:
        row = self.client.conn.execute(
            f"SELECT {_EVI_COLS} FROM evaluation_instances WHERE id = ?",
            (instance_id,),
        ).fetchone()
        return _row_to_evaluation_instance(row) if row else None

    def get_all(self) -> list[base.EvaluationInstance]:
        rows = self.client.conn.execute(
            f"SELECT {_EVI_COLS} FROM evaluation_instances"
        ).fetchall()
        return [_row_to_evaluation_instance(r) for r in rows]

    def get_completed(self) -> list[base.EvaluationInstance]:
        rows = self.client.conn.execute(
            f"SELECT {_EVI_COLS} FROM evaluation_instances "
            "WHERE status = 'EVALCOMPLETED' ORDER BY start_time DESC"
        ).fetchall()
        return [_row_to_evaluation_instance(r) for r in rows]

    def update(self, i: base.EvaluationInstance) -> bool:
        if self.get(i.id) is None:
            return False
        self.insert(i)
        return True

    def delete(self, instance_id: str) -> bool:
        with self.client.lock, self.client.conn as c:
            return c.execute(
                "DELETE FROM evaluation_instances WHERE id = ?", (instance_id,)
            ).rowcount > 0


class SQLiteModels(base.Models):
    def __init__(self, client: StorageClient, config: base.StorageClientConfig,
                 prefix: str = ""):
        self.client = client

    def insert(self, model: base.Model) -> None:
        with self.client.lock, self.client.conn as c:
            c.execute(
                "INSERT OR REPLACE INTO models (id, models) VALUES (?,?)",
                (model.id, model.models),
            )

    def get(self, model_id: str) -> Optional[base.Model]:
        row = self.client.conn.execute(
            "SELECT id, models FROM models WHERE id = ?", (model_id,)
        ).fetchone()
        return base.Model(row[0], row[1]) if row else None

    def delete(self, model_id: str) -> None:
        with self.client.lock, self.client.conn as c:
            c.execute("DELETE FROM models WHERE id = ?", (model_id,))


DATA_OBJECTS = {
    "Events": SQLiteEvents,
    "Apps": SQLiteApps,
    "AccessKeys": SQLiteAccessKeys,
    "Channels": SQLiteChannels,
    "EngineInstances": SQLiteEngineInstances,
    "EvaluationInstances": SQLiteEvaluationInstances,
    "Models": SQLiteModels,
}
