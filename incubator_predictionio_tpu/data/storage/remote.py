"""``remote`` storage backend — client for the StorageServer.

The multi-box topology enabler (VERDICT r2 #1): every DAO call is one
``POST /rpc`` round trip to a shared :class:`~.server.StorageServer`, so
an eventserver on box A, a trainer on box B, and N prediction servers all
see one store — the role PostgreSQL/HBase play for the reference
(data/.../storage/jdbc/StorageClient.scala:35-60). There is no SQL driver
in the loop: the protocol is the framework's own msgpack wire format
(storage/wire.py), and columnar training scans arrive as raw array
buffers.

Config::

    PIO_STORAGE_SOURCES_REMOTE_TYPE=remote
    PIO_STORAGE_SOURCES_REMOTE_URL=http://store-box:7077
    PIO_STORAGE_SOURCES_REMOTE_AUTHKEY=...   # optional shared key

Connections are persistent (HTTP/1.1 keep-alive) and per-thread.
"""

from __future__ import annotations

import http.client
from typing import Any, Dict, Iterator, Tuple
from urllib.parse import urlsplit

from incubator_predictionio_tpu.data.event import EventValidationError
from incubator_predictionio_tpu.data.storage import base, wire
from incubator_predictionio_tpu.data.storage.base import StorageClientConfig
from incubator_predictionio_tpu.obs import trace as obs_trace

#: typed errors re-raised client-side; anything else maps to StorageError
_ERROR_TYPES: Dict[str, type] = {
    "ValueError": ValueError,
    "KeyError": KeyError,
    "EventValidationError": EventValidationError,
}


def _storage_error() -> type:
    from incubator_predictionio_tpu.data.storage import StorageError

    return StorageError


def _ambiguous_error() -> type:
    from incubator_predictionio_tpu.data.storage import AmbiguousWriteError

    return AmbiguousWriteError


def _unsupported_error() -> type:
    from incubator_predictionio_tpu.data.storage import (
        UnsupportedMethodError,
    )

    return UnsupportedMethodError


class StorageClient(base.BaseStorageClient):
    """Keep-alive RPC channel to one StorageServer."""

    def __init__(self, config: StorageClientConfig):
        super().__init__(config)
        url = config.properties.get("URL")
        if not url:
            host = config.properties.get("HOST", "127.0.0.1")
            port = config.properties.get("PORT", "7077")
            url = f"http://{host}:{port}"
        parts = urlsplit(url)
        if parts.scheme not in ("http",):
            raise _storage_error()(
                f"remote storage URL must be http:// (got {url!r}); for TLS "
                "terminate at a proxy in front of the storage server")
        self.host = parts.hostname or "127.0.0.1"
        self.port = parts.port or 7077
        self.auth_key = config.properties.get("AUTHKEY")
        self.timeout = float(config.properties.get("TIMEOUT", "60"))
        from incubator_predictionio_tpu.utils.http import (
            ClientConnectionPool,
            RetryPolicy,
        )

        self._pool = ClientConnectionPool(self.host, self.port,
                                          self.timeout)
        # the shared client retry choreography (utils/http.RetryPolicy):
        # one re-send over a fresh connection after a short jittered
        # backoff, bounded by the channel timeout as the overall
        # deadline. WHICH failures are safe to re-send stays decided in
        # rpc() below — only it knows whether the body reached the wire.
        self._retry = RetryPolicy(attempts=2, base_delay_s=0.05,
                                  max_delay_s=0.5,
                                  deadline_s=self.timeout)

    def _conn(self) -> http.client.HTTPConnection:
        return self._pool.get()

    def rpc(self, iface: str, prefix: str, method: str,
            args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> Any:
        body = wire.pack({
            "iface": iface, "prefix": prefix, "method": method,
            "args": list(args), "kwargs": kwargs,
        })
        headers = {"Content-Type": "application/x-msgpack"}
        # cross-process trace propagation: a storage RPC issued while
        # serving a request forwards the ambient trace ID + this hop's
        # parent span, so the storage server's span line joins the tree
        headers.update(obs_trace.client_headers())
        if self.auth_key:
            headers["X-Pio-Storage-Key"] = self.auth_key
        from incubator_predictionio_tpu.utils.http import RetryableError

        # Retryability after a connection failure. Failures BEFORE the
        # request body went out (sent=False: connect error, send error on a
        # stale keep-alive) provably never executed server-side, so any
        # method retries once. After the body was sent, only idempotent
        # methods retry — a write like insert/import may already have
        # executed when the response is lost, and silently re-sending it
        # would commit the payload twice. A timeout after send is never
        # retried even for reads: the server is likely still executing the
        # call, and re-sending would run the same work twice concurrently.
        # The backoff/deadline choreography itself is the shared
        # RetryPolicy (utils/http.py); this closure only CLASSIFIES.
        def attempt() -> bytes:
            conn = self._conn()
            sent = False
            try:
                conn.request("POST", "/rpc", body=body, headers=headers)
                sent = True
                resp = conn.getresponse()
                return resp.read()
            except (http.client.HTTPException, ConnectionError, OSError) as e:
                conn.close()
                retryable = (not sent) or (
                    method in _IDEMPOTENT
                    and not isinstance(e, TimeoutError))
                ambiguous = sent and method not in _IDEMPOTENT
                if not sent:
                    state = "; the request was never sent — it was NOT applied"
                elif method in _IDEMPOTENT:
                    state = ""
                else:
                    state = ("; the call is not idempotent — it may or "
                             "may not have been applied")
                err_cls = (_ambiguous_error() if ambiguous
                           else _storage_error())
                err = err_cls(
                    f"storage server {self.host}:{self.port} failed "
                    f"during {iface}.{method} ({e!r})" + state)
                if retryable:
                    raise RetryableError(err) from e
                raise err from e

        payload = self._retry.call(attempt)
        msg = wire.unpack(payload)
        if msg.get("ok"):
            return msg.get("value")
        ename = msg.get("etype")
        if ename == "UnsupportedMethodError":
            raise _unsupported_error()(msg.get("error", ""))
        etype = _ERROR_TYPES.get(ename) or _storage_error()
        raise etype(msg.get("error", "remote storage error"))

    def close(self) -> None:
        self._pool.close_all()


#: methods safe to re-send after a lost response (reads, and writes whose
#: re-execution is a no-op: init/remove/delete/update are last-wins or
#: existence-keyed; insert/insert_batch/import_interactions are NOT)
_IDEMPOTENT = frozenset({
    "init", "remove", "get", "get_by_name", "get_all", "get_by_appid",
    "get_latest_completed", "get_completed", "find", "aggregate_properties",
    "scan_interactions", "delete", "update",
})


class _RemoteDAO:
    iface = ""

    def __init__(self, client: StorageClient, config: StorageClientConfig,
                 prefix: str = ""):
        self.client = client
        self.prefix = prefix

    def _call(self, method: str, *args: Any, **kwargs: Any) -> Any:
        return self.client.rpc(self.iface, self.prefix, method, args, kwargs)


def _forward(name: str):
    def method(self, *args: Any, **kwargs: Any) -> Any:
        return self._call(name, *args, **kwargs)

    method.__name__ = name
    return method


def _proxy(iface: str, base_cls: type, methods: Tuple[str, ...],
           extra: Dict[str, Any] | None = None) -> type:
    ns: Dict[str, Any] = {m: _forward(m) for m in methods}
    ns["iface"] = iface
    ns.update(extra or {})
    return type(f"Remote{iface}", (_RemoteDAO, base_cls), ns)


def _events_find(self, *args: Any, **kwargs: Any) -> Iterator:
    """Lazy, chunked find: the server streams FIND_CHUNK-sized pages
    through a cursor (server.py _find_rpc), so the wire and the CLIENT
    hold at most one chunk of Event objects at a time. (The server's peak
    depends on the backing backend's own ``find`` — sqlite pre-fetches its
    row set — but row tuples are far lighter than wire-encoded Events, and
    the multi-GB single response this replaces is gone.)"""
    def gen() -> Iterator:
        msg = self._call("find_open", *args, **kwargs)
        cursor = msg["cursor"]
        try:
            while True:
                for event in msg["events"]:
                    yield event
                if msg["done"]:
                    cursor = ""
                    return
                msg = self._call("find_next", cursor)
        finally:
            if cursor:  # abandoned mid-iteration: free the server cursor
                try:
                    self._call("find_close", cursor)
                except Exception:
                    pass

    return gen()


def _events_close(self) -> None:  # connection is client-owned
    return None


def _events_tail_cursor(self, *args: Any, **kwargs: Any) -> Any:
    """Sharded backends return a VectorCursor (a tuple subclass); msgpack
    flattens it to a plain list, so rewrap sequences client-side — the
    freshness controller's ``cursor < last`` reset trigger depends on the
    vector comparison semantics, not just the int() sum."""
    cur = self._call("tail_cursor", *args, **kwargs)
    if isinstance(cur, (list, tuple)):
        return base.VectorCursor(cur)
    return cur


def _events_read_interactions_since(self, cursor, *args: Any,
                                    **kwargs: Any) -> Any:
    if isinstance(cursor, tuple):  # VectorCursor → wire-safe list
        cursor = list(cursor)
    inter, times, append_ms, new_cursor, reset = self._call(
        "read_interactions_since", cursor, *args, **kwargs)
    if isinstance(new_cursor, (list, tuple)):
        new_cursor = base.VectorCursor(new_cursor)
    return inter, times, append_ms, new_cursor, reset


def _events_insert_interactions(self, *args: Any, **kwargs: Any) -> Any:
    """Columnar id-returning insert over the wire, with the capability
    answer cached: a box backed by a store without a columnar write path
    answers UnsupportedMethodError ONCE, and every later call fails
    locally (no per-batch round trip; the EventServer's fast path then
    stays off for the process)."""
    if getattr(self, "_columnar_insert_unsupported", False):
        raise _unsupported_error()(
            "remote backend has no columnar insert (cached answer)")
    try:
        return self._call("insert_interactions", *args, **kwargs)
    except Exception as e:
        if isinstance(e, _unsupported_error()):
            self._columnar_insert_unsupported = True
        raise


RemoteEvents = _proxy(
    "Events", base.Events,
    ("init", "remove", "insert", "insert_batch", "get", "delete",
     "aggregate_properties", "scan_interactions", "import_interactions",
     "replication_status", "replication_read", "replication_apply",
     "replication_configure", "replication_reset"),
    extra={"find": _events_find, "close": _events_close,
           "insert_interactions": _events_insert_interactions,
           "tail_cursor": _events_tail_cursor,
           "read_interactions_since": _events_read_interactions_since},
)
#: find_close retries safely (popping a cursor twice is a no-op). find_open
#: retries too: a stale keep-alive connection otherwise fails the *first*
#: find after an idle period even though the request usually never reached
#: the server, and the worst case — a lost response orphaning one server
#: cursor — is already bounded by the server's idle-age cursor eviction.
#: find_next is stateful by design — a lost pull loses its chunk.
#: Replication verbs are position-keyed: replication_apply carries its
#: from_entry, so a replayed apply whose first send landed is a server-side
#: no-op (local count already past from_entry) — safe to re-send. The rest
#: are reads or idempotent configuration.
_IDEMPOTENT = _IDEMPOTENT | {
    "find_close", "find_open", "tail_cursor", "read_interactions_since",
    "replication_status", "replication_read", "replication_apply",
    "replication_configure", "replication_reset",
}
RemoteApps = _proxy(
    "Apps", base.Apps,
    ("insert", "get", "get_by_name", "get_all", "update", "delete"))
RemoteAccessKeys = _proxy(
    "AccessKeys", base.AccessKeys,
    ("insert", "get", "get_all", "get_by_appid", "update", "delete"))
RemoteChannels = _proxy(
    "Channels", base.Channels,
    ("insert", "get", "get_by_appid", "delete"))
RemoteEngineInstances = _proxy(
    "EngineInstances", base.EngineInstances,
    ("insert", "get", "get_all", "get_latest_completed", "get_completed",
     "update", "delete"))
RemoteEvaluationInstances = _proxy(
    "EvaluationInstances", base.EvaluationInstances,
    ("insert", "get", "get_all", "get_completed", "update", "delete"))
RemoteEngineManifests = _proxy(
    "EngineManifests", base.EngineManifests,
    ("insert", "get", "get_all", "update", "delete"))
RemoteModels = _proxy(
    "Models", base.Models, ("insert", "get", "delete"))


DATA_OBJECTS = {
    "Events": RemoteEvents,
    "Apps": RemoteApps,
    "AccessKeys": RemoteAccessKeys,
    "Channels": RemoteChannels,
    "EngineInstances": RemoteEngineInstances,
    "EngineManifests": RemoteEngineManifests,
    "EvaluationInstances": RemoteEvaluationInstances,
    "Models": RemoteModels,
}
