"""StorageServer — the shared-store network service.

Closes the reference's multi-box deployment topology: there, N event /
prediction servers share state through external services (PostgreSQL via
jdbc/StorageClient.scala:35-60, HBase, Elasticsearch). Here the same role
is played by ONE process owning a local backend (sqlite / cpplog / memory)
and exporting the complete DAO surface over HTTP: any number of
eventservers, prediction servers, and trainers on other boxes point their
``PIO_STORAGE_SOURCES_<N>_TYPE=remote`` at it and see one store.

Protocol: ``POST /rpc`` with a msgpack body
``{iface, prefix, method, args, kwargs}`` (storage/wire.py codec) →
msgpack ``{ok, value}`` / ``{ok: false, etype, error}``. Columnar scans
(``scan_interactions``) travel as raw array buffers, so remote training
ingest stays columnar end-to-end. Optional shared-key auth via the
``X-Pio-Storage-Key`` header (KeyAuthentication.scala's role).

Start via ``pio storageserver`` (cli) or embed :class:`StorageServer`.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional, Tuple

from incubator_predictionio_tpu.data.event import EventValidationError
from incubator_predictionio_tpu.data.storage import (
    StorageError,
    UnsupportedMethodError,
    base,
    wire,
)
from incubator_predictionio_tpu.utils.http import (
    HttpServer,
    Request,
    Response,
    Router,
)

logger = logging.getLogger(__name__)

#: iface → methods callable over RPC (the full DAO surface; everything
#: else 404s, so the server's attack surface is exactly this table).
#: ``find`` is served through the cursor protocol (find_open / find_next /
#: find_close): the response, the wire, and the client stay bounded at one
#: FIND_CHUNK of encoded Events per round trip (the backend's own ``find``
#: sets the server's peak — sqlite pre-fetches row tuples).
_ALLOWED: Dict[str, Tuple[str, ...]] = {
    "Events": (
        "init", "remove", "insert", "insert_batch", "get", "delete",
        "find_open", "find_next", "find_close",
        "aggregate_properties", "scan_interactions",
        "import_interactions", "insert_interactions",
        # speed-layer tail (vector cursors cross the wire as arrays)
        "tail_cursor", "read_interactions_since",
        # async replication verbs (leader: status/read; follower:
        # configure/apply/reset — see cpplog.py and ReplicationTail)
        "replication_status", "replication_read", "replication_apply",
        "replication_configure", "replication_reset",
    ),
    "Apps": ("insert", "get", "get_by_name", "get_all", "update", "delete"),
    "AccessKeys": ("insert", "get", "get_all", "get_by_appid", "update",
                   "delete"),
    "Channels": ("insert", "get", "get_by_appid", "delete"),
    "EngineInstances": ("insert", "get", "get_all", "get_latest_completed",
                        "get_completed", "update", "delete"),
    "EvaluationInstances": ("insert", "get", "get_all", "get_completed",
                            "update", "delete"),
    "EngineManifests": ("insert", "get", "get_all", "update", "delete"),
    "Models": ("insert", "get", "delete"),
}

#: exception types that cross the wire by name (client re-raises them)
_ERROR_TYPES = {
    "StorageError": StorageError,
    "UnsupportedMethodError": UnsupportedMethodError,
    "ValueError": ValueError,
    "KeyError": KeyError,
    "EventValidationError": EventValidationError,
}


#: events per find_next chunk — bounds both sides' memory per round trip
FIND_CHUNK = 5000
#: open cursors kept server-side before idle-age eviction kicks in (a
#: client that abandons iteration mid-way cannot pin server memory forever)
MAX_CURSORS = 64
#: a cursor pulled within this window is presumed live and is not evicted
#: at the soft cap — >MAX_CURSORS genuinely concurrent scans grow the table
#: instead of killing an active iteration mid-find
CURSOR_MIN_IDLE_S = 30.0
#: any cursor idle this long is evicted regardless of table size — bounds
#: the memory an orphaned cursor (e.g. from a retried find_open whose first
#: request did land) can pin on a low-traffic server
CURSOR_TTL_S = 600.0
#: absolute ceiling; beyond this the least-recently-pulled cursor goes even
#: if recently active (logged as possibly live). 2× the soft cap keeps the
#: worst-case memory pin near the old fixed-64 bound while still letting a
#: burst of genuinely concurrent scans complete.
MAX_CURSORS_HARD = MAX_CURSORS * 2


#: which repository kind serves each RPC interface in routed mode —
#: Events → EVENTDATA, Models → MODELDATA, every metadata DAO → METADATA
#: (the same mapping Storage's typed accessors use)
_IFACE_REPOSITORY: Dict[str, str] = {
    "Events": "EVENTDATA",
    "Models": "MODELDATA",
}


class ReplicationTail:
    """Follower-side async replication loop (the read scale-out /
    failover leg of the planet-scale ingest path, docs/production.md).

    Tails a leader StorageServer per (app, writer shard) with
    byte-level frame shipping — the cpplog ``replication_*`` verbs —
    so the follower's segment files stay bit-identical prefixes of the
    leader's: tombstone target indices, sidecars, and hashes all carry
    over, and a training scan on the follower returns exactly what the
    leader's would (read parity). The leader's per-shard REWRITE EPOCH
    is the resync signal: it moves only when segment bytes were
    rewritten (roll/compact/drop/leader restart after a rewrite), never
    on append-only growth, so deletes replicate as ordinary frames and
    a follower resyncs only when it must. Leader-unreachable polls log
    and retry — catch-up after a leader restart is the normal path, not
    an error. Exposes ``pio_replication_lag_events{shard}``."""

    def __init__(self, leader_url: str, local_events: Any, apps,
                 interval_s: float = 0.5, auth_key: Optional[str] = None,
                 prefix: str = "", max_bytes: int = 4 << 20):
        from incubator_predictionio_tpu.data.storage import (
            remote as remote_mod,
        )

        props = {"URL": leader_url}
        if auth_key:
            props["AUTHKEY"] = auth_key
        cfg = base.StorageClientConfig(parallel=False, test=False,
                                       properties=props)
        self._rclient = remote_mod.StorageClient(cfg)
        self.remote = remote_mod.RemoteEvents(self._rclient, cfg,
                                              prefix=prefix)
        self.local = local_events
        self.apps = list(apps)
        self.interval_s = float(interval_s)
        self.max_bytes = int(max_bytes)
        # leader epochs as of the last successful sync, keyed
        # (app, shard); written by the tail thread, read by
        # wait_caught_up callers judging divergence
        self._epochs_mu = threading.Lock()
        self._epochs: Dict[Tuple[int, int], int] = {}  # pio-lint: guarded-by(_epochs_mu)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="pio-replication-tail", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
        self._rclient.close()

    def wait_caught_up(self, timeout_s: float = 30.0) -> bool:
        """Block until every app's follower counts match the leader's
        (tests and failover drills); False on timeout or unreachable
        leader."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                if all(self._lag_total(a) == 0 for a in self.apps):
                    return True
            except Exception:
                pass
            if self._stop.wait(0.05):
                return False
        return False

    def _lag_total(self, app_id: int) -> int:
        st = self.remote.replication_status(app_id)
        lst = {s["shard"]: s
               for s in self.local.replication_status(app_id)["status"]}
        lag = 0
        with self._epochs_mu:
            epochs = dict(self._epochs)
        for rs in st["status"]:
            k = rs["shard"]
            ls = lst.get(k, {"cold": 0, "hot": 0, "total": 0})
            if (epochs.get((app_id, k)) != rs["epoch"]
                    or int(ls["cold"]) > int(rs["cold"])
                    or int(ls["hot"]) > int(rs["hot"])):
                # divergent prefix (leader compacted/restarted under
                # us): counts can COINCIDE while the bytes differ, so
                # the shard is behind until the next pass resets and
                # re-pulls it — never report 0 here
                lag += max(int(rs["total"]), 1)
                continue
            lag += max(int(rs["total"]) - int(ls["total"]), 0)
        return lag

    def _run(self) -> None:
        while not self._stop.is_set():
            for app_id in self.apps:
                if self._stop.is_set():
                    break
                try:
                    self._sync_app(app_id)
                except Exception:
                    # leader down / restarting: catch-up is the normal
                    # path — keep polling
                    logger.warning(
                        "replication poll failed for app %s (leader "
                        "unreachable? retrying)", app_id, exc_info=True)
            self._stop.wait(self.interval_s)

    def _sync_app(self, app_id: int) -> None:
        from incubator_predictionio_tpu.data.storage import StorageError
        from incubator_predictionio_tpu.obs import metrics as obs_metrics

        st = self.remote.replication_status(app_id)
        self.local.replication_configure(app_id, shards=st["shards"])
        lst = {s["shard"]: s
               for s in self.local.replication_status(app_id)["status"]}
        gauge = obs_metrics.REGISTRY.gauge(
            "pio_replication_lag_events",
            "events the follower trails the leader by, per writer shard",
            labels=("shard",))
        for rs in st["status"]:
            k = rs["shard"]
            ls = lst.get(k, {"cold": 0, "hot": 0})
            key = (app_id, k)
            # resync on a rewrite-epoch move, or when the leader's file
            # went BACKWARDS past our prefix (restart with a torn tail)
            with self._epochs_mu:
                epoch_seen = self._epochs.get(key)
            if (epoch_seen != rs["epoch"]
                    or int(ls["cold"]) > int(rs["cold"])
                    or int(ls["hot"]) > int(rs["hot"])):
                self.local.replication_reset(app_id, shard=k)
                with self._epochs_mu:
                    self._epochs[key] = rs["epoch"]
                ls = {"cold": 0, "hot": 0}
            applied = 0
            try:
                for tier in ("cold", "hot"):
                    at = int(ls[tier])
                    want = int(rs[tier])
                    while at < want and not self._stop.is_set():
                        chunk = self.remote.replication_read(
                            app_id, shard=k, tier=tier, from_entry=at,
                            epoch=rs["epoch"],
                            max_bytes=self.max_bytes)
                        if not chunk["n_entries"]:
                            break
                        at = int(self.local.replication_apply(
                            app_id, shard=k, tier=tier, from_entry=at,
                            frames=chunk["frames"]))
                    applied += at
            except StorageError:
                # epoch moved mid-pull: next poll resyncs cleanly
                logger.info("replication epoch moved mid-pull "
                            "(app %s shard %d)", app_id, k)
                continue
            gauge.labels(shard=str(k)).set(
                max(int(rs["total"]) - applied, 0))


class StorageServer:
    """A storage source exported over HTTP.

    Two modes: a single backing backend (module, client, config), or —
    with ``module=None`` — REPOSITORY-ROUTED: each RPC interface resolves
    through this process's own `PIO_STORAGE_REPOSITORIES_*` env the way
    local Storage accessors do (Events to the EVENTDATA source, Models to
    MODELDATA, metadata DAOs to METADATA). Routed mode is what `pio
    storageserver` runs by default, so ONE box A process can own
    sqlite metadata + a cpplog event store + model blobs at once (the
    production 3-box topology, docs/production.md)."""

    def __init__(
        self,
        module: Any,
        client: Any,
        config: Optional[base.StorageClientConfig],
        host: str = "0.0.0.0",
        port: int = 0,
        auth_key: Optional[str] = None,
    ):
        self.module = module
        self.client = client
        self.config = config
        self.auth_key = auth_key
        self.replication: Optional[ReplicationTail] = None
        self._daos: Dict[Tuple[str, str], Any] = {}
        self._lock = threading.Lock()
        self._cursors: Dict[str, Any] = {}   # insertion-ordered
        self._cursor_seq = 0
        self.http = HttpServer.from_conf(self._router(), host, port,
                                         name="storage")

    @classmethod
    def from_env(cls, source: Optional[str] = None, host: str = "0.0.0.0",
                 port: int = 0, auth_key: Optional[str] = None
                 ) -> "StorageServer":
        """Back the server from the environment: with ``source`` set,
        export that one PIO_STORAGE_SOURCES_<NAME>; with ``source=None``
        (the `pio storageserver` default) run repository-routed."""
        from incubator_predictionio_tpu.data.storage import Storage

        if source:
            client, module, config = Storage._get_client(source)
            srv = cls(module, client, config, host, port, auth_key)
            srv.maybe_start_replication()
            return srv
        # routed mode: resolve every repository's source NOW so a
        # misconfigured box refuses to start instead of failing
        # per-request after printing a healthy banner
        for repo in ("METADATA", "EVENTDATA", "MODELDATA"):
            _ns, source_name = Storage.repository(repo)
            Storage._get_client(source_name)
        srv = cls(None, None, None, host, port, auth_key)
        srv.maybe_start_replication()
        return srv

    def maybe_start_replication(self) -> None:
        """``PIO_REPLICATE_FROM=<leader url>`` turns this storage
        server into an async replication FOLLOWER of that leader:
        a daemon tail thread ships frames for the apps listed in
        ``PIO_REPLICATE_APPS`` (comma-separated, default "1") every
        ``PIO_REPLICATE_INTERVAL_S`` (default 0.5s), and this server
        keeps serving reads — the scale-out/failover replica."""
        import os

        leader = os.environ.get("PIO_REPLICATE_FROM")
        if not leader:
            return
        try:
            apps = [int(a) for a in
                    os.environ.get("PIO_REPLICATE_APPS", "1").split(",")
                    if a.strip()]
        except ValueError:
            logger.error("bad PIO_REPLICATE_APPS; replication disabled")
            return
        try:
            interval = float(
                os.environ.get("PIO_REPLICATE_INTERVAL_S", "0.5"))
        except ValueError:
            interval = 0.5
        # the DAO table-name prefix of the event namespace being
        # replicated — must match the leader's EVENTDATA repository
        # name + "_" (the default mirrors the standard repository
        # config, PIO_STORAGE_REPOSITORIES_EVENTDATA_NAME=pio_event)
        prefix = os.environ.get("PIO_REPLICATE_PREFIX", "pio_event_")
        self.replication = ReplicationTail(
            leader, self._dao("Events", prefix), apps,
            interval_s=interval, prefix=prefix,
            auth_key=os.environ.get("PIO_REPLICATE_AUTHKEY"))
        self.replication.start()
        logger.info("replication follower: tailing %s for apps %s",
                    leader, apps)

    def _dao(self, iface: str, prefix: str) -> Any:
        with self._lock:
            dao = self._daos.get((iface, prefix))
            if dao is None:
                if self.module is not None:
                    module, client, config = (self.module, self.client,
                                              self.config)
                else:  # repository-routed: resolve via this box's env
                    from incubator_predictionio_tpu.data.storage import (
                        Storage,
                    )

                    repo = _IFACE_REPOSITORY.get(iface, "METADATA")
                    _ns, source_name = Storage.repository(repo)
                    client, module, config = Storage._get_client(source_name)
                cls = module.DATA_OBJECTS.get(iface)
                if cls is None:
                    raise StorageError(
                        f"backend {module.__name__} does not implement "
                        f"{iface}")
                dao = cls(client, config, prefix=prefix)
                self._daos[(iface, prefix)] = dao
            return dao

    def _router(self) -> Router:
        r = Router()

        @r.get("/")
        def status(request: Request) -> Response:
            if self.module is None:
                from incubator_predictionio_tpu.data.storage import Storage

                repos = {}
                for repo in ("METADATA", "EVENTDATA", "MODELDATA"):
                    try:
                        _ns, src = Storage.repository(repo)
                        repos[repo] = src
                    except Exception:
                        # can't normally happen: from_env validated the
                        # repos at startup — so an env drift is news
                        logger.exception("repository %s unresolvable", repo)
                        repos[repo] = None
                return Response(200, {
                    "status": "alive",
                    "backend": "repository-routed",
                    "repositories": repos,
                })
            return Response(200, {
                "status": "alive",
                "backend": self.module.__name__.rsplit(".", 1)[-1],
                "interfaces": sorted(self.module.DATA_OBJECTS),
            })

        @r.post("/rpc")
        def rpc(request: Request) -> Response:
            if self.auth_key is not None and \
                    request.headers.get("x-pio-storage-key") != self.auth_key:
                return _packed({"ok": False, "etype": "StorageError",
                                "error": "invalid storage key"}, 401)
            # sweep on EVERY rpc, not just find traffic: an orphaned cursor
            # (lost-response find_open retry, crashed client) on an
            # otherwise-quiet server must still age out past the TTL
            with self._lock:
                self._evict_cursors_locked()
            try:
                msg = wire.unpack(request.body)
                iface = msg["iface"]
                method = msg["method"]
                if method not in _ALLOWED.get(iface, ()):
                    raise StorageError(
                        f"method {iface}.{method} is not exported")
                dao = self._dao(iface, msg.get("prefix", ""))
                if method.startswith("find_"):
                    value = self._find_rpc(dao, method, msg)
                else:
                    impl = getattr(dao, method, None)
                    if impl is None:
                        # optional capability (e.g. columnar
                        # insert_interactions on a backend without a
                        # columnar write path) — typed so clients cache
                        # the answer instead of retrying per request
                        raise UnsupportedMethodError(
                            f"{iface}.{method} is not supported by the "
                            f"{type(dao).__name__} backend")
                    value = impl(
                        *msg.get("args", ()), **msg.get("kwargs", {}))
                return _packed({"ok": True, "value": value})
            except Exception as e:  # error crosses the wire, typed
                etype = type(e).__name__
                if etype not in _ERROR_TYPES:
                    logger.exception("storage rpc failed")
                    etype = "StorageError"
                return _packed({"ok": False, "etype": etype,
                                "error": str(e)})

        from incubator_predictionio_tpu.obs.http import (
            add_metrics_route,
            add_recorder_route,
        )

        add_metrics_route(r)
        # GET /recorder: the flight recorder's metric-history window
        # (obs/recorder.py) — every server records
        add_recorder_route(r)
        return r

    # -- find cursor protocol ---------------------------------------------
    def _find_rpc(self, dao: Any, method: str, msg: Dict[str, Any]) -> Any:
        """Streamed Events.find: open runs the backend query and returns the
        first chunk + a cursor; next pulls more; close releases early."""
        import itertools

        if method == "find_open":
            it = iter(dao.find(*msg.get("args", ()), **msg.get("kwargs", {})))
            events = list(itertools.islice(it, FIND_CHUNK))
            done = len(events) < FIND_CHUNK
            cursor = ""
            if not done:
                with self._lock:
                    self._cursor_seq += 1
                    cursor = f"c{self._cursor_seq}"
                    self._cursors[cursor] = (it, time.monotonic())
                    self._evict_cursors_locked()
            return {"cursor": cursor, "events": events, "done": done}
        cursor = msg.get("args", [""])[0]
        if method == "find_close":
            with self._lock:
                self._cursors.pop(cursor, None)
            return None
        # pop while pulling: backend iterators are not thread-safe, so a
        # concurrent find_next on the same cursor sees "unknown cursor"
        # instead of a torn read
        with self._lock:
            entry = self._cursors.pop(cursor, None)
        if entry is None:
            raise StorageError(
                f"unknown find cursor {cursor!r} (expired, evicted, or "
                "pulled concurrently); re-issue the find")
        it = entry[0]
        events = list(itertools.islice(it, FIND_CHUNK))
        done = len(events) < FIND_CHUNK
        if not done:
            with self._lock:
                # re-insert moves the cursor to the tail, so dict order is
                # least-recently-pulled first — what eviction walks
                self._cursors[cursor] = (it, time.monotonic())
                self._evict_cursors_locked()
        return {"cursor": cursor, "events": events, "done": done}

    def _evict_cursors_locked(self) -> None:
        """Free abandoned cursors by idle age, not raw count: at the soft
        cap only cursors idle ≥ CURSOR_MIN_IDLE_S go (an active slow scan
        among >MAX_CURSORS concurrent finds survives); the hard cap evicts
        the least-recently-pulled regardless, honestly logged."""
        now = time.monotonic()
        # TTL sweep first: orphans (lost-response retries, crashed clients)
        # must not pin backend row sets forever even when the table is small
        while self._cursors:
            oldest = next(iter(self._cursors))
            if now - self._cursors[oldest][1] < CURSOR_TTL_S:
                break
            del self._cursors[oldest]
            logger.warning("evicted find cursor %s past %.0fs TTL",
                           oldest, CURSOR_TTL_S)
        while len(self._cursors) > MAX_CURSORS:
            oldest = next(iter(self._cursors))
            idle = now - self._cursors[oldest][1]
            if idle >= CURSOR_MIN_IDLE_S:
                del self._cursors[oldest]
                logger.warning(
                    "evicted find cursor %s idle %.0fs (abandoned?)",
                    oldest, idle)
            elif len(self._cursors) > MAX_CURSORS_HARD:
                del self._cursors[oldest]
                logger.warning(
                    "evicted find cursor %s at hard cap %d — it was pulled "
                    "%.0fs ago and may have been LIVE; that client's find "
                    "will fail mid-iteration", oldest, MAX_CURSORS_HARD,
                    idle)
            else:
                break  # all remaining cursors recently active; let it grow

    # -- lifecycle ---------------------------------------------------------
    def start_background(self) -> int:
        port = self.http.start_background()
        logger.info("StorageServer listening on :%d (backend %s)",
                    port,
                    self.module.__name__ if self.module is not None
                    else "repository-routed")
        return port

    async def serve_forever(self, on_started=None) -> None:
        """``on_started(port)`` fires after the bind — the ephemeral-
        bind announcement hook the CLI uses with ``--port 0``."""
        await self.http.serve_forever(on_started=on_started)

    def stop(self) -> None:
        self.http.stop()
        if self.replication is not None:
            self.replication.stop()
            self.replication = None
        if self.client is not None:
            self.client.close()
        # routed-mode backend clients belong to the process-global
        # Storage registry (Storage._get_client cache) — closing them
        # here would break this process's own accessors; Storage.reset
        # owns their lifecycle
        with self._lock:
            self._daos.clear()


def _packed(payload: Dict[str, Any], status: int = 200) -> Response:
    return Response(status, body=wire.pack(payload),
                    content_type="application/x-msgpack")
