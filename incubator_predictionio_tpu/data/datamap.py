"""DataMap / PropertyMap — typed JSON property bags.

Behavior parity with the reference's json4s-backed property bag
(reference: data/.../storage/DataMap.scala:40-244, PropertyMap.scala:36-110):
``get`` raises on missing keys, ``opt`` returns None, ``++`` merges with
right-bias, ``--`` removes keys, and ``extract`` converts the whole bag into
a typed dataclass through the canonical JSON codec. PropertyMap adds the
``first_updated`` / ``last_updated`` aggregation timestamps.
"""

from __future__ import annotations

import typing
from datetime import datetime
from typing import Any, Iterator, Mapping, Optional, Type, TypeVar

from incubator_predictionio_tpu.utils import json_codec

T = TypeVar("T")


class DataMapError(KeyError):
    """Raised when a required property is missing or has the wrong type."""


class DataMap(Mapping[str, Any]):
    """An immutable mapping of property names to parsed-JSON values."""

    __slots__ = ("_fields",)

    def __init__(self, fields: Optional[Mapping[str, Any]] = None):
        object.__setattr__(self, "_fields", dict(fields or {}))

    # -- Mapping interface -------------------------------------------------
    def __getitem__(self, key: str) -> Any:
        return self._fields[key]

    def __iter__(self) -> Iterator[str]:
        return iter(self._fields)

    def __len__(self) -> int:
        return len(self._fields)

    def __contains__(self, key: object) -> bool:
        return key in self._fields

    def __eq__(self, other: object) -> bool:
        if isinstance(other, DataMap):
            return self._fields == other._fields
        if isinstance(other, Mapping):
            return self._fields == dict(other)
        return NotImplemented

    def __hash__(self) -> int:  # stable enough for dedup in tests
        return hash(tuple(sorted((k, repr(v)) for k, v in self._fields.items())))

    def __repr__(self) -> str:
        return f"DataMap({self._fields!r})"

    # -- reference API parity ----------------------------------------------
    @property
    def fields(self) -> dict[str, Any]:
        return dict(self._fields)

    def require(self, name: str) -> None:
        """DataMap.require (DataMap.scala:52): raise if field absent."""
        if name not in self._fields:
            raise DataMapError(f"The field {name} is required.")

    def get(self, name: str, as_: Optional[Type[T]] = None) -> Any:
        """Mandatory typed get (DataMap.scala:77). Raises if missing.

        Unlike ``dict.get``, a missing key is an *error* — this matches the
        reference, where ``get[T]`` throws ``DataMapException``. Generic
        ``Mapping`` consumers needing default semantics should use
        :meth:`get_or_else` / :meth:`opt`, or index ``dm.fields``.

        The second argument is a *type*, never a default value; passing a
        non-type raises immediately rather than being silently treated as a
        missing-key fallback.
        """
        if as_ is not None and not isinstance(as_, type) and not typing.get_origin(as_):
            raise TypeError(
                f"DataMap.get second argument must be a type, got {as_!r}; "
                "use get_or_else(name, default) for default-value semantics"
            )
        self.require(name)
        value = self._fields[name]
        if value is None:
            raise DataMapError(f"The required field {name} cannot be null.")
        if as_ is not None:
            return json_codec.extract(as_, value)
        return value

    def opt(self, name: str, as_: Optional[Type[T]] = None) -> Optional[Any]:
        """Optional typed get (DataMap.scala:96 ``getOpt``)."""
        value = self._fields.get(name)
        if value is None:
            return None
        if as_ is not None:
            return json_codec.extract(as_, value)
        return value

    def get_or_else(self, name: str, default: T, as_: Optional[Type[T]] = None) -> T:
        """DataMap.getOrElse (DataMap.scala:116)."""
        got = self.opt(name, as_)
        return default if got is None else got

    def extract(self, cls: Type[T]) -> T:
        """Convert the whole map into a typed dataclass (DataMap.scala:191)."""
        return json_codec.extract(cls, self._fields)

    def __add__(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        """``++`` merge, right-biased (DataMap.scala:137)."""
        merged = dict(self._fields)
        merged.update(dict(other))
        return DataMap(merged)

    def merge(self, other: "DataMap | Mapping[str, Any]") -> "DataMap":
        return self + other

    def __sub__(self, keys: Any) -> "DataMap":
        """``--`` key removal (DataMap.scala:145)."""
        drop = set(keys)
        return DataMap({k: v for k, v in self._fields.items() if k not in drop})

    def without(self, keys: Any) -> "DataMap":
        return self - keys

    @property
    def is_empty(self) -> bool:
        return not self._fields

    @property
    def key_set(self) -> frozenset[str]:
        return frozenset(self._fields)

    def to_jsonable(self) -> dict[str, Any]:
        return dict(self._fields)

    @classmethod
    def from_jsonable(cls, obj: Any) -> "DataMap":
        if isinstance(obj, DataMap):
            return obj
        if obj is None:
            return cls()
        if not isinstance(obj, Mapping):
            raise ValueError(f"DataMap requires a JSON object, got {obj!r}")
        return cls(obj)


class PropertyMap(DataMap):
    """Aggregated entity state with first/last update times
    (reference: data/.../storage/PropertyMap.scala:36-75)."""

    __slots__ = ("first_updated", "last_updated")

    def __init__(
        self,
        fields: Optional[Mapping[str, Any]] = None,
        *,
        first_updated: datetime,
        last_updated: datetime,
    ):
        super().__init__(fields)
        object.__setattr__(self, "first_updated", first_updated)
        object.__setattr__(self, "last_updated", last_updated)

    def __repr__(self) -> str:
        return (
            f"PropertyMap({self.fields!r}, firstUpdated={self.first_updated}, "
            f"lastUpdated={self.last_updated})"
        )

    def __eq__(self, other: object) -> bool:
        if isinstance(other, PropertyMap):
            return (
                self.fields == other.fields
                and self.first_updated == other.first_updated
                and self.last_updated == other.last_updated
            )
        return super().__eq__(other)

    __hash__ = DataMap.__hash__
