"""EntityMap — entity-id-keyed data with a dense index (Experimental).

Parity: data/.../storage/EntityMap.scala:27-99. ``EntityIdIxMap`` wraps a
:class:`~incubator_predictionio_tpu.data.bimap.BiMap` with symmetric
id↔index lookups; ``EntityMap`` adds the per-entity payload (the
aggregated ``PropertyMap`` in the reference's
``PEvents.extractEntityMap``, PEvents.scala:136-160). Templates use it to
carry entity properties alongside the dense row index their factors live
at on device.
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, Iterable, Optional, TypeVar

from incubator_predictionio_tpu.data.bimap import BiMap
from incubator_predictionio_tpu.utils.annotations import experimental

A = TypeVar("A")


@experimental
class EntityIdIxMap:
    """String entity id ↔ dense int index (EntityMap.scala:27-56)."""

    def __init__(self, id_to_ix: BiMap):
        self.id_to_ix = id_to_ix
        self.ix_to_id = id_to_ix.inverse

    @classmethod
    def from_keys(cls, keys: Iterable[str]) -> "EntityIdIxMap":
        return cls(BiMap.string_long(keys))

    def __call__(self, key):
        """id → index for a str key, index → id for an int key (the
        reference's overloaded apply)."""
        if isinstance(key, str):
            return self.id_to_ix[key]
        return self.ix_to_id[key]

    def __contains__(self, key) -> bool:
        if isinstance(key, str):
            return key in self.id_to_ix
        return key in self.ix_to_id

    def get(self, key, default=None):
        if isinstance(key, str):
            return self.id_to_ix.get(key, default)
        return self.ix_to_id.get(key, default)

    get_or_else = get

    def to_dict(self) -> Dict[str, int]:
        return self.id_to_ix.to_dict()

    def __len__(self) -> int:
        return len(self.id_to_ix)

    def take(self, n: int) -> "EntityIdIxMap":
        return EntityIdIxMap(self.id_to_ix.take(n))

    def __repr__(self) -> str:
        return f"EntityIdIxMap({self.id_to_ix!r})"


@experimental
class EntityMap(EntityIdIxMap, Generic[A]):
    """Entity payloads + the dense index (EntityMap.scala:58-99)."""

    def __init__(self, id_to_data: Dict[str, A],
                 id_to_ix: Optional[BiMap] = None):
        super().__init__(
            id_to_ix if id_to_ix is not None
            else BiMap.string_long(id_to_data.keys()))
        self.id_to_data = dict(id_to_data)

    def data(self, key) -> A:
        """Payload by id (str) or dense index (int)."""
        if isinstance(key, str):
            return self.id_to_data[key]
        return self.id_to_data[self.ix_to_id[key]]

    def get_data(self, key, default: Optional[A] = None) -> Optional[A]:
        try:
            return self.data(key)
        except KeyError:
            return default

    def get_or_else_data(self, key, default: Callable[[], A] | A) -> A:
        got = self.get_data(key)
        if got is not None:
            return got
        return default() if callable(default) else default

    def take(self, n: int) -> "EntityMap[A]":
        new_ix = self.id_to_ix.take(n)
        return EntityMap(
            {k: v for k, v in self.id_to_data.items() if k in new_ix},
            new_ix)

    def __repr__(self) -> str:
        return (f"EntityMap(data={len(self.id_to_data)} entities, "
                f"{self.id_to_ix!r})")
