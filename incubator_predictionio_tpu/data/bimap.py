"""BiMap — immutable bidirectional map for ID re-indexing.

Every recommendation template re-indexes string entity IDs to dense integer
indices before matrix work (reference: data/.../storage/BiMap.scala,
``BiMap.stringInt``/``stringLong``; used in
examples/scala-parallel-recommendation/custom-query/src/main/scala/ALSModel.scala).
On TPU the dense-index property is what lets factors live in contiguous
device arrays, so this is the boundary between host-side string IDs and
device-side rows.
"""

from __future__ import annotations

from typing import Dict, Generic, Hashable, Iterable, Iterator, Optional, Tuple, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V", bound=Hashable)


class BiMap(Generic[K, V]):
    """Immutable one-to-one mapping with O(1) lookup in both directions."""

    __slots__ = ("_fwd", "_rev")

    def __init__(self, forward: Dict[K, V]):
        self._fwd: Dict[K, V] = dict(forward)
        self._rev: Dict[V, K] = {v: k for k, v in self._fwd.items()}
        if len(self._rev) != len(self._fwd):
            raise ValueError("BiMap values must be unique")

    # -- constructors (BiMap.scala:140-196) --------------------------------
    @classmethod
    def string_int(cls, keys: Iterable[str]) -> "BiMap[str, int]":
        """Dense 0..n-1 indexing of distinct string keys (BiMap.stringInt)."""
        distinct = dict.fromkeys(keys)  # preserves first-seen order
        return BiMap({k: i for i, k in enumerate(distinct)})

    # stringLong / stringDouble are the same in Python's single int/float types
    string_long = string_int

    # -- lookups -----------------------------------------------------------
    def __call__(self, key: K) -> V:
        return self._fwd[key]

    def __getitem__(self, key: K) -> V:
        return self._fwd[key]

    def get(self, key: K, default: Optional[V] = None) -> Optional[V]:
        return self._fwd.get(key, default)

    def get_or_else(self, key: K, default: V) -> V:
        return self._fwd.get(key, default)

    def __contains__(self, key: K) -> bool:
        return key in self._fwd

    @property
    def inverse(self) -> "BiMap[V, K]":
        """O(1) — shares the two underlying dicts."""
        inv: BiMap[V, K] = BiMap.__new__(BiMap)
        inv._fwd = self._rev
        inv._rev = self._fwd
        return inv

    # -- collection views --------------------------------------------------
    def __len__(self) -> int:
        return len(self._fwd)

    def __iter__(self) -> Iterator[K]:
        return iter(self._fwd)

    def items(self) -> Iterable[Tuple[K, V]]:
        return self._fwd.items()

    def keys(self) -> Iterable[K]:
        return self._fwd.keys()

    def values(self) -> Iterable[V]:
        return self._fwd.values()

    def to_dict(self) -> Dict[K, V]:
        return dict(self._fwd)

    def take(self, n: int) -> "BiMap[K, V]":
        out: Dict[K, V] = {}
        for i, (k, v) in enumerate(self._fwd.items()):
            if i >= n:
                break
            out[k] = v
        return BiMap(out)

    def is_index_prefix_of(self, other: "BiMap[K, int]") -> bool:
        """True when every (key → index) pair of this map holds verbatim
        in ``other`` — i.e. this map's dense index space is an exact
        prefix of the other's. THE compatibility gate of the
        continuation retrain (ops/retrain.py): the traincache tail fold
        interns ids in stable first-seen order, so a prior model's
        BiMaps must satisfy this against the new PreparedData's, or its
        factor rows would seed the wrong entities. Order-independent
        (compares actual pairs, not iteration order), O(len(self))."""
        if len(self) > len(other):
            return False
        get = other._fwd.get
        return all(get(k) == v for k, v in self._fwd.items())

    def __eq__(self, other: object) -> bool:
        return isinstance(other, BiMap) and self._fwd == other._fwd

    def __hash__(self) -> int:
        return hash(frozenset(self._fwd.items()))

    def __repr__(self) -> str:
        return f"BiMap({self._fwd!r})"
