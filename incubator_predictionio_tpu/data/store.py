"""Engine-facing event store facade.

Parity: data/.../store/{LEventStore,PEventStore,Common}.scala — resolves
human-facing app *names* (plus optional channel names) to internal IDs, then
delegates to the event DAO. The reference splits this facade into a local
(iterator) and a parallel (RDD) flavor; on TPU both collapse into one
iterator-based API whose output feeds ``parallel.ingest`` for device sharding
(see base.Events docstring for the rationale).
"""

from __future__ import annotations

from datetime import datetime
from typing import Any, Dict, Iterator, Optional, Sequence, Tuple

from incubator_predictionio_tpu.data.datamap import PropertyMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.data.storage import Storage, UNSET


class EventStoreError(Exception):
    pass


def _resolve(app_name: str, channel_name: Optional[str]) -> Tuple[int, Optional[int]]:
    """appName(+channelName) → (appId, channelId) (store/Common.scala:34-55)."""
    app = Storage.get_meta_data_apps().get_by_name(app_name)
    if app is None:
        raise EventStoreError(
            f"Invalid app name {app_name}. Please use a valid app name."
        )
    if channel_name is None:
        return app.id, None
    channels = Storage.get_meta_data_channels().get_by_appid(app.id)
    for c in channels:
        if c.name == channel_name:
            return app.id, c.id
    raise EventStoreError(
        f"Invalid channel name {channel_name} for app {app_name}."
    )


class EventStore:
    """Query API used by DataSources (PEventStore.scala:35-130)."""

    @staticmethod
    def find(
        app_name: str,
        channel_name: Optional[str] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        entity_type: Optional[str] = None,
        entity_id: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        limit: Optional[int] = None,
        reversed: bool = False,
    ) -> Iterator[Event]:
        app_id, channel_id = _resolve(app_name, channel_name)
        return Storage.get_events().find(
            app_id=app_id,
            channel_id=channel_id,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed=reversed,
        )

    @staticmethod
    def find_by_entity(
        app_name: str,
        entity_type: str,
        entity_id: str,
        channel_name: Optional[str] = None,
        event_names: Optional[Sequence[str]] = None,
        target_entity_type: Any = UNSET,
        target_entity_id: Any = UNSET,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        limit: Optional[int] = None,
        latest: bool = True,
    ) -> Iterator[Event]:
        """LEventStore.findByEntity:61 — newest-first by default."""
        return EventStore.find(
            app_name=app_name,
            channel_name=channel_name,
            start_time=start_time,
            until_time=until_time,
            entity_type=entity_type,
            entity_id=entity_id,
            event_names=event_names,
            target_entity_type=target_entity_type,
            target_entity_id=target_entity_id,
            limit=limit,
            reversed=latest,
        )

    @staticmethod
    def interactions(
        app_name: str,
        channel_name: Optional[str] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_names: Sequence[str] = ("rate",),
        value_prop: Optional[str] = None,
        event_values: Optional[Dict[str, float]] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        default_value: float = 1.0,
        **backend_extras: Any,
    ):
        """Columnar training ingest (base.Events.scan_interactions): the
        TPU-native replacement for the reference's RDD event read
        (PEventStore.find → newAPIHadoopRDD) — streams matching events into
        pre-indexed COO arrays + id tables without per-event objects.

        ``backend_extras`` forwards backend-specific keywords (the cpplog
        backend accepts ``stats``/``shard_sink``/``use_cache``/
        ``seed_cache`` for the sharded-scan sub-metrics and the pipelined
        scan→prep path); passing one to a backend that lacks it raises
        TypeError — callers opting in know their backend."""
        app_id, channel_id = _resolve(app_name, channel_name)
        return Storage.get_events().scan_interactions(
            app_id=app_id,
            channel_id=channel_id,
            entity_type=entity_type,
            target_entity_type=target_entity_type,
            event_names=event_names,
            value_prop=value_prop,
            event_values=event_values,
            start_time=start_time,
            until_time=until_time,
            default_value=default_value,
            **backend_extras,
        )

    @staticmethod
    def tail_cursor(app_name: str, channel_name: Optional[str] = None) -> int:
        """Monotonic write cursor of the app's event log, or -1 when the
        backend has no cheap tail (base.Events.tail_cursor) — the speed
        layer's poll anchor."""
        app_id, channel_id = _resolve(app_name, channel_name)
        return Storage.get_events().tail_cursor(app_id, channel_id)

    @staticmethod
    def read_interactions_since(
        cursor: int,
        app_name: str,
        channel_name: Optional[str] = None,
        entity_type: str = "user",
        target_entity_type: str = "item",
        event_names: Sequence[str] = ("rate",),
        value_prop: Optional[str] = None,
        event_values: Optional[Dict[str, float]] = None,
        default_value: float = 1.0,
    ):
        """Columnar scan of only the events written since ``cursor`` →
        (Interactions, times_ms, append_ms, new_cursor, reset). O(delta):
        the speed layer polls this to maintain its dirty set between
        retrains; ``append_ms`` carries each row's wall-clock APPEND
        stamp (the end-to-end freshness anchor, -1 when the backend
        cannot attribute one — base.Events.read_interactions_since);
        ``reset=True`` means the log was rewritten (compaction/drop) and
        everything derived from older cursors must be dropped."""
        app_id, channel_id = _resolve(app_name, channel_name)
        return Storage.get_events().read_interactions_since(
            cursor, app_id, channel_id,
            entity_type=entity_type,
            target_entity_type=target_entity_type,
            event_names=event_names,
            value_prop=value_prop,
            event_values=event_values,
            default_value=default_value,
        )

    @staticmethod
    def aggregate_properties(
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        required: Optional[Sequence[str]] = None,
    ) -> Dict[str, PropertyMap]:
        """PEventStore.aggregateProperties:99."""
        app_id, channel_id = _resolve(app_name, channel_name)
        return Storage.get_events().aggregate_properties(
            app_id=app_id,
            channel_id=channel_id,
            entity_type=entity_type,
            start_time=start_time,
            until_time=until_time,
            required=required,
        )

    @staticmethod
    def extract_entity_map(
        app_name: str,
        entity_type: str,
        channel_name: Optional[str] = None,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        required: Optional[Sequence[str]] = None,
    ):
        """Aggregated entity properties keyed by id AND a dense index
        (PEvents.extractEntityMap:136-160) — the form templates feed
        factor tables from."""
        from incubator_predictionio_tpu.data.entity_map import EntityMap

        return EntityMap(EventStore.aggregate_properties(
            app_name=app_name, entity_type=entity_type,
            channel_name=channel_name, start_time=start_time,
            until_time=until_time, required=required,
        ))

    @staticmethod
    def write(
        events: Sequence[Event],
        app_name: str,
        channel_name: Optional[str] = None,
    ) -> list[str]:
        """Bulk insert (PEvents.write:184, used by `pio import`)."""
        app_id, channel_id = _resolve(app_name, channel_name)
        return Storage.get_events().insert_batch(
            list(events), app_id, channel_id)

    @staticmethod
    def delete(
        event_ids: Sequence[str],
        app_name: str,
        channel_name: Optional[str] = None,
    ) -> int:
        app_id, channel_id = _resolve(app_name, channel_name)
        dao = Storage.get_events()
        return sum(1 for eid in event_ids if dao.delete(eid, app_id, channel_id))
