"""Replay of ``$set`` / ``$unset`` / ``$delete`` into entity property state.

Behavior parity with the reference's aggregators (data/.../storage/
LEventAggregator.scala:42-148 and PEventAggregator.scala:90-212): events are
ordered by event time; ``$set`` merges properties right-biased, ``$unset``
removes the named keys, ``$delete`` resets the entity to non-existent; other
event names do not affect property state. First/last updated times track only
the special events. An entity whose final state is "deleted" is filtered out.

The parallel (RDD ``aggregateByKey``) variant collapses here into the same
pure function: the TPU build does event aggregation on host (it is string /
dict work, not FLOPs) and only the *numeric* training data crosses to device.
"""

from __future__ import annotations

import dataclasses
from datetime import datetime
from typing import Dict, Iterable, Optional

from incubator_predictionio_tpu.data.datamap import DataMap, PropertyMap
from incubator_predictionio_tpu.data.event import Event
from incubator_predictionio_tpu.utils.times import to_millis

#: Event names that control aggregation (LEventAggregator.scala:92).
AGGREGATOR_EVENT_NAMES = ("$set", "$unset", "$delete")


@dataclasses.dataclass
class _Prop:
    dm: Optional[DataMap] = None
    first_updated: Optional[datetime] = None
    last_updated: Optional[datetime] = None


def _step(p: _Prop, e: Event) -> _Prop:
    if e.event == "$set":
        dm = e.properties if p.dm is None else p.dm + e.properties
    elif e.event == "$unset":
        dm = None if p.dm is None else p.dm - e.properties.key_set
    elif e.event == "$delete":
        dm = None
    else:
        return p
    first = e.event_time if p.first_updated is None else min(p.first_updated, e.event_time)
    last = e.event_time if p.last_updated is None else max(p.last_updated, e.event_time)
    return _Prop(dm=dm, first_updated=first, last_updated=last)


def _finish(p: _Prop) -> Optional[PropertyMap]:
    if p.dm is None:
        return None
    assert p.first_updated is not None and p.last_updated is not None
    return PropertyMap(
        p.dm.fields, first_updated=p.first_updated, last_updated=p.last_updated
    )


def aggregate_properties_single(events: Iterable[Event]) -> Optional[PropertyMap]:
    """Aggregate one entity's events (LEventAggregator.scala:68-90).

    The defensive sort runs at the ORDER CONTRACT's granularity — epoch
    MILLIS (base.Events.find docstring): durable backends store millis,
    so two events differing only at microsecond precision are a TIE that
    must replay in find/insertion order on every backend. Sorting by the
    raw datetime here once re-ordered such ties on the memory backend
    (which hands back original microseconds) and made the SAME $set
    sequence aggregate differently than on sqlite/cpplog — caught by the
    differential fuzz. Python's sort is stable, so on conforming
    (find-ordered) input this is a no-op."""
    p = _Prop()
    for e in sorted(events, key=lambda e: to_millis(e.event_time)):
        p = _step(p, e)
    return _finish(p)


def aggregate_properties(events: Iterable[Event]) -> Dict[str, PropertyMap]:
    """Aggregate events grouped by entityId (LEventAggregator.scala:42-62).

    Callers are expected to pre-filter to a single entityType (the event DAO
    query does this, LEvents.futureAggregateProperties).
    """
    by_entity: Dict[str, list[Event]] = {}
    for e in events:
        by_entity.setdefault(e.entity_id, []).append(e)
    out: Dict[str, PropertyMap] = {}
    for entity_id, group in by_entity.items():
        pm = aggregate_properties_single(group)
        if pm is not None:
            out[entity_id] = pm
    return out
