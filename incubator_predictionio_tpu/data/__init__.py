"""Event data model and storage (reference: data/src/main/scala/.../data/)."""

from incubator_predictionio_tpu.data.datamap import DataMap, PropertyMap
from incubator_predictionio_tpu.data.event import Event, validate_event
from incubator_predictionio_tpu.data.bimap import BiMap

__all__ = ["DataMap", "PropertyMap", "Event", "validate_event", "BiMap"]
