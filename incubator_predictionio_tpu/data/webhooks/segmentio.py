"""SegmentIO webhook connector.

Parity: data/.../webhooks/segmentio/SegmentIOConnector.scala:24-200 —
handles identify / track / alias / page / screen / group message types;
the event name is the message type, the entity is the user
(``userId`` falling back to ``anonymousId``), and the type-specific payload
lands in ``properties``.
"""

from __future__ import annotations

from typing import Any, Dict

from incubator_predictionio_tpu.data.webhooks import ConnectorError, JsonConnector

_TYPE_PROPERTIES = {
    # message type -> fields copied into event properties
    "identify": ("traits",),
    "track": ("properties", "event"),
    "alias": ("previousId", "userId"),
    "page": ("name", "properties"),
    "screen": ("name", "properties"),
    "group": ("groupId", "traits"),
}


class SegmentIOConnector(JsonConnector):
    def to_event_json(self, data: Dict[str, Any]) -> Dict[str, Any]:
        if "version" not in data:
            raise ConnectorError("Failed to get segment.io API version.")
        msg_type = data.get("type")
        if msg_type not in _TYPE_PROPERTIES:
            raise ConnectorError(
                f"Cannot convert unknown type {msg_type} to event JSON."
            )
        user_id = data.get("userId") or data.get("anonymousId")
        if not user_id:
            raise ConnectorError(
                "there was no `userId` or `anonymousId` in the common fields."
            )
        properties: Dict[str, Any] = {}
        for field in _TYPE_PROPERTIES[msg_type]:
            if data.get(field) is not None:
                properties[field] = data[field]
        if data.get("context") is not None:
            properties["context"] = data["context"]
        event: Dict[str, Any] = {
            "event": msg_type,
            "entityType": "user",
            "entityId": user_id,
            "properties": properties,
        }
        if data.get("timestamp"):
            event["eventTime"] = data["timestamp"]
        return event
