"""Example webhook connectors — the connector-author documentation pair.

Parity: data/.../webhooks/examplejson/ExampleJsonConnector.scala and
exampleform/ExampleFormConnector.scala — the reference ships these as the
template for writing connectors, exercised by their own specs. Payload
shapes handled (same as the reference docstrings):

UserAction (json)::

    {"type": "userAction", "userId": "as34smg4", "event": "do_something",
     "context": {...}, "anotherProperty1": 100,
     "anotherProperty2": "optional1", "timestamp": "2015-01-02T00:30:12Z"}

UserActionItem (json) adds ``itemId`` and targets an item entity. The form
connector takes the same logical input flattened into form fields, with
``context[ip]``-style bracketed keys for the nested context object.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from incubator_predictionio_tpu.data.webhooks import (
    ConnectorError,
    FormConnector,
    JsonConnector,
)


class ExampleJsonConnector(JsonConnector):
    """ExampleJsonConnector.scala:63-155."""

    def to_event_json(self, data: Dict[str, Any]) -> Dict[str, Any]:
        msg_type = data.get("type")
        if msg_type is None:
            raise ConnectorError("The field 'type' is required.")
        try:
            if msg_type == "userAction":
                return self._user_action(data)
            if msg_type == "userActionItem":
                return self._user_action_item(data)
        except ConnectorError:
            raise
        except Exception as exc:
            raise ConnectorError(
                f"Cannot convert {data} to event JSON. {exc}"
            ) from exc
        raise ConnectorError(
            f"Cannot convert unknown type '{msg_type}' to Event JSON."
        )

    @staticmethod
    def _require(data: Dict[str, Any], *names: str) -> None:
        for name in names:
            if name not in data:
                raise ConnectorError(f"The field '{name}' is required.")

    def _user_action(self, data: Dict[str, Any]) -> Dict[str, Any]:
        self._require(data, "userId", "event", "anotherProperty1", "timestamp")
        properties: Dict[str, Any] = {
            "anotherProperty1": int(data["anotherProperty1"]),
        }
        if data.get("context") is not None:
            properties["context"] = data["context"]
        if data.get("anotherProperty2") is not None:
            properties["anotherProperty2"] = data["anotherProperty2"]
        return {
            "event": data["event"],
            "entityType": "user",
            "entityId": data["userId"],
            "eventTime": data["timestamp"],
            "properties": properties,
        }

    def _user_action_item(self, data: Dict[str, Any]) -> Dict[str, Any]:
        self._require(data, "userId", "event", "itemId", "context", "timestamp")
        properties: Dict[str, Any] = {"context": data["context"]}
        if data.get("anotherPropertyA") is not None:
            properties["anotherPropertyA"] = float(data["anotherPropertyA"])
        if data.get("anotherPropertyB") is not None:
            properties["anotherPropertyB"] = bool(data["anotherPropertyB"])
        return {
            "event": data["event"],
            "entityType": "user",
            "entityId": data["userId"],
            "targetEntityType": "item",
            "targetEntityId": data["itemId"],
            "eventTime": data["timestamp"],
            "properties": properties,
        }


def _form_context(data: Dict[str, str], required: bool) -> Optional[Dict[str, Any]]:
    """Bracketed two-level form fields → nested context object
    (ExampleFormConnector.scala:80-127). When ``required``, all three
    context fields must be present (the reference's userActionItem path
    accesses each unconditionally, so a missing one raises)."""
    if not required and not any(k.startswith("context[") for k in data):
        return None
    if required:
        for field in ("context[ip]", "context[prop1]", "context[prop2]"):
            if field not in data:
                raise ConnectorError(f"The field '{field}' is required.")
    context: Dict[str, Any] = {}
    if "context[ip]" in data:
        context["ip"] = data["context[ip]"]
    if "context[prop1]" in data:
        context["prop1"] = float(data["context[prop1]"])
    if "context[prop2]" in data:
        context["prop2"] = data["context[prop2]"]
    return context


class ExampleFormConnector(FormConnector):
    """ExampleFormConnector.scala:54-127."""

    def to_event_json(self, data: Dict[str, str]) -> Dict[str, Any]:
        msg_type = data.get("type")
        if msg_type is None:
            raise ConnectorError("The field 'type' is required.")
        try:
            if msg_type == "userAction":
                return self._user_action(data)
            if msg_type == "userActionItem":
                return self._user_action_item(data)
        except ConnectorError:
            raise
        except Exception as exc:
            raise ConnectorError(
                f"Cannot convert {data} to event JSON. {exc}"
            ) from exc
        raise ConnectorError(
            f"Cannot convert unknown type {msg_type} to event JSON"
        )

    def _user_action(self, data: Dict[str, str]) -> Dict[str, Any]:
        properties: Dict[str, Any] = {
            "anotherProperty1": int(data["anotherProperty1"]),
        }
        context = _form_context(data, required=False)
        if context is not None:
            properties["context"] = context
        if "anotherProperty2" in data:
            properties["anotherProperty2"] = data["anotherProperty2"]
        return {
            "event": data["event"],
            "entityType": "user",
            "entityId": data["userId"],
            "eventTime": data["timestamp"],
            "properties": properties,
        }

    def _user_action_item(self, data: Dict[str, str]) -> Dict[str, Any]:
        properties: Dict[str, Any] = {"context": _form_context(data, required=True)}
        if "anotherPropertyA" in data:
            properties["anotherPropertyA"] = float(data["anotherPropertyA"])
        if "anotherPropertyB" in data:
            properties["anotherPropertyB"] = (
                data["anotherPropertyB"].lower() == "true"
            )
        return {
            "event": data["event"],
            "entityType": "user",
            "entityId": data["userId"],
            "targetEntityType": "item",
            "targetEntityId": data["itemId"],
            "eventTime": data["timestamp"],
            "properties": properties,
        }
