"""MailChimp form-webhook connector.

Parity: data/.../webhooks/mailchimp/MailChimpConnector.scala:33-280 —
handles subscribe / unsubscribe / profile / upemail / cleaned / campaign
form posts. MailChimp posts flat form data with bracketed keys
(``data[email]``, ``data[merges][FNAME]``); times use
``yyyy-MM-dd HH:mm:ss`` in UTC.
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Any, Dict

from incubator_predictionio_tpu.data.webhooks import ConnectorError, FormConnector
from incubator_predictionio_tpu.utils.times import format_iso8601


def _parse_time(s: str) -> str:
    dt = datetime.strptime(s, "%Y-%m-%d %H:%M:%S").replace(tzinfo=timezone.utc)
    return format_iso8601(dt)


def _nested(data: Dict[str, str], prefix: str) -> Dict[str, Any]:
    """Collect ``prefix[...]`` keys into a (possibly nested) dict."""
    out: Dict[str, Any] = {}
    for key, value in data.items():
        if not key.startswith(prefix + "["):
            continue
        path = key[len(prefix):]
        parts = [p[:-1] for p in path.split("[")[1:]]  # strip trailing ]
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
            if not isinstance(node, dict):
                # 'data[x]=1&data[x][y]=2' — scalar and nested share a path
                raise ConnectorError(
                    f"Conflicting form keys under '{prefix}[{p}]'"
                )
        node[parts[-1]] = value
    return out


class MailChimpConnector(FormConnector):
    _HANDLERS = {
        "subscribe": ("subscribe", "user", "email"),
        "unsubscribe": ("unsubscribe", "user", "email"),
        "profile": ("profile", "user", "email"),
        "upemail": ("upemail", "user", "new_email"),
        "cleaned": ("cleaned", "user", "email"),
        "campaign": ("campaign", "campaign", "id"),
    }

    def to_event_json(self, data: Dict[str, str]) -> Dict[str, Any]:
        msg_type = data.get("type")
        if msg_type is None:
            raise ConnectorError(
                "The field 'type' is required for MailChimp data."
            )
        if msg_type not in self._HANDLERS:
            raise ConnectorError(
                f"Cannot convert unknown MailChimp data type {msg_type} "
                "to event JSON"
            )
        event_name, entity_type, id_field = self._HANDLERS[msg_type]
        payload = _nested(data, "data")
        entity_id = payload.get(id_field)
        if entity_id is None:
            raise ConnectorError(
                f"The field 'data[{id_field}]' is required for MailChimp "
                f"{msg_type} data."
            )
        properties = {k: v for k, v in payload.items() if k != id_field}
        event: Dict[str, Any] = {
            "event": event_name,
            "entityType": entity_type,
            "entityId": entity_id,
            "properties": properties,
        }
        if data.get("fired_at"):
            try:
                event["eventTime"] = _parse_time(data["fired_at"])
            except ValueError as e:
                raise ConnectorError(f"Invalid fired_at: {e}") from e
        return event
