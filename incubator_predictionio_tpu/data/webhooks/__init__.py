"""Webhooks framework — third-party payloads → Event JSON.

Parity: data/.../webhooks/ — ``JsonConnector.toEventJson`` and
``FormConnector.toEventJson`` SPI (JsonConnector.scala:32,
FormConnector.scala:33), with the SegmentIO and MailChimp connectors and an
explicit registry replacing the reference's ``WebhooksConnectors`` object.
"""

from __future__ import annotations

import abc
from typing import Any, Dict


class ConnectorError(ValueError):
    """webhooks/ConnectorException.scala."""


class JsonConnector(abc.ABC):
    """Translates a JSON webhook payload to Event JSON (JsonConnector.scala:32)."""

    @abc.abstractmethod
    def to_event_json(self, data: Dict[str, Any]) -> Dict[str, Any]: ...


class FormConnector(abc.ABC):
    """Translates form-encoded webhook data to Event JSON (FormConnector.scala:33)."""

    @abc.abstractmethod
    def to_event_json(self, data: Dict[str, str]) -> Dict[str, Any]: ...


_JSON_CONNECTORS: Dict[str, JsonConnector] = {}
_FORM_CONNECTORS: Dict[str, FormConnector] = {}


def register_json_connector(name: str, connector: JsonConnector) -> None:
    _JSON_CONNECTORS[name] = connector


def register_form_connector(name: str, connector: FormConnector) -> None:
    _FORM_CONNECTORS[name] = connector


def json_connector(name: str) -> JsonConnector | None:
    _ensure_builtin()
    return _JSON_CONNECTORS.get(name)


def form_connector(name: str) -> FormConnector | None:
    _ensure_builtin()
    return _FORM_CONNECTORS.get(name)


_loaded = False


def _ensure_builtin() -> None:
    """Built-in connector registry (WebhooksConnectors.scala:29-34)."""
    global _loaded
    if _loaded:
        return
    from incubator_predictionio_tpu.data.webhooks.segmentio import (
        SegmentIOConnector,
    )
    from incubator_predictionio_tpu.data.webhooks.mailchimp import (
        MailChimpConnector,
    )

    _JSON_CONNECTORS.setdefault("segmentio", SegmentIOConnector())
    _FORM_CONNECTORS.setdefault("mailchimp", MailChimpConnector())
    _loaded = True
