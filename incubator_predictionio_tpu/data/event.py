"""The Event record and its validation rules.

Behavior parity with reference data/.../storage/Event.scala:42-167:
the immutable event record (name, entity, optional target entity, property
``DataMap``, event time, tags, prId, creation time) and the full reserved-name
validation matrix for ``$set`` / ``$unset`` / ``$delete`` and the ``pio_``
prefix.
"""

from __future__ import annotations

import dataclasses
import uuid
from datetime import datetime
from typing import Any, Optional, Sequence

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.utils.times import (
    ensure_aware,
    format_iso8601,
    now_utc,
    parse_iso8601,
)

#: Reserved single-entity event names (Event.scala:83).
SPECIAL_EVENTS = frozenset({"$set", "$unset", "$delete"})

#: Built-in entity types allowed to carry the reserved prefix (Event.scala:146).
BUILTIN_ENTITY_TYPES = frozenset({"pio_pr"})

#: Built-in properties (Event.scala:149 — currently empty).
BUILTIN_PROPERTIES: frozenset[str] = frozenset()


def is_reserved_prefix(name: str) -> bool:
    """True for names starting with ``$`` or ``pio_`` (Event.scala:77)."""
    return name.startswith("$") or name.startswith("pio_")


def is_special_event(name: str) -> bool:
    return name in SPECIAL_EVENTS


@dataclasses.dataclass(frozen=True)
class Event:
    """One event in the event store (Event.scala:42-53)."""

    event: str
    entity_type: str
    entity_id: str
    target_entity_type: Optional[str] = None
    target_entity_id: Optional[str] = None
    properties: DataMap = dataclasses.field(default_factory=DataMap)
    event_time: datetime = dataclasses.field(default_factory=now_utc)
    tags: tuple[str, ...] = ()
    pr_id: Optional[str] = None
    creation_time: datetime = dataclasses.field(default_factory=now_utc)
    event_id: Optional[str] = None

    def __post_init__(self) -> None:
        if not isinstance(self.properties, DataMap):
            object.__setattr__(self, "properties", DataMap(self.properties))
        if isinstance(self.tags, list):
            object.__setattr__(self, "tags", tuple(self.tags))
        # Naive datetimes are interpreted as UTC (the reference's default
        # zone, Event.scala:70) so ordering comparisons never mix aware/naive.
        object.__setattr__(self, "event_time", ensure_aware(self.event_time))
        object.__setattr__(self, "creation_time", ensure_aware(self.creation_time))

    def with_id(self, event_id: str) -> "Event":
        return dataclasses.replace(self, event_id=event_id)

    # -- wire format (EventJson4sSupport semantics: data/.../storage/EventJson4sSupport.scala)
    def to_jsonable(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "eventId": self.event_id,
            "event": self.event,
            "entityType": self.entity_type,
            "entityId": self.entity_id,
            "targetEntityType": self.target_entity_type,
            "targetEntityId": self.target_entity_id,
            "properties": self.properties.to_jsonable(),
            "eventTime": format_iso8601(self.event_time),
            "tags": list(self.tags),
            "prId": self.pr_id,
            "creationTime": format_iso8601(self.creation_time),
        }
        return {k: v for k, v in out.items() if v is not None}

    @classmethod
    def from_jsonable(cls, obj: dict[str, Any]) -> "Event":
        """Build (and validate field types of) an Event from API JSON."""
        if not isinstance(obj, dict):
            raise ValueError(f"Event requires a JSON object, got {obj!r}")

        def _opt_str(key: str) -> Optional[str]:
            v = obj.get(key)
            if v is not None and not isinstance(v, str):
                raise ValueError(f"field {key} must be a string, got {v!r}")
            return v

        event = obj.get("event")
        if not isinstance(event, str):
            raise ValueError("field event is required and must be a string")
        entity_type = obj.get("entityType")
        entity_id = obj.get("entityId")
        if not isinstance(entity_type, str) or not isinstance(entity_id, str):
            raise ValueError("fields entityType and entityId are required strings")

        properties = obj.get("properties")
        if properties is None:
            properties = {}
        if not isinstance(properties, dict):
            raise ValueError("field properties must be a JSON object")

        # Absent/null times default to receive time; malformed values (e.g.
        # empty strings) must fail loudly, as the reference's joda parser does.
        event_time = (
            parse_iso8601(obj["eventTime"])
            if obj.get("eventTime") is not None
            else now_utc()
        )
        creation_time = (
            parse_iso8601(obj["creationTime"])
            if obj.get("creationTime") is not None
            else now_utc()
        )
        tags = obj.get("tags") or []
        if not isinstance(tags, list) or not all(isinstance(t, str) for t in tags):
            raise ValueError("field tags must be an array of strings")

        return cls(
            event=event,
            entity_type=entity_type,
            entity_id=entity_id,
            target_entity_type=_opt_str("targetEntityType"),
            target_entity_id=_opt_str("targetEntityId"),
            properties=DataMap(properties),
            event_time=event_time,
            tags=tuple(tags),
            pr_id=_opt_str("prId"),
            creation_time=creation_time,
            event_id=_opt_str("eventId"),
        )


def new_event_id() -> str:
    """Generate a unique event ID (the reference derives one from the HBase
    row key, HBEventsUtil.RowKey:84-132; a UUID serves the same purpose)."""
    return uuid.uuid4().hex


class EventValidationError(ValueError):
    """Raised when an event violates the reserved-name/shape rules."""


def validate_event(e: Event) -> None:
    """Full validation matrix (Event.scala:112-143)."""

    def check(cond: bool, msg: str) -> None:
        if not cond:
            raise EventValidationError(msg)

    check(bool(e.event), "event must not be empty.")
    check(bool(e.entity_type), "entityType must not be empty string.")
    check(bool(e.entity_id), "entityId must not be empty string.")
    check(e.target_entity_type != "", "targetEntityType must not be empty string")
    check(e.target_entity_id != "", "targetEntityId must not be empty string.")
    check(
        (e.target_entity_type is None) == (e.target_entity_id is None),
        "targetEntityType and targetEntityId must be specified together.",
    )
    check(
        not (e.event == "$unset" and e.properties.is_empty),
        "properties cannot be empty for $unset event",
    )
    check(
        not is_reserved_prefix(e.event) or is_special_event(e.event),
        f"{e.event} is not a supported reserved event name.",
    )
    check(
        not is_special_event(e.event)
        or (e.target_entity_type is None and e.target_entity_id is None),
        f"Reserved event {e.event} cannot have targetEntity",
    )
    check(
        not is_reserved_prefix(e.entity_type)
        or e.entity_type in BUILTIN_ENTITY_TYPES,
        f"The entityType {e.entity_type} is not allowed. "
        "'pio_' is a reserved name prefix.",
    )
    if e.target_entity_type is not None:
        check(
            not is_reserved_prefix(e.target_entity_type)
            or e.target_entity_type in BUILTIN_ENTITY_TYPES,
            f"The targetEntityType {e.target_entity_type} is not allowed. "
            "'pio_' is a reserved name prefix.",
        )
    for k in e.properties.key_set:
        check(
            not is_reserved_prefix(k) or k in BUILTIN_PROPERTIES,
            f"The property {k} is not allowed. 'pio_' is a reserved name prefix.",
        )


def validate_events(events: Sequence[Event]) -> None:
    for e in events:
        validate_event(e)
