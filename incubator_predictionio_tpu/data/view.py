"""Batch views (deprecated) — predicate + aggregator views over event lists.

Parity: data/.../view/{LBatchView,PBatchView,DataView}.scala. The reference
deprecated these in favour of LEvents/LEventStore (``@deprecated("Use
LEvents …", "0.9.2")``, LBatchView.scala:31) but ships them; the same
capability here is a thin functional layer over an in-memory event sequence.
The reference's L (local Seq) / P (RDD) split collapses: a Python sequence
feeds either the host path or ``parallel.ingest`` directly.

``DataView.create`` in the reference builds a Spark DataFrame
(DataView.scala:39-60); ``data_view`` returns flat row dicts, the
tabular-analysis equivalent in a Spark-free runtime.
"""

from __future__ import annotations

import warnings
from datetime import datetime
from typing import Any, Callable, Dict, Iterable, List, Optional, TypeVar

from incubator_predictionio_tpu.data.datamap import DataMap
from incubator_predictionio_tpu.data.event import Event

T = TypeVar("T")

_DEPRECATION = "Batch views are deprecated; use Events DAO / EventStore instead."


def _predicate(
    start_time: Optional[datetime] = None,
    until_time: Optional[datetime] = None,
    entity_type: Optional[str] = None,
    event: Optional[str] = None,
) -> Callable[[Event], bool]:
    """ViewPredicates (LBatchView.scala:32-68): startTime is *exclusive* in
    the reference's predicate, untilTime exclusive-end."""
    def pred(e: Event) -> bool:
        if start_time is not None and e.event_time <= start_time:
            return False
        if until_time is not None and e.event_time >= until_time:
            return False
        if entity_type is not None and e.entity_type != entity_type:
            return False
        if event is not None and e.event != event:
            return False
        return True
    return pred


def data_map_aggregator() -> Callable[[Optional[DataMap], Event], Optional[DataMap]]:
    """ViewAggregators.getDataMapAggregator (LBatchView.scala:70-94):
    fold $set/$unset/$delete into an optional property map."""
    def agg(p: Optional[DataMap], e: Event) -> Optional[DataMap]:
        if e.event == "$set":
            return e.properties if p is None else p + e.properties
        if e.event == "$unset":
            return None if p is None else p - e.properties.key_set
        if e.event == "$delete":
            return None
        return p
    return agg


class BatchView:
    """LBatchView/PBatchView — filtered, aggregated views over events.

    (LBatchView.scala:96-160: ``events.filter(...)``, ``aggregateByEntityOrdered``.)
    """

    def __init__(self, events: Iterable[Event]):
        warnings.warn(_DEPRECATION, DeprecationWarning, stacklevel=2)
        self._events: List[Event] = sorted(events, key=lambda e: e.event_time)

    @property
    def events(self) -> List[Event]:
        return list(self._events)

    def filter(
        self,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
        entity_type: Optional[str] = None,
        event: Optional[str] = None,
    ) -> List[Event]:
        pred = _predicate(start_time, until_time, entity_type, event)
        return [e for e in self._events if pred(e)]

    def aggregate_by_entity_ordered(
        self,
        init: Optional[T],
        op: Callable[[Optional[T], Event], Optional[T]],
        predicate: Optional[Callable[[Event], bool]] = None,
    ) -> Dict[str, Optional[T]]:
        """Fold events per entityId in event-time order
        (LBatchView.aggregateByEntityOrdered)."""
        out: Dict[str, Optional[T]] = {}
        for e in self._events:
            if predicate is not None and not predicate(e):
                continue
            out[e.entity_id] = op(out.get(e.entity_id, init), e)
        return out

    def aggregate_properties(
        self,
        entity_type: str,
        start_time: Optional[datetime] = None,
        until_time: Optional[datetime] = None,
    ) -> Dict[str, DataMap]:
        """The canonical view: current property state per entity
        (LBatchView.scala:150-160)."""
        result = self.aggregate_by_entity_ordered(
            None,
            data_map_aggregator(),
            _predicate(start_time, until_time, entity_type=entity_type),
        )
        return {k: v for k, v in result.items() if v is not None}


def data_view(events: Iterable[Event]) -> List[Dict[str, Any]]:
    """Flat tabular rows from events (DataView.create, DataView.scala:39-60).

    One row per event: scalar columns plus flattened ``properties.<key>``
    columns — the schema the reference derives for its DataFrame.
    """
    warnings.warn(_DEPRECATION, DeprecationWarning, stacklevel=2)
    rows = []
    for e in events:
        row: Dict[str, Any] = {
            "eventId": e.event_id,
            "event": e.event,
            "entityType": e.entity_type,
            "entityId": e.entity_id,
            "targetEntityType": e.target_entity_type,
            "targetEntityId": e.target_entity_id,
            "eventTime": e.event_time,
            "prId": e.pr_id,
        }
        for k, v in e.properties.fields.items():
            row[f"properties.{k}"] = v
        rows.append(row)
    return rows
