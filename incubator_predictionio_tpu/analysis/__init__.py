"""pio-lint — TPU/JAX-aware static analysis for this repo.

The reference caught mis-wired DASE components with Scala's compiler;
this package is the Python/JAX rebuild's equivalent guardrail: an
AST-based rule engine for the repo's documented tracer, sharding and
host-sync hazard classes. Run ``python -m
incubator_predictionio_tpu.analysis --baseline`` (CI does, on the
tier-1 path) or ``scripts/lint.sh``; rules and suppression syntax are
documented in ``docs/lint.md``.
"""

from incubator_predictionio_tpu.analysis.engine import (  # noqa: F401
    Finding,
    Module,
    apply_baseline,
    default_baseline_path,
    lint_paths,
    load_baseline,
    package_root,
    repo_root,
    write_baseline,
)
from incubator_predictionio_tpu.analysis.rules import (  # noqa: F401
    ALL_RULES,
    RULES_BY_NAME,
    Rule,
)
