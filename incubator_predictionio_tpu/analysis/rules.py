"""pio-lint rules: this repo's documented TPU/JAX hazard classes.

Every rule is grounded in a failure that either shipped here or is one
compile away (ADVICE.md, ROUND5.md, docs/performance.md): host syncs
inside traces, numpy-style negative-index wraparound on padding ids,
availability probes that compile a different kernel than production
runs, tracer-boolean branches, import-time env freezes, silent f64→f32
downcasts, wall-clock reads baked into traces, and unlocked shared
state in the async servers. ``docs/lint.md`` documents each rule with
its hazard class and suppression syntax.

Rules are pure AST visitors over :class:`~.engine.Module` — nothing is
imported or executed, so the pass runs in milliseconds with no JAX
backend and cannot be confused by import-time side effects.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from incubator_predictionio_tpu.analysis.engine import (
    CONFIG_MODULE_RE,
    Finding,
    Module,
)


class Rule:
    name: str = ""
    severity: str = "warning"
    #: one-line hazard description for --list-rules and docs
    doc: str = ""

    def check(self, mod: Module) -> Iterator[Finding]:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# 1. host syncs inside traced code
# ---------------------------------------------------------------------------

_HOST_SYNC_CALLS = {
    "jax.device_get",
    "numpy.asarray",
    "numpy.array",
}
_HOST_SYNC_ATTRS = {"block_until_ready", "item"}
#: builtin scalar coercions that force a device fetch when fed a traced
#: value — the per-sweep ``float(delta) < tol`` convergence-check
#: anti-pattern (the probe pattern fetches OUTSIDE the trace, once per
#: PIO_RETRAIN_PROBE_EVERY-sweep chunk; see ops/retrain.py)
_SCALAR_COERCIONS = {"float", "int", "bool"}
_JAX_VALUED_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.ops.", "jax.nn.")


class HostSyncInTrace(Rule):
    name = "host-sync"
    severity = "error"
    doc = ("host-sync call (jax.device_get / .block_until_ready() / "
           "np.asarray / .item() / float()-on-a-traced-value) inside a "
           "jit/pjit/shard_map-traced function — inside a trace these "
           "operate on tracers, either raising TracerError or silently "
           "baking a device round-trip into every step; fetch outside "
           "the trace (e.g. the chunked convergence probe, "
           "ops/retrain.py)")

    def check(self, mod: Module) -> Iterator[Finding]:
        for root, statics in mod.traced_roots:
            params = _param_names(root) - statics
            for node in ast.walk(root):
                if not isinstance(node, ast.Call):
                    continue
                rname = mod.resolved(node.func)
                if rname in _HOST_SYNC_CALLS:
                    yield mod.finding(
                        self, node,
                        f"{rname}() inside traced function "
                        f"{_root_name(root)!r} — move the host sync "
                        "outside the trace")
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _HOST_SYNC_ATTRS
                        and rname not in _HOST_SYNC_CALLS):
                    yield mod.finding(
                        self, node,
                        f".{node.func.attr}() inside traced function "
                        f"{_root_name(root)!r} — move the host sync "
                        "outside the trace")
                elif (isinstance(node.func, ast.Name)
                        and node.func.id in _SCALAR_COERCIONS
                        and len(node.args) == 1
                        and _is_jax_valued(mod, node.args[0], params)):
                    yield mod.finding(
                        self, node,
                        f"{node.func.id}() on a traced value inside "
                        f"{_root_name(root)!r} — a per-step host sync "
                        "(or TracerError); fetch the scalar outside the "
                        "trace (chunked probe pattern)")


def _is_jax_valued(mod: Module, expr: ast.AST,
                   params: "Set[str]") -> bool:
    """Heuristic: the expression is (or contains) a jnp/lax call, or is a
    bare non-static traced parameter — the cases where a builtin scalar
    coercion must materialize a device value."""
    if isinstance(expr, ast.Name):
        return expr.id in params
    return any(
        isinstance(sub, ast.Call)
        and (mod.resolved(sub.func) or "").startswith(_JAX_VALUED_PREFIXES)
        for sub in ast.walk(expr))


def _root_name(root: ast.AST) -> str:
    return getattr(root, "name", "<lambda>")


# ---------------------------------------------------------------------------
# 2. negative-padding gather wraparound
# ---------------------------------------------------------------------------

_IDS_NAME_RE = re.compile(r"(?:^|_)ids?$")
_CLAMP_CALLS = {
    "jax.numpy.maximum", "jax.numpy.minimum", "jax.numpy.clip",
    "jax.numpy.where", "numpy.maximum", "numpy.minimum", "numpy.clip",
    "numpy.where", "jax.numpy.abs",
}


class NegativeGather(Rule):
    name = "neg-gather"
    severity = "warning"
    doc = ("fancy-index gather fed by an *_ids variable that can carry "
           "-1 padding: JAX/numpy wrap negative indices to the LAST row, "
           "so padding rows silently read real data (the ADVICE.md "
           "als.py:518 class) — clamp (jnp.maximum(ids, 0)) and mask "
           "(jnp.where(ids >= 0, ..., 0)) or record the downstream "
           "drop justification in the baseline")

    def check(self, mod: Module) -> Iterator[Finding]:
        # module-scope clamp assignments apply everywhere; function-scope
        # ones only inside their own function (chain) — a clamp in one
        # function must not blind the rule to a same-named raw id in
        # another (clamping is scope-local, not flow-sensitive)
        module_clamped: Set[str] = set()
        stack = list(mod.tree.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            _add_clamp_assign(mod, n, module_clamped)
            stack.extend(ast.iter_child_nodes(n))
        yield from self._visit(mod, mod.tree, frozenset(module_clamped))

    def _visit(self, mod: Module, node: ast.AST,
               clamped: "frozenset[str]") -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                local: Set[str] = set()
                for sub in ast.walk(child):
                    _add_clamp_assign(mod, sub, local)
                yield from self._visit(mod, child, clamped | local)
                continue
            finding = self._check_subscript(mod, child, clamped)
            if finding is not None:
                yield finding
            yield from self._visit(mod, child, clamped)

    def _check_subscript(self, mod: Module, node: ast.AST,
                         clamped: "frozenset[str]") -> Optional[Finding]:
        if not (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Load)):
            return None
        # x.at[ids] carries explicit out-of-bounds semantics
        # (mode="drop"/"fill") — the repo's scatter path
        if (isinstance(node.value, ast.Attribute)
                and node.value.attr == "at"):
            return None
        idx = node.slice
        if not (isinstance(idx, ast.Name)
                and _IDS_NAME_RE.search(idx.id)):
            return None
        if idx.id in clamped:
            return None
        return mod.finding(
            self, node,
            f"gather indexed by {idx.id!r} without a clamp/where "
            "guard — -1 padding ids wrap to the last row")


def _add_clamp_assign(mod: Module, node: ast.AST, into: Set[str]) -> None:
    """Record ``name = jnp.where/maximum/clip(...)``-style assignments."""
    if (isinstance(node, ast.Assign) and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
            and mod.resolved(node.value.func) in _CLAMP_CALLS):
        into.add(node.targets[0].id)


# ---------------------------------------------------------------------------
# 3. availability probes that skip operands production passes
# ---------------------------------------------------------------------------


class ProbeArity(Rule):
    name = "probe-arity"
    severity = "error"
    doc = ("a *_available() probe calls a kernel entry point without one "
           "of its optional array operands — the probe then green-lights "
           "a kernel whose production variant (extra BlockSpec / input "
           "spec) was never compiled on the real backend (the "
           "als_kernel_available/x0 class: interpret passes, Mosaic "
           "fails at the first real train step)")

    def check(self, mod: Module) -> Iterator[Finding]:
        defs = {
            n.name: n for n in ast.walk(mod.tree)
            if isinstance(n, ast.FunctionDef)
        }
        for probe in ast.walk(mod.tree):
            if not (isinstance(probe, ast.FunctionDef)
                    and probe.name.endswith("_available")):
                continue
            for call in ast.walk(probe):
                if not (isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Name)):
                    continue
                callee = defs.get(call.func.id)
                if callee is None:
                    continue
                missing = _unbound_optional_arrays(callee, call)
                for param in missing:
                    yield mod.finding(
                        self, call,
                        f"probe {probe.name!r} never passes the optional "
                        f"array operand {param!r} of {callee.name!r} — "
                        "the production variant's kernel is never "
                        "compiled by the probe")


def _unbound_optional_arrays(
    callee: ast.FunctionDef, call: ast.Call
) -> List[str]:
    """Optional[jax.Array]-annotated params of ``callee`` with default
    None that ``call`` binds neither positionally nor by keyword."""
    args = callee.args
    positional = args.posonlyargs + args.args
    defaults = args.defaults
    # map trailing defaults onto the positional tail
    default_by_name = {}
    for arg, default in zip(positional[len(positional) - len(defaults):],
                            defaults):
        default_by_name[arg.arg] = default
    for arg, default in zip(args.kwonlyargs, args.kw_defaults):
        if default is not None:
            default_by_name[arg.arg] = default

    optional_arrays = []
    for arg in positional + args.kwonlyargs:
        default = default_by_name.get(arg.arg)
        if not (isinstance(default, ast.Constant) and default.value is None):
            continue
        if "jax.Array" in _annotation_text(arg.annotation):
            optional_arrays.append(arg.arg)

    bound = {kw.arg for kw in call.keywords if kw.arg}
    if any(kw.arg is None for kw in call.keywords):  # **kwargs: assume bound
        return []
    n_pos = len(call.args)
    bound |= {a.arg for a in positional[:n_pos]}
    return [p for p in optional_arrays if p not in bound]


def _annotation_text(annotation: Optional[ast.AST]) -> str:
    if annotation is None:
        return ""
    if isinstance(annotation, ast.Constant) and isinstance(
            annotation.value, str):
        return annotation.value
    try:
        return ast.unparse(annotation)
    except Exception:
        return ""


# ---------------------------------------------------------------------------
# 4. Python control flow on tracer values
# ---------------------------------------------------------------------------

_TRACER_VALUED_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.ops.", "jax.nn.")


class TracerBranch(Rule):
    name = "tracer-branch"
    severity = "error"
    doc = ("Python if/while on a tracer-valued expression inside a "
           "traced function — the branch is resolved ONCE at trace time "
           "(or raises TracerBoolConversionError); use jnp.where / "
           "lax.cond / lax.while_loop")

    def check(self, mod: Module) -> Iterator[Finding]:
        for root, statics in mod.traced_roots:
            params = _param_names(root) - statics
            for node in ast.walk(root):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                test = node.test
                if _is_none_check(test):
                    continue
                jnp_call = next(
                    (sub for sub in ast.walk(test)
                     if isinstance(sub, ast.Call)
                     and (mod.resolved(sub.func) or "").startswith(
                         _TRACER_VALUED_PREFIXES)),
                    None)
                bare_param = (isinstance(test, ast.Name)
                              and test.id in params)
                if jnp_call is not None:
                    yield mod.finding(
                        self, node,
                        f"`{ast.unparse(test)}` branches on a traced "
                        f"array inside {_root_name(root)!r} — use "
                        "jnp.where / lax.cond")
                elif bare_param:
                    yield mod.finding(
                        self, node,
                        f"branch on non-static parameter {test.id!r} "
                        f"inside traced function {_root_name(root)!r} — "
                        "mark it static or use lax.cond")


def _param_names(root: ast.AST) -> Set[str]:
    args = getattr(root, "args", None)
    if args is None:
        return set()
    return {a.arg for a in
            args.posonlyargs + args.args + args.kwonlyargs}


def _is_none_check(test: ast.AST) -> bool:
    return (isinstance(test, ast.Compare)
            and all(isinstance(op, (ast.Is, ast.IsNot))
                    for op in test.ops))


# ---------------------------------------------------------------------------
# 5. os.environ reads at import time
# ---------------------------------------------------------------------------


class EnvReadAtImport(Rule):
    name = "env-import"
    severity = "warning"
    doc = ("os.environ read at module import time outside a config-style "
           "module — the knob freezes at first import, so runtime "
           "overrides (tests, bench sweeps, launcher re-exec) are "
           "silently ignored; read it in the consumer, or baseline it "
           "with the read-once justification")

    def check(self, mod: Module) -> Iterator[Finding]:
        if CONFIG_MODULE_RE.search(Path(mod.relpath).name):
            return
        seen_lines: Set[int] = set()
        for node in _import_time_nodes(mod.tree):
            rname = mod.resolved(node) if isinstance(
                node, (ast.Name, ast.Attribute)) else None
            if rname in ("os.environ", "os.getenv"):
                line = node.lineno
                if line not in seen_lines:
                    seen_lines.add(line)
                    yield mod.finding(
                        self, node,
                        "os.environ read at import time — the value "
                        "freezes before any runtime override")


def _import_time_nodes(tree: ast.Module) -> Iterator[ast.AST]:
    """Every AST node evaluated while the module is being imported:
    module/class bodies plus decorator lists, default argument values
    and annotations of function definitions — but NOT function/lambda
    bodies."""
    stack: List[ast.AST] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(node.decorator_list)
            stack.extend(d for d in node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d is not None)
            continue
        if isinstance(node, ast.Lambda):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# 6. float64 without enable_x64
# ---------------------------------------------------------------------------


class Float64WithoutX64(Rule):
    name = "f64"
    severity = "warning"
    doc = ("jnp.float64 / dtype='float64' requested without enable_x64 "
           "anywhere in the module — JAX silently downgrades to float32 "
           "unless jax.config.update('jax_enable_x64', True) ran, so "
           "the extra precision the code asks for never materializes")

    def check(self, mod: Module) -> Iterator[Finding]:
        if "enable_x64" in mod.source:
            return
        for node in ast.walk(mod.tree):
            if (isinstance(node, (ast.Attribute, ast.Name))
                    and mod.resolved(node) == "jax.numpy.float64"):
                yield mod.finding(
                    self, node,
                    "jnp.float64 without enable_x64 — silently float32")
            elif isinstance(node, ast.Call):
                rname = mod.resolved(node.func) or ""
                if not rname.startswith(("jax.", "jax.numpy.")):
                    continue
                for sub in list(node.args) + [
                        kw.value for kw in node.keywords]:
                    if (isinstance(sub, ast.Constant)
                            and sub.value == "float64"):
                        yield mod.finding(
                            self, sub,
                            f"dtype 'float64' passed to {rname} without "
                            "enable_x64 — silently float32")


# ---------------------------------------------------------------------------
# 7. wall clock inside traced code
# ---------------------------------------------------------------------------

_WALLCLOCK_CALLS = {
    "time.time", "time.time_ns", "time.perf_counter",
    "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}


class WallClockInTrace(Rule):
    name = "wallclock"
    severity = "warning"
    doc = ("time.time()/perf_counter()/datetime.now() inside a traced "
           "function — the value is captured ONCE at trace time and "
           "baked into the compiled program as a constant; take "
           "timestamps outside the jit boundary")

    def check(self, mod: Module) -> Iterator[Finding]:
        for root, _statics in mod.traced_roots:
            for node in ast.walk(root):
                if (isinstance(node, ast.Call)
                        and mod.resolved(node.func) in _WALLCLOCK_CALLS):
                    yield mod.finding(
                        self, node,
                        f"{mod.resolved(node.func)}() inside traced "
                        f"function {_root_name(root)!r} — trace-time "
                        "constant, not a per-step timestamp")


# ---------------------------------------------------------------------------
# 8. unlocked shared mutable state in async server handlers
# ---------------------------------------------------------------------------

_MUTATING_METHODS = {
    "append", "extend", "insert", "add", "discard", "remove", "pop",
    "popleft", "popitem", "update", "setdefault", "clear",
}
_LOCK_NAME_RE = re.compile(r"lock", re.IGNORECASE)


class ServerUnlockedState(Rule):
    name = "server-state"
    severity = "warning"
    doc = ("read-modify-write of shared instance/module state from an "
           "async server handler without a lock — handlers interleave "
           "at every await (and the pool-dispatch ingest path runs them "
           "on threads), so counters and dicts mutated bare lose "
           "updates under load (servers/*.py only)")

    def check(self, mod: Module) -> Iterator[Finding]:
        if "/servers/" not in f"/{mod.relpath}":
            return
        seen: Set[Tuple[int, str]] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                for f in self._visit(mod, node.body, in_lock=False,
                                     fn=node.name):
                    # nested async defs are walked twice — dedupe
                    if (f.line, f.message) not in seen:
                        seen.add((f.line, f.message))
                        yield f

    def _visit(self, mod: Module, body: Sequence[ast.stmt],
               in_lock: bool, fn: str) -> Iterator[Finding]:
        for stmt in body:
            # nested defs get their own ast.walk root (async) or run in
            # an unknown context (sync) — descending here would report
            # their mutations twice under two handler names
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            locked = in_lock
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                ctx = " ".join(
                    ast.unparse(item.context_expr) for item in stmt.items)
                locked = in_lock or bool(_LOCK_NAME_RE.search(ctx))
            if not locked:
                yield from self._flag_mutations(mod, stmt, fn)
            for field in ("body", "orelse", "finalbody", "handlers"):
                sub = getattr(stmt, field, None)
                if not sub:
                    continue
                for child in sub:
                    child_body = (child.body
                                  if isinstance(child, ast.ExceptHandler)
                                  else [child])
                    yield from self._visit(mod, child_body, locked, fn)

    def _flag_mutations(self, mod: Module, stmt: ast.stmt,
                        fn: str) -> Iterator[Finding]:
        if isinstance(stmt, ast.AugAssign) and _is_shared_target(
                stmt.target):
            yield mod.finding(
                self, stmt,
                f"read-modify-write of shared state "
                f"`{ast.unparse(stmt.target)}` in async handler "
                f"{fn!r} without a lock")
        elif isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                if (isinstance(tgt, ast.Subscript)
                        and _is_shared_target(tgt.value)):
                    yield mod.finding(
                        self, stmt,
                        f"item assignment to shared state "
                        f"`{ast.unparse(tgt)}` in async handler "
                        f"{fn!r} without a lock")
        elif isinstance(stmt, ast.Expr) and isinstance(
                stmt.value, ast.Call):
            func = stmt.value.func
            if (isinstance(func, ast.Attribute)
                    and func.attr in _MUTATING_METHODS
                    and _is_shared_target(func.value)):
                yield mod.finding(
                    self, stmt,
                    f"`{ast.unparse(func)}()` mutates shared state in "
                    f"async handler {fn!r} without a lock")


def _is_shared_target(node: ast.AST) -> bool:
    """self.<attr> (possibly nested, e.g. self.stats.counts)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return isinstance(node, ast.Name) and node.id == "self"


# ---------------------------------------------------------------------------
# 9. long-running native scans under the storage lock
# ---------------------------------------------------------------------------

#: the event-log scan entry points whose wall scales with the log size
#: (seconds at training scale). The native side snapshots under its own
#: short mutex, so nothing is gained — and every concurrent writer is
#: stalled — by holding a Python storage lock across them.
_NATIVE_SCAN_RE = re.compile(
    r"^(pio_evlog_scan\w*|_scan_native|_scan_sharded)$")


class LockNativeScan(Rule):
    name = "lock-native-scan"
    severity = "error"
    doc = ("long-running native scan entry point (pio_evlog_scan* / "
           "_scan_native / _scan_sharded) called inside a `with ...lock:` "
           "body — the scan snapshots consistently under its own short "
           "native mutex, so holding the Python storage lock across it "
           "stalls every concurrent event write for the whole scan "
           "(the ~13 s cpplog.scan_interactions class this repo fixed): "
           "snapshot counts under the lock, scan outside it, revalidate "
           "before publishing derived state")

    def check(self, mod: Module) -> Iterator[Finding]:
        seen: Set[Tuple[int, int]] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            ctx = " ".join(
                ast.unparse(item.context_expr) for item in node.items)
            if not _LOCK_NAME_RE.search(ctx):
                continue
            for call in self._calls_in_body(node):
                func = call.func
                cname = (func.attr if isinstance(func, ast.Attribute)
                         else func.id if isinstance(func, ast.Name)
                         else None)
                if cname is None or not _NATIVE_SCAN_RE.match(cname):
                    continue
                key = (call.lineno, call.col_offset)
                if key in seen:  # nested lock withs walk the call twice
                    continue
                seen.add(key)
                yield mod.finding(
                    self, call,
                    f"native scan {cname!r} called while holding "
                    f"`{ctx}` — scans snapshot under their own native "
                    "mutex; release the storage lock before scanning")

    @staticmethod
    def _calls_in_body(with_node: ast.AST) -> Iterator[ast.Call]:
        """Call nodes lexically under the with, excluding nested function
        bodies (a function *defined* under a lock is not *called* under
        it)."""
        stack: List[ast.AST] = list(
            ast.iter_child_nodes(with_node))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Call):
                yield n
            stack.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
# 10. metrics mutation inside traced code
# ---------------------------------------------------------------------------

#: obs-registry mutators (obs/metrics.py): Counter.inc / Gauge.inc/dec /
#: Histogram.observe. ``set`` is handled separately — ``x.at[i].set(v)``
#: is the JAX scatter idiom and must stay exempt.
_METRIC_MUTATORS = {"inc", "dec", "observe"}


class MetricInTrace(Rule):
    name = "metric-in-trace"
    severity = "error"
    doc = ("metrics-registry mutation (.inc()/.dec()/.observe()/metric "
           ".set()) inside a jit/pjit/shard_map/pallas_call-traced "
           "function — at trace time it books once and never again (a "
           "lying counter), and any host-callback variant would "
           "serialize the device per step; book metrics outside the "
           "trace boundary (obs/metrics.py's hot-path contract)")

    def check(self, mod: Module) -> Iterator[Finding]:
        for root, _statics in mod.traced_roots:
            for node in ast.walk(root):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)):
                    continue
                attr = node.func.attr
                if attr in _METRIC_MUTATORS or (
                        attr == "set"
                        and not _is_at_indexed(node.func.value)):
                    yield mod.finding(
                        self, node,
                        f".{attr}() metric mutation inside traced "
                        f"function {_root_name(root)!r} — book metrics "
                        "outside the trace boundary")


def _is_at_indexed(node: ast.AST) -> bool:
    """True for ``x.at[...]`` receivers (the JAX functional-update
    idiom ``x.at[i].set(v)``, including chained updates)."""
    return (isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "at")


# ---------------------------------------------------------------------------
# 11. blocking storage reads on the serving hot path
# ---------------------------------------------------------------------------

#: EventStore read entry points whose wall scales with the event log —
#: a synchronous storage round trip per query is the latency class the
#: speed layer's TTL micro-cache (speed/cache.py) exists to remove
_EVENTSTORE_READS = {
    "find", "find_by_entity", "aggregate_properties", "interactions",
    "extract_entity_map",
}
_SERVE_ENTRY_POINTS = {"predict", "batch_predict", "batch_serve_json"}


class ServeBlockingIO(Rule):
    name = "serve-blocking-io"
    severity = "warning"
    doc = ("direct EventStore read (find/find_by_entity/"
           "aggregate_properties/...) reachable from a predict() hot "
           "path — a synchronous storage round trip per query; route it "
           "through the bounded TTL micro-cache (speed/cache.py "
           "TTLCache, invalidated by the speed-layer cursor) and record "
           "the cache-miss loader in the baseline")

    def check(self, mod: Module) -> Iterator[Finding]:
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {
                n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            # intra-class call graph over self.<method>() edges —
            # ast.walk covers lambdas/closures, so a loader passed to a
            # cache helper still counts as reachable (its read then
            # carries a baseline justification)
            edges: dict = {}
            for name, fn in methods.items():
                callees = set()
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "self"
                            and node.func.attr in methods):
                        callees.add(node.func.attr)
                edges[name] = callees
            reachable: Set[str] = set()
            stack = [m for m in _SERVE_ENTRY_POINTS if m in methods]
            while stack:
                m = stack.pop()
                if m in reachable:
                    continue
                reachable.add(m)
                stack.extend(edges.get(m, ()))
            for name in sorted(reachable):
                for node in ast.walk(methods[name]):
                    if not (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr in _EVENTSTORE_READS):
                        continue
                    base = mod.resolved(node.func.value) or ""
                    if base != "EventStore" and not base.endswith(
                            ".EventStore"):
                        continue
                    yield mod.finding(
                        self, node,
                        f"EventStore.{node.func.attr}() reachable from "
                        f"the serving hot path (via {name!r}) — a "
                        "storage round trip per query; front it with "
                        "the TTL micro-cache (speed/cache.py)")


# ---------------------------------------------------------------------------
# 12. blocking profiler calls on the serving hot path
# ---------------------------------------------------------------------------

#: profiler-capture entry points — each one either serializes the device
#: (block_until_ready per query) or starts a process-wide trace capture;
#: both are catastrophic inside a predict path that is supposed to
#: pipeline dispatches
_PROFILER_CAPTURE_CALLS = {
    "jax.block_until_ready",
    "jax.profiler.start_trace",
    "jax.profiler.stop_trace",
    "jax.profiler.trace",
    "jax.profiler.start_server",
    "jax.profiler.TraceAnnotation",
}


class BlockingProfiler(Rule):
    name = "blocking-profiler"
    severity = "error"
    doc = ("block_until_ready / jax.profiler capture call reachable "
           "from a predict/batch_predict/batch_serve_json hot path — "
           "each query then synchronizes (or trace-captures) the whole "
           "device instead of pipelining dispatches; route device-wall "
           "attribution through obs/profile.py (profile.t0()/record(), "
           "gated on PIO_PROFILE and exempt from this rule)")

    def check(self, mod: Module) -> Iterator[Finding]:
        # obs/profile.py IS the sanctioned guard: its record() exists so
        # nobody else ever writes a bare block_until_ready on a serve
        # path, and its own block is env-gated
        path = str(mod.path).replace("\\", "/")
        if path.endswith("obs/profile.py"):
            return
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {
                n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            edges: dict = {}
            for name, fn in methods.items():
                callees = set()
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "self"
                            and node.func.attr in methods):
                        callees.add(node.func.attr)
                edges[name] = callees
            reachable: Set[str] = set()
            stack = [m for m in _SERVE_ENTRY_POINTS if m in methods]
            while stack:
                m = stack.pop()
                if m in reachable:
                    continue
                reachable.add(m)
                stack.extend(edges.get(m, ()))
            for name in sorted(reachable):
                for node in ast.walk(methods[name]):
                    if not isinstance(node, ast.Call):
                        continue
                    rname = mod.resolved(node.func) or ""
                    blocking = rname in _PROFILER_CAPTURE_CALLS or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "block_until_ready")
                    if not blocking:
                        continue
                    what = (f"{rname}()" if rname
                            else f".{node.func.attr}()")
                    yield mod.finding(
                        self, node,
                        f"{what} reachable from the serving hot path "
                        f"(via {name!r}) — a device sync/capture per "
                        "query; use obs/profile.py's gated "
                        "t0()/record() instead")


# ---------------------------------------------------------------------------
# 13. host gathers inside an active mesh context
# ---------------------------------------------------------------------------

#: `with mesh:` / `with Mesh(...):` / `with placement.mesh:` context
#: expressions — the lexical scope in which factor tables and sweep
#: outputs are mesh-distributed
_MESH_CTX_RE = re.compile(r"(?i)(^|[^\w])mesh\b|[^\w]Mesh\(|^Mesh\(")
_HOST_GATHER_CALLS = {"jax.device_get", "numpy.asarray", "numpy.array"}
_HOST_GATHER_ATTRS = {"tolist", "item"}


class HostGatherInMesh(Rule):
    name = "host-gather-in-mesh"
    severity = "error"
    doc = ("jax.device_get / np.asarray / .tolist() / .item() on a "
           "value inside an active mesh context (`with mesh:` body) — "
           "on mesh-sharded values each fetch is a cross-device "
           "gather + host round trip in the middle of the training "
           "loop, exactly the anti-pattern the sharded ALS sweep "
           "forbids (ROADMAP item 1: no host round-trips between "
           "dispatches); keep the loop device-side and fetch once "
           "after the mesh context closes (obs/profile.py's gated "
           "attribution is the one sanctioned exception)")

    def check(self, mod: Module) -> Iterator[Finding]:
        # obs/profile.py is the sanctioned sync point: its record() is
        # env-gated and a wall measurement IS a host sync
        path = str(mod.path).replace("\\", "/")
        if path.endswith("obs/profile.py"):
            return
        seen: Set[Tuple[int, int]] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            ctx = " ".join(
                ast.unparse(item.context_expr) for item in node.items)
            if not _MESH_CTX_RE.search(ctx):
                continue
            # reuse the lock rule's body walk: nested function DEFS are
            # exempt (host-sync already covers shard_map-traced bodies)
            for call in LockNativeScan._calls_in_body(node):
                rname = mod.resolved(call.func)
                if rname in _HOST_GATHER_CALLS:
                    what = f"{rname}()"
                elif (isinstance(call.func, ast.Attribute)
                        and call.func.attr in _HOST_GATHER_ATTRS
                        and rname not in _HOST_GATHER_CALLS):
                    what = f".{call.func.attr}()"
                else:
                    continue
                key = (call.lineno, call.col_offset)
                if key in seen:  # nested mesh withs walk the call twice
                    continue
                seen.add(key)
                yield mod.finding(
                    self, call,
                    f"{what} inside active mesh context `{ctx}` — a "
                    "cross-shard gather + host round trip mid-loop; "
                    "fetch after the mesh context closes")


# ---------------------------------------------------------------------------
# 14. unbounded metric label values
# ---------------------------------------------------------------------------

#: value names that smell like per-entity/per-request data — one time
#: series per distinct value, which is how a registry (and every scraper
#: behind it) OOMs. Terminal name of the expression (Name id / Attribute
#: attr) is matched; bounded-set names (route patterns, status codes,
#: phases, modes) deliberately absent.
_UNBOUNDED_LABEL_NAME_RE = re.compile(
    r"(?:^|_)(id|ids|uuid|guid|key|token|path|url|uri|query|entity|"
    r"user|item|session|trace|span|instance|host|hostname|addr|"
    r"address|exc|exception|err|error|message|detail)s?$",
    re.IGNORECASE)


def _terminal_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class MetricLabelCardinality(Rule):
    name = "metric-label-cardinality"
    severity = "error"
    doc = ("unbounded value (id / raw path / exception string / "
           "interpolated f-string) used as a metric label value in a "
           "``.labels(...)`` call — every distinct value mints a new "
           "time series, so wire-derived label values grow the registry "
           "(and every scrape) without bound until the process OOMs; "
           "label values must come from BOUNDED sets (route PATTERNS, "
           "status codes, enum/phase names — obs/metrics.py's "
           "cardinality contract), or carry a boundedness justification "
           "in the baseline")

    def check(self, mod: Module) -> Iterator[Finding]:
        exc_names: Set[str] = {
            h.name for h in ast.walk(mod.tree)
            if isinstance(h, ast.ExceptHandler) and h.name
        }
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"):
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    continue  # **kwargs: opaque, other rules' problem
                reason = self._unbounded(kw.value, exc_names)
                if reason:
                    yield mod.finding(
                        self, kw.value,
                        f"label {kw.arg!r} value {reason} — one time "
                        "series per distinct value; use a bounded set "
                        "(pattern/code/enum), bucket the value, or "
                        "baseline it with a boundedness justification")

    def _unbounded(self, v: ast.AST,
                   exc_names: "Set[str]") -> Optional[str]:
        if isinstance(v, ast.JoinedStr) and any(
                isinstance(x, ast.FormattedValue) for x in v.values):
            return "is an interpolated f-string"
        if isinstance(v, ast.BinOp) and isinstance(
                v.op, (ast.Add, ast.Mod)) and not (
                isinstance(v.left, ast.Constant)
                and isinstance(v.right, ast.Constant)):
            return "is built by string concatenation/%-formatting"
        if isinstance(v, ast.Call):
            f = v.func
            if isinstance(f, ast.Attribute) and f.attr == "format":
                return "is built by .format()"
            if (isinstance(f, ast.Name) and f.id in ("str", "repr")
                    and len(v.args) == 1):
                arg = v.args[0]
                if (isinstance(arg, ast.Name) and arg.id in exc_names):
                    return (f"stringifies caught exception "
                            f"{ast.unparse(arg)!r}")
                nm = _terminal_name(arg)
                if nm and _UNBOUNDED_LABEL_NAME_RE.search(nm):
                    return f"stringifies {ast.unparse(arg)!r}"
            return None
        if isinstance(v, ast.Name) and v.id in exc_names:
            return f"is the caught exception {v.id!r}"
        nm = _terminal_name(v)
        if nm and _UNBOUNDED_LABEL_NAME_RE.search(nm):
            try:
                text = ast.unparse(v)
            except Exception:
                text = nm
            return f"reads {text!r} (unbounded-looking name)"
        return None


# ---------------------------------------------------------------------------
# 15. unbatched device dispatch from server modules
# ---------------------------------------------------------------------------

#: device-dispatch entry points the serving scheduler exists to front:
#: direct top-k/fold-in calls from a server module bypass the queue →
#: ladder → shed plane entirely
_DISPATCH_ENTRY_POINTS = {
    "score_and_top_k", "score_user_and_top_k", "batch_score_top_k",
    "sharded_top_k", "top_k_with_exclusions", "FoldInSolver",
    "als_fused_solve_cg_pallas", "score_and_top_k_pallas",
}
#: algorithm methods that reach the device — sanctioned ONLY from the
#: scheduler's handle_batch callback (whose calls carry baseline
#: justifications) and the deploy-time warmup cold path
_DISPATCH_METHODS = {"predict", "batch_predict", "batch_serve_json",
                     "warmup"}


class UnbatchedDispatch(Rule):
    name = "unbatched-dispatch"
    severity = "warning"
    doc = ("direct solver/top-k device dispatch (ops/topk entries, "
           "FoldInSolver, or an algorithm predict/batch_predict/"
           "batch_serve_json/warmup call) in a server module "
           "(servers/*.py) — query-path device work must route through "
           "the continuous-batching scheduler seam "
           "(serving/scheduler.py) so queue-depth coalescing and SLO "
           "shedding apply; the scheduler's own handle_batch callback "
           "and deploy-time warmup are the sanctioned baseline-"
           "justified exceptions")

    def check(self, mod: Module) -> Iterator[Finding]:
        if "/servers/" not in f"/{mod.relpath}":
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            rname = mod.resolved(node.func) or ""
            tail = rname.rsplit(".", 1)[-1] if rname else ""
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else (node.func.id
                          if isinstance(node.func, ast.Name) else ""))
            if tail in _DISPATCH_ENTRY_POINTS \
                    or attr in _DISPATCH_ENTRY_POINTS:
                what = rname or attr
                yield mod.finding(
                    self, node,
                    f"direct device dispatch `{what}()` in a server "
                    "module bypasses the continuous-batching scheduler "
                    "seam (serving/scheduler.py) — no queue coalescing, "
                    "no shed policy")
            elif attr in _DISPATCH_METHODS and isinstance(
                    node.func, ast.Attribute):
                yield mod.finding(
                    self, node,
                    f"device-dispatching `{attr}()` call in a server "
                    "module outside the scheduler seam — route query "
                    "work through BatchScheduler.submit (the scheduler's "
                    "handle_batch callback and deploy warmup belong in "
                    "the baseline)")


# ---------------------------------------------------------------------------
# 16. exhaustive full-table scans that bypass the MIPS auto-router
# ---------------------------------------------------------------------------

#: scoring entries BELOW the auto-router seam: calling one of these
#: directly pins the query to the exhaustive full-table scan even when
#: a two-stage MIPS index is registered (ops/mips.py). The public
#: routers (score_and_top_k / score_user_and_top_k / batch_score_top_k)
#: are the sanctioned entries — they fall back to exhaustive themselves
#: when the index/mode says so.
_EXHAUSTIVE_BYPASS = {
    "_score_and_top_k_xla", "_score_user_top_k_xla",
    "_batch_score_top_k_xla", "score_and_top_k_pallas",
    "sharded_top_k", "top_k_with_exclusions",
}


class ExhaustiveScan(Rule):
    name = "exhaustive-scan"
    severity = "warning"
    doc = ("direct full-table scoring call in a server/serving module "
           "(servers/*.py, serving/*.py) below the MIPS auto-router "
           "seam — sharded_top_k / top_k_with_exclusions / the private "
           "XLA+Pallas scoring entries, or a raw jax.lax.top_k over "
           "catalogue scores. These pin the query to the exhaustive "
           "scan even when a registered two-stage index (ops/mips.py) "
           "could serve it at a fraction of the device wall; route "
           "through score_and_top_k / score_user_and_top_k / "
           "batch_score_top_k, which auto-route and keep exhaustive as "
           "the fallback")

    def check(self, mod: Module) -> Iterator[Finding]:
        rel = f"/{mod.relpath}"
        if "/servers/" not in rel and "/serving/" not in rel:
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            rname = mod.resolved(node.func) or ""
            tail = rname.rsplit(".", 1)[-1] if rname else ""
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else (node.func.id
                          if isinstance(node.func, ast.Name) else ""))
            if tail in _EXHAUSTIVE_BYPASS or attr in _EXHAUSTIVE_BYPASS:
                what = rname or attr
                yield mod.finding(
                    self, node,
                    f"`{what}()` scores the FULL catalogue from a "
                    "server/serving module, bypassing the MIPS "
                    "auto-router — use the ops/topk router entries so "
                    "a registered two-stage index can serve the query")
            elif rname == "jax.lax.top_k":
                yield mod.finding(
                    self, node,
                    "raw `jax.lax.top_k()` in a server/serving module "
                    "— full-score ranking belongs behind the ops/topk "
                    "auto-routers (exhaustive stays their fallback)")


# ---------------------------------------------------------------------------
# 17. ad-hoc retry loops outside the shared RetryPolicy
# ---------------------------------------------------------------------------


class UnboundedRetry(Rule):
    name = "unbounded-retry"
    severity = "warning"
    doc = ("retry loop swallowing exceptions with a bare fixed-delay "
           "time.sleep (no backoff, no deadline) outside utils/http.py "
           "— fixed delays herd every client back onto a struggling "
           "server in lockstep and the loop never gives up; route "
           "client retries through utils/http.RetryPolicy (jittered "
           "exponential backoff under an overall deadline, Retry-After "
           "honored, idempotent-only by default)")

    def check(self, mod: Module) -> Iterator[Finding]:
        rel = f"/{mod.relpath}".replace("\\", "/")
        if rel.endswith("/utils/http.py"):  # RetryPolicy's own home
            return
        seen: Set[Tuple[int, int]] = set()
        for loop in ast.walk(mod.tree):
            if not isinstance(loop, (ast.While, ast.For)):
                continue
            # a retry loop = a loop that both swallows a failure (an
            # except handler in its own body) and sleeps a CONSTANT
            # delay anywhere in that body. Computed delays (backoff
            # expressions) and sleeps outside failure loops stay silent
            # — this is a drift detector, not a sleep ban.
            if not any(isinstance(n, ast.ExceptHandler)
                       for n in self._body_nodes(loop)):
                continue
            for call in self._body_nodes(loop):
                if not (isinstance(call, ast.Call)
                        and mod.resolved(call.func) == "time.sleep"
                        and len(call.args) == 1
                        and isinstance(call.args[0], ast.Constant)):
                    continue
                key = (call.lineno, call.col_offset)
                if key in seen:  # nested loops walk the call twice
                    continue
                seen.add(key)
                yield mod.finding(
                    self, call,
                    "fixed-delay time.sleep() in a retry loop — no "
                    "backoff, no deadline, no jitter; use "
                    "utils/http.RetryPolicy")

    @staticmethod
    def _body_nodes(loop: ast.AST) -> Iterator[ast.AST]:
        """Nodes lexically inside the loop body, excluding nested
        function bodies (a function DEFINED in a loop is not the loop
        retrying) and the loop's else clause."""
        stack: List[ast.AST] = list(loop.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))


# ---------------------------------------------------------------------------
# 18. fleet actuation outside the decision-record emitter
# ---------------------------------------------------------------------------

#: the retrain/reload actuator surface reachable from the freshness
#: controller: the workflow's training entry, the front door's rolling
#: reload, and the controller's own injected actuator callables
_ACTUATION_CALLS = {
    "run_train", "rolling_reload", "rolling_reload_async",
    "retrain_fn", "reload_fn", "_retrain_fn", "_reload_fn",
}


class UnauditedActuation(Rule):
    name = "unaudited-actuation"
    severity = "error"
    doc = ("call into a retrain/reload actuator (CoreWorkflow."
           "run_train, FrontDoor.rolling_reload, or the controller's "
           "injected retrain_fn/reload_fn callables) from "
           "obs/controller.py OUTSIDE the decision-record emitter — "
           "every fleet actuation must flow through "
           "FreshnessController._actuate, which runs it inside the "
           "decision's trace context and writes the outcome into the "
           "audit ring; an actuation anywhere else is a fleet mutation "
           "nothing audited (actuator FACTORIES — functions named "
           "*_fn building the callables the emitter later invokes — "
           "are the sanctioned construction sites)")

    def check(self, mod: Module) -> Iterator[Finding]:
        rel = f"/{mod.relpath}".replace("\\", "/")
        if not rel.endswith("/obs/controller.py"):
            return
        # map every call to its enclosing function-def stack
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else (node.func.id
                          if isinstance(node.func, ast.Name) else ""))
            rname = mod.resolved(node.func) or ""
            tail = rname.rsplit(".", 1)[-1] if rname else ""
            if attr not in _ACTUATION_CALLS \
                    and tail not in _ACTUATION_CALLS:
                continue
            # sanctioned scopes: the emitter itself (_actuate, nested
            # defs included) and actuator factories (*_fn) whose
            # closures the emitter invokes later
            sanctioned = False
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    if cur.name == "_actuate" \
                            or cur.name.endswith("_fn"):
                        sanctioned = True
                        break
                cur = parents.get(cur)
            if sanctioned:
                continue
            what = rname or attr
            yield mod.finding(
                self, node,
                f"actuator call `{what}()` outside the decision-record "
                "emitter — route fleet retrain/reload through "
                "FreshnessController._actuate so the action lands in "
                "the audit ring under its decision's trace ID")


# ---------------------------------------------------------------------------
# 19. flight-recorder snapshot/capture on the serving hot path
# ---------------------------------------------------------------------------

#: recorder snapshot/capture entry points (obs/recorder.py): each one
#: walks the whole registry (sample_now), replays the delta ring
#: (dump), or writes a multi-worker JSON bundle to disk (capture_now) —
#: milliseconds-to-seconds of work that must only ever run on the
#: recorder/capture module's OWN threads and the admin/debug HTTP
#: executor, never where a query dispatch can reach it
_RECORDER_CAPTURE_ATTRS = {"sample_now", "capture_now"}
_RECORDER_GATEWAYS = {
    "incubator_predictionio_tpu.obs.recorder.get_recorder",
    "incubator_predictionio_tpu.obs.recorder.get_capture",
}
#: serve-path roots for this rule: the predict-family entries the other
#: serve rules guard PLUS the scheduler's admission/dispatch methods
#: (serving/scheduler.py) — incident capture must never block serving
_RECORDER_SERVE_ENTRY_POINTS = _SERVE_ENTRY_POINTS | {
    "submit", "_run", "_handle_batch", "handle_batch",
}


class RecorderInServePath(Rule):
    name = "recorder-in-serve-path"
    severity = "error"
    doc = ("flight-recorder snapshot/capture call (sample_now / "
           "capture_now / a get_recorder()/get_capture() gateway) "
           "reachable from a predict/batch_predict/scheduler-dispatch "
           "path outside obs/recorder.py — a registry walk, ring "
           "replay or bundle write inline with a query dispatch stalls "
           "serving exactly when an incident fires; the serve path's "
           "only sanctioned recorder exposure is the exemplar "
           "reservoir inside Histogram.observe(), everything else runs "
           "on the recorder's own sampler/capture threads "
           "(IncidentCapture.trigger() is the non-blocking hook)")

    def check(self, mod: Module) -> Iterator[Finding]:
        # obs/recorder.py owns the sampler/capture threads these calls
        # are FOR
        path = str(mod.path).replace("\\", "/")
        if path.endswith("obs/recorder.py"):
            return
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {
                n.name: n for n in cls.body
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            edges: dict = {}
            for name, fn in methods.items():
                callees = set()
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id == "self"
                            and node.func.attr in methods):
                        callees.add(node.func.attr)
                edges[name] = callees
            reachable: Set[str] = set()
            stack = [m for m in _RECORDER_SERVE_ENTRY_POINTS
                     if m in methods]
            while stack:
                m = stack.pop()
                if m in reachable:
                    continue
                reachable.add(m)
                stack.extend(edges.get(m, ()))
            for name in sorted(reachable):
                for node in ast.walk(methods[name]):
                    if not isinstance(node, ast.Call):
                        continue
                    rname = mod.resolved(node.func) or ""
                    hit = rname in _RECORDER_GATEWAYS or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _RECORDER_CAPTURE_ATTRS)
                    if not hit:
                        continue
                    what = (f"{rname}()" if rname
                            else f".{node.func.attr}()")
                    yield mod.finding(
                        self, node,
                        f"{what} reachable from the serving/dispatch "
                        f"hot path (via {name!r}) — recorder snapshots "
                        "and incident captures run on obs/recorder.py's "
                        "own threads; from a serve path use the "
                        "non-blocking IncidentCapture.trigger() hook "
                        "(or nothing: the sampler already records)")


# ---------------------------------------------------------------------------
# 20. serving-knob mutation outside the audited apply seam
# ---------------------------------------------------------------------------

#: the registered serving-knob env surface — a LITERAL copy of
#: obs/knobs.KNOB_ENV_VARS (rules must not import runtime modules;
#: tests/test_knobs.py pins the two sets equal so they cannot drift)
_KNOB_ENV_VARS = {
    "PIO_SERVE_MIPS_NPROBE",
    "PIO_SERVE_MIPS_CANDIDATES",
    "PIO_SERVE_MAX_BATCH",
    "PIO_SERVE_MAX_WAIT_MS",
    "PIO_SERVE_SHED",
    "PIO_SPEED_MAX_BATCH",
    "PIO_SERVE_MIPS_PQ_M",
    "PIO_SERVE_MIPS_PQ_CANDIDATES",
    "PIO_MIPS_REBUILD_TAIL",
    "PIO_MIPS_REBUILD_AGE_S",
}
#: knob-backed scheduler fields (serving/scheduler.py) — assigning them
#: on ANOTHER object's scheduler bypasses both the env seam and
#: apply_knobs()'s lock; writes on `self` are the scheduler's own
_KNOB_SCHED_FIELDS = {"cap", "max_batch", "wait_bound_s", "_shed"}
#: sanctioned writer scopes: the knob controller's single audited seam
#: (KnobController._apply), the worker/front-door /knobs handlers
#: (both deliberately named post_knobs), and actuator factories (*_fn)
_KNOB_SANCTIONED_DEFS = ("_apply", "post_knobs")


class UnauditedKnobWrite(Rule):
    name = "unaudited-knob-write"
    severity = "error"
    doc = ("mutation of a registered serving knob (a PIO_SERVE_*/"
           "PIO_SPEED_MAX_BATCH env write via os.environ assignment/"
           "setdefault/putenv, or a knob-backed scheduler field poked "
           "on another object) outside the audited apply seam — every "
           "knob change must flow through KnobController._apply or the "
           "POST /knobs route handlers (post_knobs), which run it "
           "inside a knob.decision trace and record it in the audit "
           "ring; a knob write anywhere else is a serving-behavior "
           "mutation nothing audited and incident rollback cannot "
           "undo (actuator factories — *_fn functions building the "
           "callables _apply later invokes — are the sanctioned "
           "construction sites)")

    @staticmethod
    def _is_os_environ(mod: Module, expr: ast.AST) -> bool:
        if (isinstance(expr, ast.Attribute) and expr.attr == "environ"
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "os"):
            return True
        rname = mod.resolved(expr) or ""
        return rname == "os.environ" or rname.endswith(".os.environ")

    @staticmethod
    def _literal_knob(expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Index):  # py<3.9 slice wrapper
            expr = expr.value
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
                and expr.value in _KNOB_ENV_VARS:
            return expr.value
        return None

    def check(self, mod: Module) -> Iterator[Finding]:
        parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

        def sanctioned(node: ast.AST) -> bool:
            cur = parents.get(node)
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    if cur.name in _KNOB_SANCTIONED_DEFS \
                            or cur.name.endswith("_fn"):
                        return True
                cur = parents.get(cur)
            return False

        for node in ast.walk(mod.tree):
            hit: Optional[str] = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and self._is_os_environ(mod, t.value):
                        env = self._literal_knob(t.slice)
                        if env:
                            hit = (f"os.environ[{env!r}] write")
                    elif isinstance(t, ast.Attribute) \
                            and t.attr in _KNOB_SCHED_FIELDS \
                            and not (isinstance(t.value, ast.Name)
                                     and t.value.id == "self"):
                        hit = (f"scheduler knob field `.{t.attr}` "
                               "assigned on another object")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.args:
                env = self._literal_knob(node.args[0])
                if env and node.func.attr == "setdefault" \
                        and self._is_os_environ(mod, node.func.value):
                    hit = f"os.environ.setdefault({env!r}, ...)"
                elif env and node.func.attr == "putenv":
                    hit = f"os.putenv({env!r}, ...)"
            if hit is None or sanctioned(node):
                continue
            yield mod.finding(
                self, node,
                f"{hit} outside the audited knob seam — route serving-"
                "knob changes through KnobController._apply or the "
                "POST /knobs handlers (post_knobs) so the change lands "
                "in the audit ring under a knob.decision trace and "
                "incident rollback can restore the last-known-good "
                "vector")


# ---------------------------------------------------------------------------
# 21. tenant-attributable serving metrics booked without a bounded
#     tenant label
# ---------------------------------------------------------------------------

#: the serving-plane metric families the multi-tenant platform
#: attributes per tenant (serving/tenancy.py) — booking one of these
#: without a ``tenant`` label silently merges every tenant's traffic
#: into one series, and booking it with a WIRE value (raw accessKey,
#: raw tenant parameter) mints unbounded series
_TENANT_SCOPED_METRICS = {
    "pio_query_latency_seconds",
    "pio_serve_shed_total",
    "pio_serve_queue_depth",
}
#: registry constructor attributes whose first argument names the family
_METRIC_CTOR_ATTRS = {"histogram", "counter", "gauge"}


class UnscopedTenantMetric(Rule):
    name = "unscoped-tenant-metric"
    severity = "error"
    doc = ("serving-path ``.labels(...)`` call on a tenant-attributable "
           "metric family (pio_query_latency_seconds / "
           "pio_serve_shed_total / pio_serve_queue_depth) without a "
           "``tenant=`` label, or with a tenant value that is not a "
           "string literal or a bounded-registry ``.label(...)`` "
           "gateway call — an unlabeled booking merges every tenant's "
           "traffic into one series (per-tenant SLOs and the "
           "noisy-neighbor evidence go blind), and a raw wire value "
           "(the request's tenant/accessKey) mints one series per "
           "distinct value; route every tenant label through "
           "TenantRegistry.label(), which maps unknown ids to the "
           "bounded 'default' child")

    def check(self, mod: Module) -> Iterator[Finding]:
        path = str(mod.path).replace("\\", "/")
        if "/serving/" not in path and "/servers/" not in path:
            return
        # module-level bindings of the scoped families: NAME =
        # REGISTRY.histogram("pio_query_latency_seconds", ...)
        scoped: Set[str] = set()
        for stmt in mod.tree.body:
            if not (isinstance(stmt, ast.Assign)
                    and isinstance(stmt.value, ast.Call)
                    and isinstance(stmt.value.func, ast.Attribute)
                    and stmt.value.func.attr in _METRIC_CTOR_ATTRS
                    and stmt.value.args
                    and isinstance(stmt.value.args[0], ast.Constant)
                    and stmt.value.args[0].value
                    in _TENANT_SCOPED_METRICS):
                continue
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    scoped.add(tgt.id)
        if not scoped:
            return
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "labels"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in scoped):
                continue
            family = node.func.value.id
            tenant_kw = next((kw for kw in node.keywords
                              if kw.arg == "tenant"), None)
            if tenant_kw is None:
                yield mod.finding(
                    self, node,
                    f"{family}.labels(...) books a tenant-attributable "
                    "series without a tenant= label — every tenant's "
                    "traffic merges into one child and the per-tenant "
                    "SLO/isolation evidence goes blind; pass "
                    "tenant=<registry>.label(...)")
                continue
            v = tenant_kw.value
            bounded = isinstance(v, ast.Constant) or (
                isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "label")
            if not bounded:
                try:
                    text = ast.unparse(v)
                except Exception:  # pragma: no cover — unparse is total
                    text = "<expr>"
                yield mod.finding(
                    self, v,
                    f"{family}.labels(tenant={text}) passes a raw "
                    "(wire-derived) tenant value — one series per "
                    "distinct value until the registry OOMs; route it "
                    "through the bounded TenantRegistry.label() "
                    "gateway (unknown ids collapse to 'default')")


# whole-program (rule API v2) passes live in their own module — they
# consume the package index, not a single Module
from incubator_predictionio_tpu.analysis.concur import (  # noqa: E402
    ThreadLifecycle,
    UnguardedSharedState,
)

ALL_RULES: Sequence[Rule] = (
    HostSyncInTrace(),
    NegativeGather(),
    ProbeArity(),
    TracerBranch(),
    EnvReadAtImport(),
    Float64WithoutX64(),
    WallClockInTrace(),
    ServerUnlockedState(),
    LockNativeScan(),
    MetricInTrace(),
    ServeBlockingIO(),
    BlockingProfiler(),
    HostGatherInMesh(),
    MetricLabelCardinality(),
    UnbatchedDispatch(),
    ExhaustiveScan(),
    UnboundedRetry(),
    UnauditedActuation(),
    UnauditedKnobWrite(),
    RecorderInServePath(),
    UnscopedTenantMetric(),
    UnguardedSharedState(),
    ThreadLifecycle(),
)

RULES_BY_NAME = {r.name: r for r in ALL_RULES}
