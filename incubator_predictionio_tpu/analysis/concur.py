"""Whole-program lock-discipline analysis (rule API v2).

PRs 10-15 turned the serving system into a fleet of cooperating
threads — scheduler dispatch loops, the recorder's lock-free sampler
ring, overlay pollers, the controller loop — whose locking discipline
was enforced only by convention. This module is the RacerD-style
replacement guardrail, run as a two-phase whole-program pass over the
:class:`~.engine.Package`:

Phase one (:func:`build_index`) indexes every class in the package:
spawned ``threading.Thread`` / ``threading.Timer`` / executor-submit
entry points, per-method attribute reads and writes, ``with
self._lock:``-style guard regions (nested and locally aliased locks
included), the class's lock attributes, and the intra-class call graph
(the ``serve-blocking-io`` machinery, extended to closures).

Phase two reports:

``unguarded-shared-state`` (error) — an attribute written on a
thread-entry-reachable path and accessed on another path with
inconsistent lock protection. The GuardedBy set is INFERRED from the
guard regions the code already has, never annotated by hand.

``thread-lifecycle`` (warning) — a spawned thread with no daemon flag,
stop-event, or join seam (leaked threads are why shutdown-race tests
exist), and a ``ThreadPoolExecutor`` that is neither scoped by ``with``
nor ever shut down.

Sanctioned lock-free idioms are expressible, not baselined away:

* attributes holding thread-safe types (``queue.Queue`` handoff,
  ``threading.Event``, ``contextvars.ContextVar``, locks themselves)
  are exempt by construction;
* writes in ``__init__`` (and writes textually before the first spawn
  in the spawning method) are pre-publication and exempt;
* ``# pio-lint: publish-only`` declares a single-writer
  immutable-publish attribute (the recorder ring's tuple-swap); the
  analyzer VERIFIES the single-writer half — a publish-only attribute
  written from more than one thread domain is still an error;
* ``# pio-lint: guarded-by(<lock>)`` pins an attribute to a specific
  lock; a write outside any region of that lock is still an error.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from incubator_predictionio_tpu.analysis.engine import (
    Finding,
    Module,
    Package,
)

#: constructors whose product is a lock — both a guard region source
#: (``with self.<attr>:``) and exempt from shared-state analysis
_LOCK_TYPES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}
#: constructors whose product is safe to share without a lock: the
#: queue/contextvar handoff idioms, events, thread-locals
_SAFE_TYPES = _LOCK_TYPES | {
    "threading.Event", "threading.Barrier", "threading.local",
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue", "contextvars.ContextVar",
    "asyncio.Queue", "asyncio.Event", "asyncio.Lock",
}
_THREAD_CTORS = {"threading.Thread": "thread", "threading.Timer": "timer"}
_EXECUTOR_CTORS = {
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.thread.ThreadPoolExecutor",
}
#: method names that mutate their receiver in place (a
#: ``self.attr.append(...)`` is a WRITE of ``attr`` for race purposes) —
#: applied only to attributes known to BE plain containers; a method
#: named ``discard`` on a domain object is that object's business (deep
#: ownership: an object synchronizes itself)
_MUTATING_CALLS = {
    "append", "extend", "insert", "add", "discard", "remove", "pop",
    "popleft", "popitem", "update", "setdefault", "clear",
    "move_to_end", "sort", "reverse",
}
_CONTAINER_CTORS = {
    "dict", "set", "list", "collections.deque",
    "collections.defaultdict", "collections.OrderedDict",
    "collections.Counter",
}
_CONTAINER_LITERALS = (ast.Dict, ast.Set, ast.List, ast.DictComp,
                       ast.SetComp, ast.ListComp)
_LOCK_NAME_RE = re.compile(r"lock", re.IGNORECASE)
_GUARDED_BY_RE = re.compile(r"^guarded-by\((?:self\.)?([\w.]+)\)$")


def _self_dotted(node: ast.AST) -> Optional[str]:
    """Dotted attribute path rooted at ``self`` — ``self.lock`` →
    ``"lock"``, ``self.client.lock`` → ``"client.lock"``; None for
    anything else. Lock guards routinely live on a collaborator (a DAO
    synchronizing on its client's lock), so lock identity must be the
    whole path, not just the first hop."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and parts:
        return ".".join(reversed(parts))
    return None

_INDEX_CACHE_KEY = "concur.index"


# ---------------------------------------------------------------------------
# phase one: the package index
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Access:
    """One read or write of ``self.<attr>`` somewhere in a class."""

    attr: str
    kind: str            # "read" | "write"
    line: int
    locks: frozenset     # lock attr names held at the access site
    node: str            # method key, or "<method>.<nested>" for closures


@dataclasses.dataclass
class SpawnSite:
    """One thread/timer/executor creation site."""

    kind: str                      # "thread" | "timer" | "executor"
    line: int
    node: str                      # node key the spawn happens in
    target: Optional[str] = None   # entry node key, when resolvable
    daemon: bool = False           # daemon=True kwarg at the ctor
    bound: Optional[Tuple[str, str]] = None  # ("self", attr)|("local", n)
    #: every name the spawn is bound to — a chained assignment
    #: (``pool = self._pool = Executor(...)``) yields several live
    #: handles, and a lifecycle seam through ANY of them counts
    bounds: List[Tuple[str, str]] = dataclasses.field(
        default_factory=list)
    structured: bool = False       # executor opened by a with-block
    ctor: str = ""                 # resolved constructor name

    def bind(self, scope: str, name: str) -> None:
        self.bound = (scope, name)
        self.bounds.append((scope, name))


class NodeInfo:
    """Per method (or nested function) facts."""

    def __init__(self, key: str) -> None:
        self.key = key
        self.calls: Set[str] = set()         # callee node keys
        #: (callee key, locks held at the call site) — feeds the
        #: caller-held-lock propagation for `_locked`-style helpers
        self.call_sites: List[Tuple[str, frozenset]] = []
        self.accesses: List[Access] = []
        self.spawn_lines: List[int] = []     # thread-publication points
        self.local_joins: Set[str] = set()   # locals .join()/.cancel()ed
        self.local_daemons: Set[str] = set()  # locals with .daemon = True
        self.local_shutdowns: Set[str] = set()


class ClassInfo:
    """Phase-one index of one class: locks, accesses, spawns, edges."""

    def __init__(self, mod: Module, node: ast.ClassDef) -> None:
        self.mod = mod
        self.name = node.name
        self.node = node
        self.methods: Dict[str, ast.AST] = {
            n.name: n for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs: Set[str] = set()
        self.safe_attrs: Set[str] = set()
        self.container_attrs: Set[str] = set()
        self.attr_types: Dict[str, str] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.spawns: List[SpawnSite] = []
        self.entries: Set[str] = set()
        #: attr → directives ("publish-only" / "guarded-by:<lock>")
        self.annotations: Dict[str, Set[str]] = {}
        #: attr → method names ever called on it (stop-event detection)
        self.attr_calls: Dict[str, Set[str]] = {}
        self.joined_attrs: Set[str] = set()
        self.daemon_attrs: Set[str] = set()

    # -- reachability -------------------------------------------------------

    def reachable_from(self, entry: str) -> Set[str]:
        seen: Set[str] = set()
        stack = [entry]
        while stack:
            k = stack.pop()
            if k in seen or k not in self.nodes:
                continue
            seen.add(k)
            stack.extend(self.nodes[k].calls)
        return seen

    def held_locks(self) -> Dict[str, frozenset]:
        """Caller-held-lock propagation: the locks a node can rely on
        its callers holding. A ``_pick_locked()``-style private helper
        called only from ``with self._cv:`` regions inherits ``_cv``.
        Public methods and thread entries are callable from anywhere
        and inherit nothing; closures are only callable where visible,
        so they always qualify. Fixpoint over the call graph with the
        intersection of (site locks | caller's inherited locks) across
        every call site."""
        top = frozenset(self.lock_attrs)
        sites: Dict[str, List[Tuple[str, frozenset]]] = {}
        for caller, info in self.nodes.items():
            for callee, lks in info.call_sites:
                if callee in self.nodes:
                    sites.setdefault(callee, []).append((caller, lks))
        held: Dict[str, frozenset] = {}
        pinned: Set[str] = set()
        for key in self.nodes:
            nested = ".<" in key
            private = key.startswith("_") and not key.startswith("__")
            if key in self.entries or not (nested or private):
                held[key] = frozenset()
                pinned.add(key)
            elif not sites.get(key):
                held[key] = frozenset()
            else:
                held[key] = top
        changed = True
        while changed:
            changed = False
            for key, slist in sites.items():
                if key in pinned:
                    continue
                new = frozenset.intersection(*[
                    lks | held.get(caller, frozenset())
                    for caller, lks in slist])
                if new != held[key]:
                    held[key] = new
                    changed = True
        return held

    def domains_of(self) -> Dict[str, frozenset]:
        """node key → the thread domains it runs in: one domain per
        spawn entry whose reachable set contains it, else the caller
        ("main") domain."""
        per_entry = {e: self.reachable_from(e) for e in self.entries}
        out: Dict[str, frozenset] = {}
        for key in self.nodes:
            doms = frozenset(
                f"thread:{e}" for e, reach in per_entry.items()
                if key in reach)
            out[key] = doms or frozenset({"main"})
        return out


class ConcurrencyIndex:
    """The whole-package phase-one product shared by both rules."""

    def __init__(self) -> None:
        self.classes: List[ClassInfo] = []
        #: spawns in module-level functions (no ``self`` state to race,
        #: but the lifecycle contract still applies)
        self.function_spawns: List[Tuple[Module, SpawnSite, NodeInfo]] = []


def get_index(package: Package) -> ConcurrencyIndex:
    """Build (once per run) and share the package index."""
    idx = package.cache.get(_INDEX_CACHE_KEY)
    if idx is None:
        idx = build_index(package.modules)
        package.cache[_INDEX_CACHE_KEY] = idx
    return idx


def build_index(modules: Sequence[Module]) -> ConcurrencyIndex:
    index = ConcurrencyIndex()
    for mod in modules:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                index.classes.append(_index_class(mod, node))
        _index_module_functions(mod, index)
    return index


def _index_module_functions(mod: Module, index: ConcurrencyIndex) -> None:
    """Spawn/lifecycle facts for functions outside classes."""
    fn_types = (ast.FunctionDef, ast.AsyncFunctionDef)
    class_fns = {
        id(fn) for cls in ast.walk(mod.tree)
        if isinstance(cls, ast.ClassDef)
        for fn in ast.walk(cls) if isinstance(fn, fn_types)
    }
    # closures inside module functions belong to their parent's scan —
    # only top functions get their own pass
    nested_fns = {
        id(inner) for outer in ast.walk(mod.tree)
        if isinstance(outer, fn_types)
        for inner in ast.walk(outer)
        if inner is not outer and isinstance(inner, fn_types)
    }
    for fn in ast.walk(mod.tree):
        if not isinstance(fn, fn_types):
            continue
        if id(fn) in class_fns or id(fn) in nested_fns:
            continue
        dummy = ClassInfo(mod, ast.ClassDef(
            name="<module>", bases=[], keywords=[], body=[],
            decorator_list=[]))
        scanner = _FunctionScanner(dummy, mod)
        info = scanner.scan(fn, fn.name, is_init=False)
        for site in dummy.spawns:
            index.function_spawns.append((mod, site, info))


def _index_class(mod: Module, node: ast.ClassDef) -> ClassInfo:
    cls = ClassInfo(mod, node)
    _collect_lock_and_safe_attrs(cls, mod)
    scanner = _FunctionScanner(cls, mod)
    for name, fn in cls.methods.items():
        cls.nodes[name] = scanner.scan(fn, name, is_init=(name == "__init__"))
    # spawn targets become thread entries
    for site in cls.spawns:
        if site.target is not None and site.target in cls.nodes:
            cls.entries.add(site.target)
    return cls


def _collect_lock_and_safe_attrs(cls: ClassInfo, mod: Module) -> None:
    """Pass A: lock attributes (typed lock assignment anywhere, or a
    lock-named ``with self.<attr>:`` guard) and thread-safe-typed
    attributes (queue/event/contextvar handoffs)."""
    for sub in ast.walk(cls.node):
        if isinstance(sub, (ast.Assign, ast.AnnAssign)):
            targets = sub.targets if isinstance(sub, ast.Assign) \
                else [sub.target]
            value = sub.value
            if value is None:
                continue
            rname = (mod.resolved(value.func) or "") \
                if isinstance(value, ast.Call) else ""
            container = (rname in _CONTAINER_CTORS
                         or isinstance(value, _CONTAINER_LITERALS))
            for tgt in targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    if rname in _LOCK_TYPES:
                        cls.lock_attrs.add(tgt.attr)
                    if rname in _SAFE_TYPES:
                        cls.safe_attrs.add(tgt.attr)
                    if container:
                        cls.container_attrs.add(tgt.attr)
                    if rname:
                        cls.attr_types[tgt.attr] = rname
        elif isinstance(sub, (ast.With, ast.AsyncWith)):
            for item in sub.items:
                path = _self_dotted(item.context_expr)
                if (path is not None
                        and _LOCK_NAME_RE.search(path.rsplit(".", 1)[-1])):
                    cls.lock_attrs.add(path)
    cls.safe_attrs |= cls.lock_attrs
    # the root of a dotted lock path (``client`` in ``client.lock``) is
    # reached in order to TAKE the lock, so it cannot itself be guarded
    # by it — it must be stably published (Java's final-field rule for
    # @GuardedBy paths); exempt it from inference
    cls.safe_attrs |= {p.split(".", 1)[0] for p in cls.lock_attrs
                       if "." in p}


class _FunctionScanner:
    """Single-method walker: guard regions (nested + aliased locks),
    attribute accesses, spawn sites, call edges. Nested functions get
    their own node (``method.<name>``) — a closure handed to
    ``threading.Thread(target=run)`` is its own thread entry, while
    code before the spawn stays in the caller's domain."""

    def __init__(self, cls: ClassInfo, mod: Module) -> None:
        self.cls = cls
        self.mod = mod

    def scan(self, fn: ast.AST, key: str, is_init: bool) -> NodeInfo:
        info = NodeInfo(key)
        self.cls.nodes[key] = info
        nested_defs = [n for n in fn.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
        # pre-register nested names so a spawn can resolve a target
        # defined later in the body too
        self._nested_names = {n.name for n in ast.walk(fn)
                              if isinstance(n, (ast.FunctionDef,
                                                ast.AsyncFunctionDef))
                              and n is not fn}
        self._info = info
        self._spawned_calls: Dict[int, SpawnSite] = {}
        aliases: Dict[str, str] = {}
        for stmt in fn.body:
            self._visit(stmt, frozenset(), aliases)
        # nested functions (any depth) become their own nodes
        collected: List[ast.AST] = []

        def collect(n: ast.AST) -> None:
            for child in ast.walk(n):
                if (isinstance(child, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                        and child is not n):
                    collected.append(child)
        collect(fn)
        saved = (self._info, self._spawned_calls, self._nested_names)
        for sub in collected:
            sub_key = f"{key}.<{sub.name}>"
            sub_info = NodeInfo(sub_key)
            self.cls.nodes[sub_key] = sub_info
            self._info = sub_info
            self._spawned_calls = {}
            self._nested_names = set()
            sub_aliases: Dict[str, str] = {}
            for stmt in sub.body:
                self._visit(stmt, frozenset(), sub_aliases)
        self._info, self._spawned_calls, self._nested_names = saved
        # init-time / pre-spawn accesses are pre-publication: no other
        # thread can observe them (RacerD's ownership rule). Scope:
        # __init__ wholesale unless __init__ itself spawns, else only
        # lines before the method's first spawn.
        first_spawn = min(info.spawn_lines, default=None)
        pruned: List[Access] = []
        for a in info.accesses:
            if is_init and (first_spawn is None or a.line < first_spawn):
                continue
            if (not is_init and first_spawn is not None
                    and a.line < first_spawn):
                continue
            pruned.append(a)
        info.accesses = pruned
        del nested_defs
        return info

    # -- recording ----------------------------------------------------------

    def _record(self, attr: str, kind: str, line: int,
                locks: frozenset) -> None:
        cls = self.cls
        if attr in cls.safe_attrs or attr in cls.methods:
            if attr in cls.methods:
                self._info.calls.add(attr)
                self._info.call_sites.append((attr, locks))
            return
        for d in self.mod.annotations_at(line):
            cls.annotations.setdefault(attr, set()).add(d)
        self._info.accesses.append(Access(
            attr=attr, kind=kind, line=line, locks=locks,
            node=self._info.key))

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return node.attr
        return None

    def _self_root_attr(self, node: ast.AST) -> Optional[str]:
        """Innermost self attribute of an attribute/subscript chain
        (``self.a.b[c].d`` → ``a``)."""
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            inner = self._self_attr(node)
            if inner is not None:
                return inner
            node = node.value
        return None

    def _lock_of(self, expr: ast.AST, aliases: Dict[str, str]
                 ) -> Optional[str]:
        path = _self_dotted(expr)
        if path is not None and path in self.cls.lock_attrs:
            return path
        if isinstance(expr, ast.Name):
            return aliases.get(expr.id)
        return None

    # -- the walk -----------------------------------------------------------

    def _visit(self, node: ast.AST, locks: frozenset,
               aliases: Dict[str, str]) -> None:
        if node is None:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # separate node; scanned by the caller
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(locks)
            for item in node.items:
                self._visit(item.context_expr, locks, aliases)
                lk = self._lock_of(item.context_expr, aliases)
                if lk is not None:
                    inner.add(lk)
                # `with ThreadPoolExecutor(...) as pool:` — structured
                if isinstance(item.context_expr, ast.Call):
                    site = self._spawned_calls.get(
                        id(item.context_expr))
                    if site is not None:
                        site.structured = True
                        if (item.optional_vars is not None
                                and isinstance(item.optional_vars,
                                               ast.Name)):
                            site.bind("local",
                                      item.optional_vars.id)
            inner_f = frozenset(inner)
            for stmt in node.body:
                self._visit(stmt, inner_f, aliases)
            return
        if isinstance(node, ast.Assign):
            self._visit(node.value, locks, aliases)
            for tgt in node.targets:
                self._visit_target(tgt, locks, aliases)
            self._post_assign(node.targets, node.value, aliases)
            return
        if isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._visit(node.value, locks, aliases)
                self._post_assign([node.target], node.value, aliases)
            self._visit_target(node.target, locks, aliases)
            return
        if isinstance(node, ast.AugAssign):
            self._visit(node.value, locks, aliases)
            attr = self._self_attr(node.target)
            if attr is not None:
                self._record(attr, "write", node.lineno, locks)
            else:
                self._visit_target(node.target, locks, aliases)
            return
        if isinstance(node, ast.Delete):
            for tgt in node.targets:
                self._visit_target(tgt, locks, aliases)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, locks, aliases)
            return
        if isinstance(node, ast.Attribute):
            attr = self._self_attr(node)
            if attr is not None:
                kind = ("write" if isinstance(node.ctx,
                                              (ast.Store, ast.Del))
                        else "read")
                self._record(attr, kind, node.lineno, locks)
                return
            self._visit(node.value, locks, aliases)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, locks, aliases)

    def _visit_target(self, tgt: ast.AST, locks: frozenset,
                      aliases: Dict[str, str]) -> None:
        """Assignment/delete target: a store through a self attribute —
        direct (``self.x = v``), item (``self.x[k] = v``), or nested
        (``self.x.y = v``) — is a write of the root attribute."""
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._visit_target(el, locks, aliases)
            return
        if isinstance(tgt, ast.Starred):
            self._visit_target(tgt.value, locks, aliases)
            return
        root = self._self_root_attr(tgt)
        if root is not None:
            self._record(root, "write", tgt.lineno, locks)
            if isinstance(tgt, ast.Subscript):
                self._visit(tgt.slice, locks, aliases)
            return
        # t.daemon = True on a local thread handle
        if (isinstance(tgt, ast.Attribute) and tgt.attr == "daemon"
                and isinstance(tgt.value, ast.Name)):
            self._info.local_daemons.add(tgt.value.id)
        if isinstance(tgt, ast.Subscript):
            self._visit(tgt.value, locks, aliases)
            self._visit(tgt.slice, locks, aliases)

    def _post_assign(self, targets: Sequence[ast.AST], value: ast.AST,
                     aliases: Dict[str, str]) -> None:
        """Track lock aliases (``lk = self._lock``), spawn bindings
        (``self._thread = threading.Thread(...)``), and daemon flags.
        Chained assignments (``pool = self._pool = Executor(...)``)
        bind every target — each one is a live handle to the spawn."""
        for tgt in targets:
            self._post_assign_one(tgt, value, aliases)

    def _post_assign_one(self, tgt: ast.AST, value: ast.AST,
                         aliases: Dict[str, str]) -> None:
        if isinstance(tgt, ast.Name):
            lk = self._lock_of(value, aliases)
            if lk is not None:
                aliases[tgt.id] = lk
            else:
                aliases.pop(tgt.id, None)
            site = self._spawned_calls.get(id(value))
            if site is not None:
                site.bind("local", tgt.id)
        else:
            attr = self._self_attr(tgt)
            if attr is not None:
                site = self._spawned_calls.get(id(value))
                if site is not None:
                    site.bind("self", attr)
                if (isinstance(value, ast.Constant)
                        and value.value is True and attr == "daemon"):
                    pass  # self.daemon = True is not a thread handle
        # self.<attr>.daemon = True
        if (isinstance(tgt, ast.Attribute) and tgt.attr == "daemon"
                and isinstance(value, ast.Constant)
                and value.value is True):
            base = self._self_attr(tgt.value)
            if base is not None:
                self.cls.daemon_attrs.add(base)
            elif isinstance(tgt.value, ast.Name):
                self._info.local_daemons.add(tgt.value.id)

    # -- calls --------------------------------------------------------------

    def _visit_call(self, node: ast.Call, locks: frozenset,
                    aliases: Dict[str, str]) -> None:
        f = node.func
        consumed: Optional[ast.AST] = None
        if isinstance(f, ast.Attribute):
            recv_attr = self._self_attr(f.value)
            if self._self_attr(f) is not None:
                # self.m(...) — call edge (methods/properties) or a
                # read of a stored callable
                self._record(f.attr, "read", f.lineno, locks)
                consumed = f
            elif recv_attr is not None:
                # self.<attr>.<m>(...) — a mutator counts as a write
                # only on a plain container; a domain object owns its
                # own synchronization (deep ownership)
                self.cls.attr_calls.setdefault(recv_attr, set()).add(
                    f.attr)
                kind = ("write" if f.attr in _MUTATING_CALLS
                        and recv_attr in self.cls.container_attrs
                        else "read")
                self._record(recv_attr, kind, f.lineno, locks)
                if f.attr in ("join", "cancel"):
                    self.cls.joined_attrs.add(recv_attr)
                consumed = f.value
            elif isinstance(f.value, ast.Name):
                if f.attr in ("join", "cancel"):
                    self._info.local_joins.add(f.value.id)
                elif f.attr == "shutdown":
                    self._info.local_shutdowns.add(f.value.id)
            if f.attr == "submit" and node.args:
                self._register_submit(node, locks)
        rname = self.mod.resolved(node.func) or ""
        if rname in _THREAD_CTORS:
            self._register_thread_ctor(node, _THREAD_CTORS[rname],
                                       rname)
        elif rname in _EXECUTOR_CTORS:
            site = SpawnSite(kind="executor", line=node.lineno,
                             node=self._info.key, ctor=rname)
            self.cls.spawns.append(site)
            self._spawned_calls[id(node)] = site
            self._info.spawn_lines.append(node.lineno)
        # local nested-def call: run() invoked synchronously
        if isinstance(f, ast.Name) and f.id in self._nested_names:
            nested_key = f"{self._info.key}.<{f.id}>"
            self._info.calls.add(nested_key)
            self._info.call_sites.append((nested_key, locks))
        if consumed is None and not isinstance(f, ast.Name):
            self._visit(f, locks, aliases)
        for arg in node.args:
            self._visit(arg, locks, aliases)
        for kw in node.keywords:
            self._visit(kw.value, locks, aliases)

    def _callable_key(self, expr: ast.AST) -> Optional[str]:
        """Entry node key for a callable handed to a thread/executor:
        a bound method (``self._run``) or a nested function name."""
        attr = self._self_attr(expr)
        if attr is not None and attr in self.cls.methods:
            return attr
        if isinstance(expr, ast.Name) and expr.id in self._nested_names:
            base = self._info.key
            return f"{base}.<{expr.id}>"
        return None

    def _register_thread_ctor(self, node: ast.Call, kind: str,
                              rname: str) -> None:
        daemon = any(
            kw.arg == "daemon" and isinstance(kw.value, ast.Constant)
            and kw.value.value is True for kw in node.keywords)
        target_expr: Optional[ast.AST] = None
        if kind == "thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
        else:  # Timer(interval, function, ...)
            for kw in node.keywords:
                if kw.arg == "function":
                    target_expr = kw.value
            if target_expr is None and len(node.args) >= 2:
                target_expr = node.args[1]
        target = (self._callable_key(target_expr)
                  if target_expr is not None else None)
        site = SpawnSite(kind=kind, line=node.lineno,
                         node=self._info.key, target=target,
                         daemon=daemon, ctor=rname)
        self.cls.spawns.append(site)
        self._spawned_calls[id(node)] = site
        self._info.spawn_lines.append(node.lineno)

    def _register_submit(self, node: ast.Call, locks: frozenset) -> None:
        target = self._callable_key(node.args[0])
        if target is None:
            return
        site = SpawnSite(kind="executor", line=node.lineno,
                         node=self._info.key, target=target,
                         daemon=True,  # pool workers are pool-managed
                         structured=True, ctor="submit")
        self.cls.spawns.append(site)
        self._info.spawn_lines.append(node.lineno)


# ---------------------------------------------------------------------------
# phase two: the rules
# ---------------------------------------------------------------------------


class UnguardedSharedState:
    name = "unguarded-shared-state"
    severity = "error"
    whole_program = True
    doc = ("attribute written on a thread-entry-reachable path and "
           "accessed on another path with inconsistent lock protection "
           "(GuardedBy inferred from the class's own `with self._lock:` "
           "regions) — also verifies the `# pio-lint: guarded-by(<lock>)`"
           " / `# pio-lint: publish-only` annotations and exempts the "
           "sanctioned idioms: queue/event/contextvar handoff, "
           "pre-publication `__init__` writes, single-writer "
           "immutable-publish")

    def check(self, mod: Module) -> Iterator[Finding]:
        return iter(())  # v2 rule: per-file phase contributes nothing

    def check_package(self, package: Package) -> Iterator[Finding]:
        index = get_index(package)
        for cls in index.classes:
            if not cls.entries:
                continue
            yield from self._check_class(cls)

    # -- per-class analysis -------------------------------------------------

    def _check_class(self, cls: ClassInfo) -> Iterator[Finding]:
        domains = cls.domains_of()
        held = cls.held_locks()
        by_attr: Dict[str, List[Access]] = {}
        for info in cls.nodes.values():
            for a in info.accesses:
                inherited = held.get(a.node, frozenset())
                if inherited:
                    a = dataclasses.replace(
                        a, locks=a.locks | inherited)
                by_attr.setdefault(a.attr, []).append(a)
        for attr in sorted(by_attr):
            if attr.startswith("__"):
                continue
            yield from self._check_attr(cls, attr, by_attr[attr],
                                        domains)

    def _check_attr(self, cls: ClassInfo, attr: str,
                    accesses: List[Access],
                    domains: Dict[str, frozenset]) -> Iterator[Finding]:
        mod = cls.mod
        writes = [a for a in accesses if a.kind == "write"]
        if not writes:
            return
        ann = cls.annotations.get(attr, set())
        dom = {a: domains.get(a.node, frozenset({"main"}))
               for a in accesses}

        gb = next((m.group(1) for d in ann
                   for m in [_GUARDED_BY_RE.match(d)] if m), None)
        if gb is not None:
            for w in writes:
                if gb not in w.locks:
                    yield mod.finding_at(
                        self, w.line,
                        f"`self.{attr}` ({cls.name}) is declared "
                        f"guarded-by({gb}) but this write holds "
                        + (f"{{{', '.join(sorted(w.locks))}}}"
                           if w.locks else "no lock")
                        + f" — every write must hold `self.{gb}`")
            return

        if "publish-only" in ann:
            ordered = sorted(writes, key=lambda w: w.line)
            primary = dom[ordered[0]]
            for w in ordered:
                if dom[w] == primary:
                    continue
                yield mod.finding_at(
                    self, w.line,
                    f"`self.{attr}` ({cls.name}) is declared "
                    "publish-only (single-writer immutable-publish) "
                    "but is written from more than one thread domain "
                    "— the idiom is only safe with exactly one writer")
            return

        # cross-domain conflict: a write in one domain, any access in
        # another — same-domain state (however racy it looks) is
        # sequential and out of scope
        conflict = any(dom[w] != dom[a] for w in writes for a in accesses)
        if not conflict:
            return

        guarded = [a for a in accesses if a.locks]
        unguarded = [a for a in accesses if not a.locks]
        if not guarded:
            yield from self._flag_fully_unguarded(
                cls, attr, accesses, writes, dom)
            return
        # GuardedBy inference: a lock held at EVERY access means the
        # discipline is consistent; otherwise infer the majority lock
        # and flag the accesses that skip it
        common = frozenset.intersection(*[a.locks for a in accesses])
        if common:
            return
        counts: Dict[str, int] = {}
        for a in guarded:
            for lk in a.locks:
                counts[lk] = counts.get(lk, 0) + 1
        inferred = max(sorted(counts), key=lambda k: counts[k])
        n_guarded = sum(1 for a in guarded if inferred in a.locks)
        seen_lines: Set[int] = set()
        for a in sorted(accesses, key=lambda a: a.line):
            if inferred in a.locks or a.line in seen_lines:
                continue
            seen_lines.add(a.line)
            where = ("a thread-entry path"
                     if dom[a] != frozenset({"main"})
                     else "the caller side")
            yield mod.finding_at(
                self, a.line,
                f"`self.{attr}` ({cls.name}) {a.kind} without "
                f"`self.{inferred}` on {where} — {n_guarded} other "
                f"access(es) of this attribute hold it (inferred "
                f"GuardedBy({inferred})); hold the lock here or "
                "declare the idiom (docs/lint.md \"Concurrency "
                "contract\")")

    def _flag_fully_unguarded(self, cls: ClassInfo, attr: str,
                              accesses: List[Access],
                              writes: List[Access],
                              dom: Dict[Access, frozenset]
                              ) -> Iterator[Finding]:
        """No lock anywhere: report once per attribute, anchored at the
        first thread-side write (per the rule contract, a write must be
        thread-entry-reachable to count as a race here)."""
        thread_writes = [w for w in writes
                         if dom[w] != frozenset({"main"})]
        if not thread_writes:
            return
        w = min(thread_writes, key=lambda a: a.line)
        others = sorted({a.line for a in accesses
                         if dom[a] != dom[w]})
        entry = sorted(dom[w])[0].partition(":")[2]
        yield cls.mod.finding_at(
            self, w.line,
            f"`self.{attr}` ({cls.name}) is written on the "
            f"{entry!r} thread path and accessed from other paths "
            f"(line(s) {', '.join(map(str, others))}) with no lock "
            "held anywhere — guard it, hand it over via queue.Queue, "
            "or declare `# pio-lint: publish-only` if it is a "
            "single-writer immutable publish (docs/lint.md "
            "\"Concurrency contract\")")


class ThreadLifecycle:
    name = "thread-lifecycle"
    severity = "warning"
    whole_program = True
    doc = ("spawned thread/timer with no daemon flag, stop-event, or "
           "join seam (a leaked non-daemon thread blocks interpreter "
           "exit and is why shutdown-race tests exist), or a "
           "ThreadPoolExecutor neither scoped by `with` nor ever shut "
           "down")

    def check(self, mod: Module) -> Iterator[Finding]:
        return iter(())

    def check_package(self, package: Package) -> Iterator[Finding]:
        index = get_index(package)
        for cls in index.classes:
            stop_event = self._has_stop_event(cls)
            for site in cls.spawns:
                yield from self._check_site(cls.mod, site, cls,
                                            stop_event)
        for mod, site, info in index.function_spawns:
            yield from self._check_site(mod, site, None, False,
                                        fn_info=info)

    @staticmethod
    def _has_stop_event(cls: ClassInfo) -> bool:
        """A stop-event discipline: an Event attribute that some method
        sets and the loop side waits on / polls."""
        for attr, typ in cls.attr_types.items():
            if typ != "threading.Event":
                continue
            calls = cls.attr_calls.get(attr, set())
            if "set" in calls and ({"wait", "is_set"} & calls):
                return True
        return False

    def _check_site(self, mod: Module, site: SpawnSite,
                    cls: Optional[ClassInfo], stop_event: bool,
                    fn_info: Optional[NodeInfo] = None
                    ) -> Iterator[Finding]:
        if site.kind == "executor":
            if site.ctor == "submit" or site.structured:
                return
            shut = False
            for scope, name in site.bounds:
                if cls is not None:
                    if scope == "self":
                        shut = "shutdown" in cls.attr_calls.get(
                            name, set())
                    else:
                        shut = any(name in n.local_shutdowns
                                   for n in cls.nodes.values())
                elif fn_info is not None:
                    shut = name in fn_info.local_shutdowns
                if shut:
                    break
            if not shut:
                yield mod.finding_at(
                    self, site.line,
                    "ThreadPoolExecutor created outside a `with` block "
                    "and never shut down — workers leak past the "
                    "owner's lifetime; scope it with `with` or keep a "
                    ".shutdown() seam")
            return
        if site.daemon:
            return
        if self._daemon_set_later(site, cls, fn_info):
            return
        if self._join_seam(site, cls, fn_info):
            return
        if stop_event:
            return
        what = ("threading.Timer" if site.kind == "timer"
                else "threading.Thread")
        yield mod.finding_at(
            self, site.line,
            f"{what} spawned with no daemon flag, stop-event, or join "
            "seam — a leaked non-daemon thread blocks interpreter exit "
            "(and survives its owner); pass daemon=True, keep a "
            ".join()/.cancel() seam, or guard the loop with a stop "
            "Event")

    @staticmethod
    def _daemon_set_later(site: SpawnSite, cls: Optional[ClassInfo],
                          fn_info: Optional[NodeInfo]) -> bool:
        for scope, name in site.bounds:
            if scope == "self":
                if cls is not None and name in cls.daemon_attrs:
                    return True
                continue
            if cls is not None:
                node = cls.nodes.get(site.node)
                if node is not None and name in node.local_daemons:
                    return True
            if fn_info is not None and name in fn_info.local_daemons:
                return True
        return False

    @staticmethod
    def _join_seam(site: SpawnSite, cls: Optional[ClassInfo],
                   fn_info: Optional[NodeInfo]) -> bool:
        for scope, name in site.bounds:
            if scope == "self":
                if cls is not None and name in cls.joined_attrs:
                    return True
                continue
            if cls is not None:
                node = cls.nodes.get(site.node)
                if node is not None and name in node.local_joins:
                    return True
            if fn_info is not None and name in fn_info.local_joins:
                return True
        return False


__all__ = [
    "Access", "ClassInfo", "ConcurrencyIndex", "NodeInfo", "SpawnSite",
    "ThreadLifecycle", "UnguardedSharedState", "build_index",
    "get_index",
]
