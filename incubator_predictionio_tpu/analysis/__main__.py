"""CLI for pio-lint: ``python -m incubator_predictionio_tpu.analysis``.

Exit codes: 0 = clean (modulo inline suppressions and, with
``--baseline``, the baseline file), 1 = unsuppressed findings, 2 = a
scanned file failed to parse or the invocation was malformed.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List

from incubator_predictionio_tpu.analysis.engine import (
    apply_baseline,
    default_baseline_path,
    lint_paths,
    load_baseline,
    package_root,
    write_baseline,
)
from incubator_predictionio_tpu.analysis.rules import ALL_RULES, RULES_BY_NAME


def _entry_in_scope(entry: dict, rules, paths: List[Path]) -> bool:
    """Whether this run could even SEE the entry's finding: its rule is
    selected and its file is under one of the scanned paths."""
    if entry["rule"] not in {r.name for r in rules}:
        return False
    from incubator_predictionio_tpu.analysis.engine import _relpath
    for p in paths:
        rel = _relpath(p)
        if entry["path"] == rel or entry["path"].startswith(
                rel.rstrip("/") + "/"):
            return True
    return False


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m incubator_predictionio_tpu.analysis",
        description="pio-lint: TPU/JAX-aware static analysis "
                    "(docs/lint.md)")
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to scan (default: the "
             "incubator_predictionio_tpu package)")
    parser.add_argument(
        "--baseline", action="store_true",
        help="subtract the checked-in analysis/baseline.json (this is "
             "also the default when it exists; the flag makes CI "
             "invocations explicit)")
    parser.add_argument(
        "--baseline-path", type=Path, default=None, metavar="PATH",
        help="subtract a specific baseline JSON instead of the "
             "checked-in one")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report baselined findings too (audit mode)")
    parser.add_argument(
        "--write-baseline", nargs="?", const=default_baseline_path(),
        type=Path, default=None, metavar="PATH",
        help="write the current findings as a fresh baseline and exit 0 "
             "(every entry then needs a hand-written justification)")
    parser.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule names to run (default: all)")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule with its severity and hazard class")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name} ({rule.severity}): {rule.doc}")
        return 0

    rules = list(ALL_RULES)
    if args.select:
        names = [n.strip() for n in args.select.split(",") if n.strip()]
        unknown = [n for n in names if n not in RULES_BY_NAME]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(known: {', '.join(RULES_BY_NAME)})", file=sys.stderr)
            return 2
        rules = [RULES_BY_NAME[n] for n in names]

    paths = args.paths or [package_root()]
    parse_errors: List[str] = []
    findings = lint_paths(paths, rules, on_parse_error=parse_errors)
    for err in parse_errors:
        print(f"parse error: {err}", file=sys.stderr)

    if args.write_baseline is not None:
        # under --select / explicit paths this run cannot see every
        # entry's finding — carry out-of-scope entries over verbatim
        # instead of silently deleting their curated justifications
        keep: List[dict] = []
        if args.write_baseline.exists():
            try:
                keep = [e for e in load_baseline(args.write_baseline)
                        if not _entry_in_scope(e, rules, paths)]
            except (OSError, ValueError):
                keep = []
        write_baseline(args.write_baseline, findings, keep_entries=keep)
        print(f"wrote {len(findings) + len(keep)} baseline entries to "
              f"{args.write_baseline}"
              + (f" ({len(keep)} out-of-scope kept)" if keep else ""))
        return 0 if not parse_errors else 2

    baseline_path = args.baseline_path
    if (baseline_path is None and not args.no_baseline
            and (args.baseline or default_baseline_path().exists())):
        baseline_path = default_baseline_path()
    stale: List[dict] = []
    if baseline_path is not None and not args.no_baseline:
        try:
            entries = load_baseline(baseline_path)
        except (OSError, ValueError) as exc:
            print(f"cannot load baseline {baseline_path}: {exc}",
                  file=sys.stderr)
            return 2
        # a filtered run (--select / explicit paths) never produces
        # findings for out-of-scope entries — judging those "stale"
        # would tell the developer to prune entries the full run needs
        in_scope = [e for e in entries
                    if _entry_in_scope(e, rules, paths)]
        findings, stale = apply_baseline(findings, in_scope)

    for f in findings:
        print(f.format())
    for e in stale:
        print(f"stale baseline entry (fixed or drifted — prune it): "
              f"{e['path']}: [{e['rule']}] {e['snippet']}",
              file=sys.stderr)

    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    if findings:
        print(f"pio-lint: {n_err} error(s), {n_warn} warning(s)")
        # parse errors outrank findings: part of the tree went unlinted
        return 2 if parse_errors else 1
    print("pio-lint: clean"
          + (f" ({len(stale)} stale baseline entries)" if stale else ""))
    return 2 if parse_errors else 0


if __name__ == "__main__":
    sys.exit(main())
