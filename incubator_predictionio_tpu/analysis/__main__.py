"""CLI for pio-lint: ``python -m incubator_predictionio_tpu.analysis``.

Exit codes: 0 = clean (modulo inline suppressions and, with
``--baseline``, the baseline file), 1 = unsuppressed findings, 2 = a
scanned file failed to parse or the invocation was malformed.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Dict, List, Optional

from incubator_predictionio_tpu.analysis.engine import (
    Finding,
    apply_baseline,
    default_baseline_path,
    lint_paths,
    load_baseline,
    package_root,
    save_baseline_entries,
    write_baseline,
)
from incubator_predictionio_tpu.analysis.rules import ALL_RULES, RULES_BY_NAME


def _entry_in_scope(entry: dict, rules, paths: List[Path]) -> bool:
    """Whether this run could even SEE the entry's finding: its rule is
    selected and its file is under one of the scanned paths."""
    if entry["rule"] not in {r.name for r in rules}:
        return False
    from incubator_predictionio_tpu.analysis.engine import _relpath
    for p in paths:
        rel = _relpath(p)
        if entry["path"] == rel or entry["path"].startswith(
                rel.rstrip("/") + "/"):
            return True
    return False


def _finding_json(f: Finding, suppressed: bool) -> dict:
    return {"rule": f.rule, "severity": f.severity, "path": f.path,
            "line": f.line, "message": f.message, "snippet": f.snippet,
            "suppressed": suppressed}


def _report_json(findings: List[Finding], suppressed: List[Finding],
                 stale: List[dict], parse_errors: List[str],
                 timings: Optional[Dict[str, float]]) -> dict:
    """The machine-readable report: every surviving finding plus the
    inline-suppressed ones (flagged, so CI can audit suppressions);
    baseline-matched findings are deliberate exceptions and excluded."""
    n_err = sum(1 for f in findings if f.severity == "error")
    doc = {
        "version": 1,
        "findings": ([_finding_json(f, False) for f in findings]
                     + [_finding_json(f, True) for f in suppressed]),
        "staleBaseline": [{"rule": e["rule"], "path": e["path"],
                           "snippet": e["snippet"]} for e in stale],
        "parseErrors": list(parse_errors),
        "summary": {"errors": n_err,
                    "warnings": len(findings) - n_err,
                    "suppressed": len(suppressed),
                    "clean": not findings and not parse_errors},
    }
    if timings is not None:
        doc["ruleTimingsMs"] = {
            name: round(sec * 1e3, 3)
            for name, sec in sorted(timings.items())}
    return doc


def main(argv: List[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m incubator_predictionio_tpu.analysis",
        description="pio-lint: TPU/JAX-aware static analysis "
                    "(docs/lint.md)")
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to scan (default: the "
             "incubator_predictionio_tpu package)")
    parser.add_argument(
        "--baseline", action="store_true",
        help="subtract the checked-in analysis/baseline.json (this is "
             "also the default when it exists; the flag makes CI "
             "invocations explicit)")
    parser.add_argument(
        "--baseline-path", type=Path, default=None, metavar="PATH",
        help="subtract a specific baseline JSON instead of the "
             "checked-in one")
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="report baselined findings too (audit mode)")
    parser.add_argument(
        "--write-baseline", nargs="?", const=default_baseline_path(),
        type=Path, default=None, metavar="PATH",
        help="write the current findings as a fresh baseline and exit 0 "
             "(every entry then needs a hand-written justification)")
    parser.add_argument(
        "--prune-baseline", action="store_true",
        help="rewrite the active baseline without its stale entries "
             "(entries whose finding no longer occurs), keeping every "
             "surviving justification verbatim")
    parser.add_argument(
        "--select", default=None, metavar="RULES",
        help="comma-separated rule names to run (default: all)")
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="findings output format on stdout (json: one document "
             "with rule/severity/file/line/message/suppressed per "
             "finding)")
    parser.add_argument(
        "--json-out", type=Path, default=None, metavar="FILE",
        help="also write the JSON report to FILE (CI artifact) while "
             "stdout keeps the chosen --format")
    parser.add_argument(
        "--timings", action="store_true",
        help="report per-rule wall-clock to stderr (and in the JSON "
             "report) — the tier-1 budget test keeps the whole-program "
             "phase honest as the package grows")
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print every rule with its severity and hazard class")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name} ({rule.severity}): {rule.doc}")
        return 0

    rules = list(ALL_RULES)
    if args.select:
        names = [n.strip() for n in args.select.split(",") if n.strip()]
        unknown = [n for n in names if n not in RULES_BY_NAME]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)} "
                  f"(known: {', '.join(RULES_BY_NAME)})", file=sys.stderr)
            return 2
        rules = [RULES_BY_NAME[n] for n in names]

    paths = args.paths or [package_root()]
    parse_errors: List[str] = []
    timings: Optional[Dict[str, float]] = {} if (
        args.timings or args.format == "json"
        or args.json_out is not None) else None
    suppressed: List[Finding] = []
    findings = lint_paths(paths, rules, on_parse_error=parse_errors,
                          timings=timings, suppressed_out=suppressed)
    for err in parse_errors:
        print(f"parse error: {err}", file=sys.stderr)

    if args.write_baseline is not None:
        # under --select / explicit paths this run cannot see every
        # entry's finding — carry out-of-scope entries over verbatim
        # instead of silently deleting their curated justifications
        keep: List[dict] = []
        if args.write_baseline.exists():
            try:
                keep = [e for e in load_baseline(args.write_baseline)
                        if not _entry_in_scope(e, rules, paths)]
            except (OSError, ValueError):
                keep = []
        write_baseline(args.write_baseline, findings, keep_entries=keep)
        print(f"wrote {len(findings) + len(keep)} baseline entries to "
              f"{args.write_baseline}"
              + (f" ({len(keep)} out-of-scope kept)" if keep else ""))
        return 0 if not parse_errors else 2

    baseline_path = args.baseline_path
    if (baseline_path is None and not args.no_baseline
            and (args.baseline or args.prune_baseline
                 or default_baseline_path().exists())):
        baseline_path = default_baseline_path()
    stale: List[dict] = []
    entries: List[dict] = []
    if baseline_path is not None and not args.no_baseline:
        try:
            entries = load_baseline(baseline_path)
        except (OSError, ValueError) as exc:
            print(f"cannot load baseline {baseline_path}: {exc}",
                  file=sys.stderr)
            return 2
        # a filtered run (--select / explicit paths) never produces
        # findings for out-of-scope entries — judging those "stale"
        # would tell the developer to prune entries the full run needs
        in_scope = [e for e in entries
                    if _entry_in_scope(e, rules, paths)]
        findings, stale = apply_baseline(findings, in_scope)
    elif args.prune_baseline:
        print("--prune-baseline needs an active baseline "
              "(it conflicts with --no-baseline)", file=sys.stderr)
        return 2

    if args.prune_baseline:
        stale_ids = {id(e) for e in stale}
        survivors = [e for e in entries if id(e) not in stale_ids]
        save_baseline_entries(baseline_path, survivors)
        print(f"pruned {len(stale)} stale entr"
              f"{'y' if len(stale) == 1 else 'ies'} from "
              f"{baseline_path} ({len(survivors)} kept)",
              file=sys.stderr)
        stale = []  # handled: the rewrite IS the prune

    report = _report_json(findings, suppressed, stale, parse_errors,
                          timings)
    if args.json_out is not None:
        args.json_out.parent.mkdir(parents=True, exist_ok=True)
        args.json_out.write_text(json.dumps(report, indent=2) + "\n",
                                 encoding="utf-8")

    if args.format == "json":
        print(json.dumps(report, indent=2))
    else:
        for f in findings:
            print(f.format())
    for e in stale:
        print(f"stale baseline entry (fixed or drifted — prune it): "
              f"{e['path']}: [{e['rule']}] {e['snippet']}",
              file=sys.stderr)
    if args.timings and timings is not None:
        total_ms = sum(timings.values()) * 1e3
        print(f"rule timings (total {total_ms:.1f} ms):",
              file=sys.stderr)
        for name, sec in sorted(timings.items(),
                                key=lambda kv: -kv[1]):
            print(f"  {sec * 1e3:8.1f} ms  {name}", file=sys.stderr)

    n_err = sum(1 for f in findings if f.severity == "error")
    n_warn = len(findings) - n_err
    if findings:
        if args.format != "json":
            print(f"pio-lint: {n_err} error(s), {n_warn} warning(s)")
        # parse errors outrank findings: part of the tree went unlinted
        return 2 if parse_errors else 1
    if args.format != "json":
        print("pio-lint: clean"
              + (f" ({len(stale)} stale baseline entries)" if stale
                 else ""))
    return 2 if parse_errors else 0


if __name__ == "__main__":
    sys.exit(main())
